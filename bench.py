"""Headline benchmarks: GPT-2 124M tokens/sec/chip + ResNet-50 images/sec/chip.

Runs the FULL training steps (forward + backward + optimizer) on whatever
platform jax selects — the real TPU chip under the driver. Prints exactly
ONE JSON line; the headline metric stays GPT-2 tokens/s/chip (tracked by
``vs_baseline``), with ResNet-50 images/s and MFU estimates carried as extra
keys of the same object (BASELINE.md rows 1 and 3):

    {"metric": "gpt2_124m_tokens_per_sec_per_chip", "value": N,
     "unit": "tokens/s/chip", "vs_baseline": R, "platform": "tpu",
     "mfu": F,
     "extras": {"resnet50_images_per_sec_per_chip": M, "resnet50_mfu": F2}}

``vs_baseline`` compares against BASELINE.json's published number when one
exists; the reference published none (BASELINE.md: "no published numbers
were recoverable"), so the fallback baseline is this repo's own recorded
first measurement (bench_baseline.json), making the ratio a regression
tracker. With no record at all it reports 1.0 and writes the record.
Baselines are PER PLATFORM FAMILY: backend-init failure (TPU tunnel
down) self-heals onto CPU instead of crashing the round, the record is
labeled ``"platform"``, and a CPU run only seeds/compares the CPU
anchor — it can never regress (or overwrite) the TPU baseline.

MFU = measured model FLOP/s divided by peak chip FLOP/s. Model FLOPs come
from XLA's own cost analysis of the compiled step (fallback: the standard
6*N_params + attention analytic estimate). Peak defaults to 197 TFLOP/s
(v5e bf16); override with NEZHA_PEAK_TFLOPS for other chips.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _aot_compile(step, *args):
    """AOT-compile the step; return (callable, flops-per-XLA-cost-analysis).

    The compiled executable is reused for timing (the jit dispatch cache is
    separate from lower().compile(), so handing back `step` would compile
    the identical program twice). Falls back to the jitted step with
    flops=None when AOT/cost analysis is unavailable.
    """
    try:
        compiled = step.lower(*args).compile()
    except Exception:
        return step, None
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0]
        flops = float(cost["flops"])
        if flops <= 0:
            flops = None
    except Exception:
        pass
    return compiled, flops


def _peak_flops(platform: str):
    """Peak chip FLOP/s for MFU; None off-accelerator (MFU meaningless)."""
    if platform not in ("tpu", "axon"):
        return None
    return float(os.environ.get("NEZHA_PEAK_TFLOPS", "197")) * 1e12


def _init_backend() -> str:
    """Initialize the jax backend SELF-HEALINGLY and return its platform.

    The ambient `axon` TPU plugin raises (or hangs inside its own
    timeout) in backend init when the tunnel is down — historically that
    turned a whole bench round into a crash record (BENCH_r03–r05:
    `RuntimeError: Unable to initialize backend 'axon'` out of
    `jax.devices()`). A bench that cannot reach the accelerator should
    still MEASURE — on CPU, labeled as CPU, compared against the CPU
    baseline only — so backend-init failure falls back to the cpu
    platform instead of propagating. NEZHA_BENCH_CPU still forces cpu
    up front (the historical escape hatch)."""
    import jax

    if os.environ.get("NEZHA_BENCH_CPU"):
        # The axon plugin hangs in backend init when the tunnel is down,
        # and JAX_PLATFORMS alone cannot override the site hook (same
        # pattern as tests/conftest.py and gpt2_tune --tiny).
        jax.config.update("jax_platforms", "cpu")
    try:
        return jax.devices()[0].platform
    except RuntimeError as e:
        print(f"bench: backend init failed ({e!s:.200}); retrying on "
              f"cpu — numbers will be CPU-baselined, not a TPU claim",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform


# ----------------------------------------------- per-platform baselines
def _platform_family(platform: str) -> str:
    """Baseline namespace for a platform ('axon' is the tunneled TPU)."""
    return "tpu" if platform in ("tpu", "axon") else platform


def _load_baseline(path: str):
    """-> (record dict, corrupt flag). A file we failed to parse is
    surfaced as corrupt so a crashed writer can never reset the
    regression anchor to the current run."""
    try:
        with open(path) as f:
            recorded = json.load(f)
    except FileNotFoundError:
        return {}, False
    except (ValueError, OSError):
        return {}, True
    if not isinstance(recorded, dict):
        return {}, True
    return recorded, False


def _family_baseline(recorded: dict, family: str) -> dict:
    """The anchor numbers for one platform family. Legacy flat records
    (pre-namespacing) belong to the platform they name (default tpu);
    `by_platform` entries overlay them — so a CPU fallback run is only
    ever compared against (and only ever records) CPU anchors, and the
    TPU baseline cannot be regressed or overwritten from a machine with
    no TPU."""
    out = {}
    if _platform_family(str(recorded.get("platform", "tpu"))) == family:
        out.update({k: v for k, v in recorded.items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)})
    by = recorded.get("by_platform")
    if isinstance(by, dict) and isinstance(by.get(family), dict):
        out.update(by[family])
    return out


def _record_anchors(recorded: dict, family: str, updates: dict) -> None:
    recorded.setdefault("by_platform", {}).setdefault(
        family, {}).update(updates)


def _time_steps(step, state, batch, steps_target: int, budget_s: float,
                windows: int = 5):
    """Warm up, then time ``windows`` independent windows of
    ``steps_target`` steps each (host-fetch barrier per window) and return
    (median steps/sec, relative spread).

    Median-of-N so the regression tracker can see single-digit-percent
    moves through host jitter (VERDICT r2 weak #1: one window hid a 7%
    RN50 regression inside an assumed ±8% noise band; the r4 GPT-2 run
    saw one-window excursions of 15% through tunnel jitter — windows are
    ~seconds, compile dominates, so five are as cheap as three). On the
    tunneled `axon` platform block_until_ready can return before the
    computation finishes — only a host fetch is a true barrier there.
    """
    for _ in range(2):
        state, m = step(state, batch)
    float(m["loss"])

    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        done = 0
        while done < steps_target and (time.perf_counter() - t0) < budget_s:
            state, m = step(state, batch)
            done += 1
        float(m["loss"])
        rates.append(done / (time.perf_counter() - t0))
    rates.sort()
    median = rates[len(rates) // 2]
    spread = (rates[-1] - rates[0]) / median if median else 0.0
    return median, spread


def bench_gpt2(on_tpu: bool, peak, **cfg_overrides):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import optim
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train.loop import init_train_state, make_train_step

    batch, seq = (8, 1024) if on_tpu else (2, 256)
    steps_target = 20 if on_tpu else 3
    # fused_loss_chunk=-1: bf16 logits with the fp32 upcast fused into the
    # CE's logsumexp — never materializes fp32 [B,S,V] (+3% measured).
    cfg = (GPT2Config(fused_loss_chunk=-1, **cfg_overrides) if on_tpu
           else GPT2Config(num_layers=4, fused_loss_chunk=-1,
                           **cfg_overrides))

    model = GPT2(cfg, policy=bf16_policy())
    opt = optim.adamw(6e-4, weight_decay=0.1)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, lm_loss)

    tokens = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    b = {"tokens": jnp.asarray(tokens)}

    step, _xla_flops = _aot_compile(step, state, b)
    # GPT-2 MFU uses the analytic count, not XLA's: the attention runs in a
    # Pallas kernel whose FLOPs are opaque to compiled.cost_analysis(), so
    # the XLA number undercounts. 6*N per token fwd+bwd + 6*L*d*S causal
    # attention (score+value dots, halved for causality).
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(
        state["variables"]["params"]))
    step_flops = (6 * n_params +
                  6 * cfg.num_layers * cfg.hidden_size * seq) * batch * seq

    steps_per_sec, spread = _time_steps(step, state, b, steps_target, 60.0)
    tokens_per_sec = batch * seq * steps_per_sec
    mfu = (step_flops * steps_per_sec / peak) if (peak and step_flops) else None
    return tokens_per_sec, mfu, spread


def bench_resnet50(on_tpu: bool, peak):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import ops, optim
    from nezha_tpu.models.resnet import resnet50
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train.loop import init_train_state, make_train_step

    batch, size = (128, 224) if on_tpu else (4, 64)
    steps_target = 10 if on_tpu else 2

    # s2d stem: same arithmetic as the 7x7/s2 conv, relaid out for the MXU
    # (test_s2d_stem_matches_conv7 proves equivalence).
    model = resnet50(stem="s2d" if on_tpu else "conv7",
                     policy=bf16_policy())
    opt = optim.momentum(0.1, beta=0.9, weight_decay=1e-4)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    ce = lambda logits, b_: ops.softmax_cross_entropy_with_integer_labels(
        logits, b_["label"]).mean()
    step = make_train_step(model, opt, ce)

    rng = np.random.RandomState(0)
    b = {"image": jnp.asarray(
             rng.rand(batch, size, size, 3).astype(np.float32)),
         "label": jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)}

    step, step_flops = _aot_compile(step, state, b)
    if step_flops is None and peak:
        # RN50 fwd ~= 8.2 GFLOP per 224px image (4.1 GMACs); train ~= 3x.
        step_flops = 3 * 8.2e9 * (size / 224.0) ** 2 * batch
    steps_per_sec, spread = _time_steps(step, state, b, steps_target, 90.0)
    images_per_sec = batch * steps_per_sec
    mfu = (step_flops * steps_per_sec / peak) if (peak and step_flops) else None
    return images_per_sec, mfu, spread


def bench_bert(on_tpu: bool, peak):
    """Config 4's model on one chip (dense adamw step; the ZeRO-1 sharding
    itself is exercised by tests/dryrun — per-chip throughput is the perf
    number of record)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import optim
    from nezha_tpu.models.bert import Bert, BertConfig, mlm_loss
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train.loop import init_train_state, make_train_step

    batch, seq = (16, 512) if on_tpu else (2, 64)
    steps_target = 10 if on_tpu else 2
    # fused_loss_chunk=-1: never materializes the fp32 [16,512,30522]
    # logits (~1 GB/step) — same fused-logsumexp head as GPT-2.
    cfg = (BertConfig(fused_loss_chunk=-1) if on_tpu
           else BertConfig(num_layers=2))

    model = Bert(cfg, policy=bf16_policy())
    opt = optim.adamw(1e-4, weight_decay=0.01)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, mlm_loss)

    r = np.random.RandomState(0)
    tokens = r.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.full_like(tokens, -100)
    mask = r.rand(batch, seq) < 0.15
    labels[mask] = tokens[mask]
    # No padding_mask: full-length batches; its all-True mask would force
    # composed-XLA attention off the flash path (BertConfig.attn_impl).
    b = {"tokens": jnp.asarray(tokens), "labels": jnp.asarray(labels),
         "segment_ids": jnp.zeros_like(jnp.asarray(tokens))}

    step, _ = _aot_compile(step, state, b)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(
        state["variables"]["params"]))
    step_flops = (6 * n_params +
                  6 * cfg.num_layers * cfg.hidden_size * seq) * batch * seq
    steps_per_sec, spread = _time_steps(step, state, b, steps_target, 60.0)
    tokens_per_sec = batch * seq * steps_per_sec
    mfu = (step_flops * steps_per_sec / peak) if (peak and step_flops) else None
    return tokens_per_sec, mfu, spread


def bench_wrn101(on_tpu: bool, peak):
    """Config 5: Wide-ResNet-101-2, large-batch mixed bf16/fp32."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import ops, optim
    from nezha_tpu.models.resnet import ResNet, wide_resnet101
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train.loop import init_train_state, make_train_step

    batch, size = (64, 224) if on_tpu else (2, 64)
    steps_target = 5 if on_tpu else 2

    model = (wide_resnet101(stem="s2d", policy=bf16_policy()) if on_tpu
             else ResNet((1, 1, 1, 1), width_factor=2, policy=bf16_policy()))
    opt = optim.momentum(0.1, beta=0.9, weight_decay=1e-4)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    ce = lambda logits, b_: ops.softmax_cross_entropy_with_integer_labels(
        logits, b_["label"]).mean()
    step = make_train_step(model, opt, ce)

    rng = np.random.RandomState(0)
    b = {"image": jnp.asarray(
             rng.rand(batch, size, size, 3).astype(np.float32)),
         "label": jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)}

    step, step_flops = _aot_compile(step, state, b)
    if step_flops is None and peak:
        # WRN-101-2 fwd ~= 45.6 GFLOP per 224px image; train ~= 3x.
        step_flops = 3 * 45.6e9 * (size / 224.0) ** 2 * batch
    steps_per_sec, spread = _time_steps(step, state, b, steps_target, 90.0)
    images_per_sec = batch * steps_per_sec
    mfu = (step_flops * steps_per_sec / peak) if (peak and step_flops) else None
    return images_per_sec, mfu, spread


def bench_mlp(on_tpu: bool):
    """Config 1 through the REAL CLI entry (the reference's CPU-path
    benchmark config): examples/sec from the trainer's own metrics.

    Two logging windows; the returned metrics are the LAST one, whose t0
    resets after the first window — so the reported rate excludes the
    first-step compile (the Trainer's window timer starts before step 1)."""
    from nezha_tpu.cli.train import build_parser, run

    steps = 300 if on_tpu else 20
    metrics = run(build_parser().parse_args(
        ["--config", "mlp_mnist", "--steps", str(steps),
         "--batch-size", "256", "--log-every", str(steps // 2)]))
    return metrics.get("examples_per_sec", 0.0)


def main() -> int:
    import jax

    platform = _init_backend()
    on_tpu = platform in ("tpu", "axon")
    peak = _peak_flops(platform)

    # Persistent compile cache (same-machine): repeat bench sessions reuse
    # executables instead of paying the 20-40 s first-compile per config.
    from nezha_tpu.utils import enable_persistent_compile_cache
    enable_persistent_compile_cache()

    # Dispatch round-trip: one trivial op + host fetch per call. Under
    # the axon tunnel every dispatch crosses a network hop, and the CLI
    # MLP number (~9 steps/s in r4) is hypothesized to be exactly this
    # latency (BENCH_NOTES r5); recording it beside the configs makes the
    # attribution mechanical.
    import jax.numpy as jnp
    _x = jnp.zeros((), jnp.float32)
    _add = jax.jit(lambda v: v + 1.0)
    _add(_x).block_until_ready()
    _t0 = time.perf_counter()
    for _ in range(20):
        _x = _add(_x)
        _x.block_until_ready()
    ping_ms = (time.perf_counter() - _t0) / 20 * 1e3

    tokens_per_sec, gpt2_mfu, gpt2_spread = bench_gpt2(on_tpu, peak)
    images_per_sec, rn50_mfu, rn50_spread = bench_resnet50(on_tpu, peak)
    bert_tps, bert_mfu, _ = bench_bert(on_tpu, peak)
    wrn_ips, wrn_mfu, _ = bench_wrn101(on_tpu, peak)
    mlp_eps = bench_mlp(on_tpu)

    # r5 trunk-lever A/B points, captured even when the ONLY tunnel
    # window of the round is this driver-run bench (the watchdog queue
    # measures them properly when it gets a window; these are fallback
    # evidence). They run LAST — after every headline config — and each
    # is bounded by an alarm, so a hung variant on a dying tunnel cannot
    # cost the numbers of record.
    gpt2_scan_tps = gpt2_ln_tps = None
    if on_tpu:
        import signal

        def _bounded(fn, seconds=240):
            def _alarm(signum, frame):
                raise TimeoutError("variant timed out")
            old = signal.signal(signal.SIGALRM, _alarm)
            signal.alarm(seconds)
            try:
                return fn()
            except Exception as e:
                print(f"variant failed: {e}", file=sys.stderr)
                return None
            finally:
                signal.alarm(0)
                signal.signal(signal.SIGALRM, old)

        r = _bounded(lambda: bench_gpt2(on_tpu, peak, scan_layers=True))
        gpt2_scan_tps = r[0] if r else None
        r = _bounded(lambda: bench_gpt2(on_tpu, peak, ln_impl="pallas"))
        gpt2_ln_tps = r[0] if r else None

    baseline_path = os.environ.get("NEZHA_BENCH_BASELINE") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")
    family = _platform_family(platform)
    recorded, corrupt = _load_baseline(baseline_path)
    anchors = _family_baseline(recorded, family)
    vs_baseline = 1.0
    base = anchors.get("gpt2_124m_tokens_per_sec_per_chip")
    if isinstance(base, (int, float)) and base > 0:
        vs_baseline = tokens_per_sec / base
    else:
        base = None
    if not corrupt:
        # Record this platform family's first measurements (regression
        # anchors); never overwrite an existing anchor, never touch
        # another family's — a CPU fallback run can only ever seed or
        # compare against the CPU slot.
        updates = {}
        if not base:
            updates["gpt2_124m_tokens_per_sec_per_chip"] = tokens_per_sec
        if not anchors.get("resnet50_images_per_sec_per_chip"):
            updates["resnet50_images_per_sec_per_chip"] = images_per_sec
        if updates:
            _record_anchors(recorded, family, updates)
            try:
                with open(baseline_path, "w") as f:
                    json.dump(recorded, f)
            except OSError:
                pass

    rn50_base = anchors.get("resnet50_images_per_sec_per_chip")
    extras = {
        "resnet50_images_per_sec_per_chip": round(images_per_sec, 2),
        "gpt2_spread": round(gpt2_spread, 4),
        "resnet50_spread": round(rn50_spread, 4),
        "bert_base_tokens_per_sec_per_chip": round(bert_tps, 2),
        "wrn101_images_per_sec_per_chip": round(wrn_ips, 2),
        "mlp_examples_per_sec": round(mlp_eps, 2),
        "ping_ms": round(ping_ms, 3),
    }
    if isinstance(rn50_base, (int, float)) and rn50_base > 0:
        extras["resnet50_vs_baseline"] = round(images_per_sec / rn50_base, 4)
    if rn50_mfu is not None:
        extras["resnet50_mfu"] = round(rn50_mfu, 4)
    if bert_mfu is not None:
        extras["bert_base_mfu"] = round(bert_mfu, 4)
    if wrn_mfu is not None:
        extras["wrn101_mfu"] = round(wrn_mfu, 4)
    if gpt2_scan_tps is not None:
        extras["gpt2_scan_tokens_per_sec"] = round(gpt2_scan_tps, 2)
    if gpt2_ln_tps is not None:
        extras["gpt2_ln_pallas_tokens_per_sec"] = round(gpt2_ln_tps, 2)

    out = {
        "metric": "gpt2_124m_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
        # The platform label makes a CPU-fallback record legible as one:
        # its vs_baseline tracks the CPU anchor, never the TPU number.
        "platform": platform,
        "extras": extras,
    }
    if gpt2_mfu is not None:
        out["mfu"] = round(gpt2_mfu, 4)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
