"""Headline benchmark: GPT-2 124M training throughput, tokens/sec/chip.

Runs the FULL training step (forward + backward + AdamW, bf16 compute /
fp32 master) on whatever platform jax selects — the real TPU chip under the
driver. Prints exactly ONE JSON line:

    {"metric": "gpt2_124m_tokens_per_sec_per_chip", "value": N,
     "unit": "tokens/s/chip", "vs_baseline": R}

``vs_baseline`` compares against BASELINE.json's published number when one
exists; the reference published none (BASELINE.md: "no published numbers
were recoverable"), so the fallback baseline is this repo's own recorded
first measurement (bench_baseline.json), making the ratio a regression
tracker. With no record at all it reports 1.0 and writes the record.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import optim
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train.loop import init_train_state, make_train_step

    platform = jax.devices()[0].platform
    on_tpu = platform in ("tpu", "axon")

    batch, seq = (8, 1024) if on_tpu else (2, 256)
    steps_target = 20 if on_tpu else 3
    cfg = GPT2Config() if on_tpu else GPT2Config(num_layers=4)

    model = GPT2(cfg, policy=bf16_policy())
    opt = optim.adamw(6e-4, weight_decay=0.1)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, lm_loss)

    tokens = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    b = {"tokens": jnp.asarray(tokens)}

    # Warmup (compile + first dispatch). Synchronize by fetching the loss to
    # host (device_get): on the tunneled `axon` platform block_until_ready
    # returns before the computation finishes, which once inflated this
    # number ~30x — only a host fetch is a true barrier there.
    for _ in range(2):
        state, m = step(state, b)
    float(m["loss"])

    t0 = time.perf_counter()
    done = 0
    while done < steps_target and (time.perf_counter() - t0) < 60.0:
        state, m = step(state, b)
        done += 1
    float(m["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * done / dt

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "bench_baseline.json")
    vs_baseline = 1.0
    try:
        with open(baseline_path) as f:
            recorded = json.load(f)
        base = recorded.get("gpt2_124m_tokens_per_sec_per_chip")
        if base:
            vs_baseline = tokens_per_sec / base
    except FileNotFoundError:
        if on_tpu:  # record the first real-chip measurement
            try:
                with open(baseline_path, "w") as f:
                    json.dump({"gpt2_124m_tokens_per_sec_per_chip":
                               tokens_per_sec, "platform": platform}, f)
            except OSError:
                pass
    except (ValueError, TypeError, AttributeError, OSError):
        pass  # corrupt/partial record: report vs_baseline=1.0, don't crash

    print(json.dumps({
        "metric": "gpt2_124m_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
