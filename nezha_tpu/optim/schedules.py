"""Learning-rate schedules (pure functions of the int step)."""

from __future__ import annotations

import jax.numpy as jnp


def constant_schedule(value: float):
    return lambda step: jnp.asarray(value, jnp.float32)


def cosine_decay_schedule(init_value: float, decay_steps: int, alpha: float = 0.0):
    def sched(step):
        t = jnp.clip(step.astype(jnp.float32) / decay_steps, 0.0, 1.0)
        cosine = 0.5 * (1.0 + jnp.cos(jnp.pi * t))
        return init_value * ((1 - alpha) * cosine + alpha)
    return sched


def linear_warmup_schedule(peak: float, warmup_steps: int):
    def sched(step):
        s = step.astype(jnp.float32)
        return peak * jnp.minimum(1.0, (s + 1.0) / max(warmup_steps, 1))
    return sched


def warmup_cosine_schedule(peak: float, warmup_steps: int, total_steps: int,
                           end_value: float = 0.0):
    """Linear warmup then cosine decay — GPT-2/BERT standard."""
    def sched(step):
        s = step.astype(jnp.float32)
        warm = peak * jnp.minimum(1.0, (s + 1.0) / max(warmup_steps, 1))
        t = jnp.clip((s - warmup_steps) / max(total_steps - warmup_steps, 1), 0.0, 1.0)
        cos = end_value + (peak - end_value) * 0.5 * (1.0 + jnp.cos(jnp.pi * t))
        return jnp.where(s < warmup_steps, warm, cos)
    return sched
