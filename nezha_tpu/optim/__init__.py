"""Optimizers and LR schedules.

The reference updates weights inside its op graph with optimizer kernels
(SURVEY.md §2: custom CUDA optimizer kernels). Here optimizers are pure
pytree transforms — (grads, state, params) -> (updates, state) — which jit
into the training step so XLA fuses the whole update. The ZeRO-1 sharded
variant lives in `nezha_tpu.parallel.zero1` and wraps any optimizer here.
"""

from nezha_tpu.optim.optimizers import (
    Optimizer,
    sgd,
    momentum,
    adam,
    adamw,
    apply_updates,
    global_norm,
    clip_by_global_norm,
    lars,
    lamb,
    matrix_decay_mask,
    adafactor,
    with_grad_clipping,
    accumulate_gradients,
)
from nezha_tpu.optim.schedules import (
    constant_schedule,
    cosine_decay_schedule,
    warmup_cosine_schedule,
    linear_warmup_schedule,
)

__all__ = [
    "Optimizer", "sgd", "momentum", "adam", "adamw", "apply_updates",
    "global_norm", "clip_by_global_norm",
    "lars", "lamb", "matrix_decay_mask", "adafactor", "with_grad_clipping", "accumulate_gradients",
    "constant_schedule", "cosine_decay_schedule", "warmup_cosine_schedule",
    "linear_warmup_schedule",
]
