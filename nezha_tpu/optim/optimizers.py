"""Pure-pytree optimizers: SGD, momentum, Adam, AdamW.

Every optimizer is ``init(params) -> state`` plus
``update(grads, state, params) -> (updates, new_state)``; ``updates`` are
deltas applied by ``apply_updates``. States are pytrees, so ZeRO-1 sharding
(`nezha_tpu.parallel.zero1`) can shard them over the data-parallel axis
unchanged. Optimizer math runs in fp32 on the master params even when the
forward is bf16 (mixed-precision path — SURVEY.md §2).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def _as_schedule(lr) -> Schedule:
    if callable(lr):
        return lr
    return lambda step: jnp.asarray(lr, jnp.float32)


@dataclasses.dataclass(frozen=True)
class Optimizer:
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Tuple[Any, Any]]


def apply_updates(params, updates):
    return jax.tree_util.tree_map(
        lambda p, u: (p + u.astype(p.dtype)) if u is not None else p,
        params, updates)


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def clip_by_global_norm(tree, max_norm: float):
    norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda x: x * scale, tree), norm


def sgd(lr) -> Optimizer:
    sched = _as_schedule(lr)

    def init(params):
        return {"step": jnp.zeros((), jnp.int32)}

    def update(grads, state, params=None):
        del params
        step = state["step"]
        lr_t = sched(step)
        updates = jax.tree_util.tree_map(
            lambda g: -lr_t * g.astype(jnp.float32), grads)
        return updates, {"step": step + 1}

    return Optimizer(init, update)


def momentum(lr, beta: float = 0.9, nesterov: bool = False,
             weight_decay: float = 0.0) -> Optimizer:
    """SGD+momentum — the classic ResNet-50/ImageNet optimizer.

    ``weight_decay`` here is coupled (L2 added to the gradient), matching the
    standard ResNet recipe.
    """
    sched = _as_schedule(lr)

    def init(params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "velocity": jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, jnp.float32), params),
        }

    def update(grads, state, params):
        step = state["step"]
        lr_t = sched(step)

        def upd(g, v, p):
            g = g.astype(jnp.float32)
            if weight_decay:
                g = g + weight_decay * p.astype(jnp.float32)
            v_new = beta * v + g
            d = (g + beta * v_new) if nesterov else v_new
            return -lr_t * d, v_new

        flat = jax.tree_util.tree_map(upd, grads, state["velocity"], params)
        updates = jax.tree_util.tree_map(lambda t: t[0], flat,
                                         is_leaf=lambda t: isinstance(t, tuple))
        velocity = jax.tree_util.tree_map(lambda t: t[1], flat,
                                          is_leaf=lambda t: isinstance(t, tuple))
        return updates, {"step": step + 1, "velocity": velocity}

    return Optimizer(init, update)


def adam(lr, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8) -> Optimizer:
    return adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=0.0)


def adamw(lr, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
          weight_decay: float = 0.01,
          mask: Optional[Callable[[Any], Any]] = None) -> Optimizer:
    """AdamW (decoupled weight decay) — GPT-2/BERT optimizer.

    ``mask(params)`` may return a matching pytree of bools selecting which
    leaves get weight decay (norm scales/biases usually don't).
    """
    sched = _as_schedule(lr)

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, jnp.float32)
        return {
            "step": jnp.zeros((), jnp.int32),
            "mu": jax.tree_util.tree_map(zeros, params),
            "nu": jax.tree_util.tree_map(zeros, params),
        }

    def update(grads, state, params):
        step = state["step"] + 1
        lr_t = sched(state["step"])
        c1 = 1.0 - b1 ** step.astype(jnp.float32)
        c2 = 1.0 - b2 ** step.astype(jnp.float32)
        wd_mask = mask(params) if mask is not None else jax.tree_util.tree_map(
            lambda p: True, params)

        def upd(g, m, v, p, use_wd):
            g = g.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g
            v_new = b2 * v + (1 - b2) * jnp.square(g)
            m_hat = m_new / c1
            v_hat = v_new / c2
            d = m_hat / (jnp.sqrt(v_hat) + eps)
            if weight_decay:
                d = d + jnp.where(use_wd, weight_decay, 0.0) * p.astype(jnp.float32)
            return -lr_t * d, m_new, v_new

        flat = jax.tree_util.tree_map(upd, grads, state["mu"], state["nu"],
                                      params, wd_mask)
        pick = lambda i: jax.tree_util.tree_map(
            lambda t: t[i], flat, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), {"step": step, "mu": pick(1), "nu": pick(2)}

    return Optimizer(init, update)
