"""Pure-pytree optimizers: SGD, momentum, Adam, AdamW.

Every optimizer is ``init(params) -> state`` plus
``update(grads, state, params) -> (updates, new_state)``; ``updates`` are
deltas applied by ``apply_updates``. States are pytrees, so ZeRO-1 sharding
(`nezha_tpu.parallel.zero1`) can shard them over the data-parallel axis
unchanged. Optimizer math runs in fp32 on the master params even when the
forward is bf16 (mixed-precision path — SURVEY.md §2).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def _as_schedule(lr) -> Schedule:
    if callable(lr):
        return lr
    return lambda step: jnp.asarray(lr, jnp.float32)


@dataclasses.dataclass(frozen=True)
class Optimizer:
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Tuple[Any, Any]]


def apply_updates(params, updates):
    return jax.tree_util.tree_map(
        lambda p, u: (p + u.astype(p.dtype)) if u is not None else p,
        params, updates)


def global_norm(tree, axis_name=None) -> jnp.ndarray:
    """Global L2 norm of a pytree. ``axis_name``: psum the squared sum over
    that mapped axis first — for trees holding only this rank's SHARD of
    each leaf (ZeRO-1's post-reduce-scatter chunks)."""
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
             for x in jax.tree_util.tree_leaves(tree))
    if axis_name is not None:
        from jax import lax
        sq = lax.psum(sq, axis_name)
    return jnp.sqrt(sq)


def clip_by_global_norm(tree, max_norm: float, axis_name=None):
    """Scale ``tree`` so its global L2 norm is at most ``max_norm``.

    ``axis_name``: see :func:`global_norm` — without it, sharded-gradient
    callers would clip against a ~sqrt(world)x-too-small per-rank norm."""
    norm = global_norm(tree, axis_name=axis_name)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda x: x * scale, tree), norm


def sgd(lr) -> Optimizer:
    sched = _as_schedule(lr)

    def init(params):
        return {"step": jnp.zeros((), jnp.int32)}

    def update(grads, state, params=None):
        del params
        step = state["step"]
        lr_t = sched(step)
        updates = jax.tree_util.tree_map(
            lambda g: -lr_t * g.astype(jnp.float32), grads)
        return updates, {"step": step + 1}

    return Optimizer(init, update)


def momentum(lr, beta: float = 0.9, nesterov: bool = False,
             weight_decay: float = 0.0) -> Optimizer:
    """SGD+momentum — the classic ResNet-50/ImageNet optimizer.

    ``weight_decay`` here is coupled (L2 added to the gradient), matching the
    standard ResNet recipe.
    """
    sched = _as_schedule(lr)

    def init(params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "velocity": jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, jnp.float32), params),
        }

    def update(grads, state, params):
        step = state["step"]
        lr_t = sched(step)

        def upd(g, v, p):
            g = g.astype(jnp.float32)
            if weight_decay:
                g = g + weight_decay * p.astype(jnp.float32)
            v_new = beta * v + g
            d = (g + beta * v_new) if nesterov else v_new
            return -lr_t * d, v_new

        flat = jax.tree_util.tree_map(upd, grads, state["velocity"], params)
        updates = jax.tree_util.tree_map(lambda t: t[0], flat,
                                         is_leaf=lambda t: isinstance(t, tuple))
        velocity = jax.tree_util.tree_map(lambda t: t[1], flat,
                                          is_leaf=lambda t: isinstance(t, tuple))
        return updates, {"step": step + 1, "velocity": velocity}

    return Optimizer(init, update)


def adam(lr, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8) -> Optimizer:
    return adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=0.0)


def adamw(lr, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
          weight_decay: float = 0.01,
          mask: Optional[Callable[[Any], Any]] = None) -> Optimizer:
    """AdamW (decoupled weight decay) — GPT-2/BERT optimizer.

    ``mask(params)`` may return a matching pytree of bools selecting which
    leaves get weight decay (norm scales/biases usually don't).
    """
    sched = _as_schedule(lr)

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, jnp.float32)
        return {
            "step": jnp.zeros((), jnp.int32),
            "mu": jax.tree_util.tree_map(zeros, params),
            "nu": jax.tree_util.tree_map(zeros, params),
        }

    def update(grads, state, params):
        step = state["step"] + 1
        lr_t = sched(state["step"])
        c1 = 1.0 - b1 ** step.astype(jnp.float32)
        c2 = 1.0 - b2 ** step.astype(jnp.float32)
        wd_mask = mask(params) if mask is not None else jax.tree_util.tree_map(
            lambda p: True, params)

        def upd(g, m, v, p, use_wd):
            g = g.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g
            v_new = b2 * v + (1 - b2) * jnp.square(g)
            m_hat = m_new / c1
            v_hat = v_new / c2
            d = m_hat / (jnp.sqrt(v_hat) + eps)
            if weight_decay:
                d = d + jnp.where(use_wd, weight_decay, 0.0) * p.astype(jnp.float32)
            return -lr_t * d, m_new, v_new

        flat = jax.tree_util.tree_map(upd, grads, state["mu"], state["nu"],
                                      params, wd_mask)
        pick = lambda i: jax.tree_util.tree_map(
            lambda t: t[i], flat, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), {"step": step, "mu": pick(1), "nu": pick(2)}

    return Optimizer(init, update)


def lars(lr, beta: float = 0.9, weight_decay: float = 0.0,
         trust_coefficient: float = 0.001, eps: float = 1e-9,
         skip_fn: Optional[Callable[[Any], Any]] = None) -> Optimizer:
    """LARS — layerwise-adaptive SGD for very large batch CNN training
    (the standard recipe for BASELINE config 5's large-batch WRN-101).

    Each leaf's step is scaled by trust * |p| / (|g| + wd*|p|), so layers
    with small weights aren't blown away by a global LR sized for the
    large-batch regime. ``skip_fn(params)`` may return a bool pytree
    marking leaves (biases, norm scales) that use plain momentum.
    """
    sched = _as_schedule(lr)

    def init(params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "velocity": jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, jnp.float32), params),
        }

    def update(grads, state, params):
        step = state["step"]
        lr_t = sched(step)
        skip = (skip_fn(params) if skip_fn is not None
                else jax.tree_util.tree_map(lambda p: False, params))

        def upd(g, v, p, plain):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            # Skip-listed leaves (biases, norm scales) are excluded from
            # weight decay as well as trust scaling, per the LARS recipe.
            g = g + jnp.where(plain, 0.0, weight_decay) * p32
            p_norm = jnp.linalg.norm(p32.reshape(-1))
            g_norm = jnp.linalg.norm(g.reshape(-1))
            trust = jnp.where(
                (p_norm > 0) & (g_norm > 0),
                trust_coefficient * p_norm / (g_norm + eps), 1.0)
            scale = jnp.where(plain, 1.0, trust)
            v_new = beta * v + scale * g
            return -lr_t * v_new, v_new

        flat = jax.tree_util.tree_map(upd, grads, state["velocity"], params,
                                      skip)
        pick = lambda i: jax.tree_util.tree_map(
            lambda t: t[i], flat, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), {"step": step + 1, "velocity": pick(1)}

    return Optimizer(init, update)


def lamb(lr, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-6,
         weight_decay: float = 0.01,
         mask: Optional[Callable[[Any], Any]] = None) -> Optimizer:
    """LAMB — layerwise-adaptive AdamW for large-batch transformer
    pretraining (the BERT 64k-batch recipe; pairs with config 4)."""
    sched = _as_schedule(lr)

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, jnp.float32)
        return {
            "step": jnp.zeros((), jnp.int32),
            "mu": jax.tree_util.tree_map(zeros, params),
            "nu": jax.tree_util.tree_map(zeros, params),
        }

    def update(grads, state, params):
        step = state["step"] + 1
        lr_t = sched(state["step"])
        c1 = 1.0 - b1 ** step.astype(jnp.float32)
        c2 = 1.0 - b2 ** step.astype(jnp.float32)
        wd_mask = mask(params) if mask is not None else jax.tree_util.tree_map(
            lambda p: True, params)

        def upd(g, m, v, p, use_wd):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g
            v_new = b2 * v + (1 - b2) * jnp.square(g)
            d = (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
            d = d + jnp.where(use_wd, weight_decay, 0.0) * p32
            p_norm = jnp.linalg.norm(p32.reshape(-1))
            d_norm = jnp.linalg.norm(d.reshape(-1))
            trust = jnp.where((p_norm > 0) & (d_norm > 0),
                              p_norm / d_norm, 1.0)
            return -lr_t * trust * d, m_new, v_new

        flat = jax.tree_util.tree_map(upd, grads, state["mu"], state["nu"],
                                      params, wd_mask)
        pick = lambda i: jax.tree_util.tree_map(
            lambda t: t[i], flat, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), {"step": step, "mu": pick(1), "nu": pick(2)}

    return Optimizer(init, update)


def adafactor(lr, decay: float = 0.8, eps: float = 1e-30,
              clip_threshold: float = 1.0,
              weight_decay: float = 0.0) -> Optimizer:
    """Adafactor (factored second moment, no first moment): optimizer
    memory for a [m, n] matrix is m + n instead of 2*m*n — the standard
    choice when optimizer state must not dominate HBM."""
    sched = _as_schedule(lr)

    def _factored(p):
        return p.ndim >= 2

    def init(params):
        def slot(p):
            if _factored(p):
                return {"vr": jnp.zeros(p.shape[:-1], jnp.float32),
                        "vc": jnp.zeros(p.shape[:-2] + p.shape[-1:],
                                        jnp.float32)}
            return {"v": jnp.zeros_like(p, jnp.float32)}

        return {"step": jnp.zeros((), jnp.int32),
                "slots": jax.tree_util.tree_map(slot, params)}

    def update(grads, state, params):
        step = state["step"] + 1
        lr_t = sched(state["step"])
        # Increasing decay schedule per the paper: 1 - step^-decay.
        beta = 1.0 - step.astype(jnp.float32) ** (-decay)

        def upd(g, slot, p):
            g = g.astype(jnp.float32)
            g2 = jnp.square(g) + eps
            if _factored(p):
                vr = beta * slot["vr"] + (1 - beta) * g2.mean(axis=-1)
                vc = beta * slot["vc"] + (1 - beta) * g2.mean(axis=-2)
                r = vr / jnp.maximum(vr.mean(axis=-1, keepdims=True), eps)
                d = g / (jnp.sqrt(r)[..., None] * jnp.sqrt(vc)[..., None, :])
                new_slot = {"vr": vr, "vc": vc}
            else:
                v = beta * slot["v"] + (1 - beta) * g2
                d = g / jnp.sqrt(v)
                new_slot = {"v": v}
            rms = jnp.sqrt(jnp.mean(jnp.square(d)))
            d = d / jnp.maximum(1.0, rms / clip_threshold)
            if weight_decay:
                d = d + weight_decay * p.astype(jnp.float32)
            return -lr_t * d, new_slot

        # No is_leaf: tree_map flattens to grads' structure and passes the
        # matching slot subtree whole (prefix semantics) — an is_leaf
        # keyed on dict keys would misfire on q/k/v-named param dicts.
        flat = jax.tree_util.tree_map(upd, grads, state["slots"], params)
        pick = lambda i: jax.tree_util.tree_map(
            lambda t: t[i], flat, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), {"step": step, "slots": pick(1)}

    return Optimizer(init, update)


def with_grad_clipping(opt: Optimizer, max_norm: float,
                       axis_name=None) -> Optimizer:
    """Wrap an optimizer with global-norm gradient clipping. Pass
    ``axis_name`` when the optimizer runs on per-rank gradient SHARDS
    (ZeRO-1) so the norm is global, not shard-local."""

    def update(grads, state, params):
        grads, _ = clip_by_global_norm(grads, max_norm, axis_name=axis_name)
        return opt.update(grads, state, params)

    return Optimizer(opt.init, update)


def accumulate_gradients(opt: Optimizer, every: int) -> Optimizer:
    """Gradient accumulation: apply the wrapped optimizer once per `every`
    micro-steps with the mean of the accumulated grads; in between, emit
    zero updates. Effective batch = micro-batch * every, constant memory,
    jit-compatible (lax.cond on the micro-step counter)."""
    if every < 1:
        raise ValueError("every must be >= 1")
    if every == 1:
        return opt

    from jax import lax

    def init(params):
        return {
            "inner": opt.init(params),
            "acc": jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, jnp.float32), params),
            "count": jnp.zeros((), jnp.int32),
        }

    def update(grads, state, params):
        acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), state["acc"], grads)
        count = state["count"] + 1

        def flush(_):
            mean = jax.tree_util.tree_map(lambda a: a / every, acc)
            updates, inner = opt.update(mean, state["inner"], params)
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return updates, {"inner": inner, "acc": zeroed,
                             "count": jnp.zeros((), jnp.int32)}

        def hold(_):
            updates = jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, jnp.float32), params)
            return updates, {"inner": state["inner"], "acc": acc,
                             "count": count}

        return lax.cond(count >= every, flush, hold, None)

    return Optimizer(init, update)


def matrix_decay_mask(params):
    """The standard GPT-2/BERT weight-decay exclusion: decay only leaves
    with ndim >= 2 (kernels, embeddings); norm scales, biases, and other
    1-D/scalar leaves get none. Pass as ``adamw(..., mask=...)`` /
    ``lamb(..., mask=...)`` (CLI: ``--wd-exclude-1d``).

    Scan-over-layers trunks (``h_scan`` / ``layers_scan`` subtrees) carry
    a leading [num_layers] dim on every leaf, so the threshold there is
    ndim >= 3 — a stacked LN scale [L, H] still gets no decay."""
    def leaf_mask(path, p):
        keys = {getattr(k, "key", None) for k in path}
        stacked = "h_scan" in keys or "layers_scan" in keys
        return jnp.ndim(p) >= (3 if stacked else 2)

    return jax.tree_util.tree_map_with_path(leaf_mask, params)
