"""Device-mesh construction.

The mesh is the TPU-native replacement for the reference's NCCL communicator
setup (SURVEY.md §1 "Collectives": communicator setup via rendezvous): axes
are named (dp/fsdp/tp/sp), shardings are `PartitionSpec`s over those names,
and XLA lays collectives onto ICI rings for each axis.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with named axes, e.g. ``make_mesh({"dp": 4, "tp": 2})``.

    An axis size of -1 means "all remaining devices". Axis order in ``axes``
    is the device-grid order (outermost first); keep fast-collective axes
    (tp/sp) innermost so their groups map to adjacent ICI neighbours.
    """
    devs = list(devices if devices is not None else jax.devices())
    sizes = dict(axes)
    unknown = [k for k, v in sizes.items() if v == -1]
    known = math.prod(v for v in sizes.values() if v != -1)
    if len(unknown) > 1:
        raise ValueError("at most one axis may be -1")
    if unknown:
        if len(devs) % known:
            raise ValueError(f"{len(devs)} devices not divisible by {known}")
        sizes[unknown[0]] = len(devs) // known
    total = math.prod(sizes.values())
    if total > len(devs):
        raise ValueError(f"mesh needs {total} devices, have {len(devs)}")
    grid = np.array(devs[:total]).reshape(tuple(sizes.values()))
    return Mesh(grid, tuple(sizes.keys()))


def make_cpu_mesh(axes: Dict[str, int]) -> Mesh:
    """Mesh over host-platform (CPU) devices — the multi-device test rig
    (requires ``--xla_force_host_platform_device_count=N``)."""
    cpus = [d for d in jax.devices() if d.platform == "cpu"]
    if not cpus:
        cpus = jax.devices("cpu")
    return make_mesh(axes, devices=cpus)


def local_mesh_axes(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
