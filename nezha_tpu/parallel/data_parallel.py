"""Data parallelism.

TPU-native version of the reference's DP all-reduce path (SURVEY.md §3 call
stack 2: backward -> pkg/nccl all-reduce on grads -> optimizer update):
the whole step runs in one ``shard_map`` over the ``dp`` mesh axis, the
gradient all-reduce is a ``lax.pmean`` XLA schedules onto ICI and overlaps
with backward compute, and the optimizer update happens replicated.
BatchNorm running stats are pmean-synced each step (cheap: stats are tiny).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nezha_tpu.nn.module import Module
from nezha_tpu.optim.optimizers import Optimizer, apply_updates
from nezha_tpu.parallel._compat import shard_map
from nezha_tpu.train.loop import TrainState, merge_state


def shard_batch(mesh: Mesh, batch: Any, axis: str = "dp") -> Any:
    """Place a host batch with its leading dim sharded over ``axis`` —
    arrays land already distributed, so no resharding inside the step.

    Multi-process note: ``device_put`` treats ``batch`` as the GLOBAL batch
    and every process must pass the same logical value (each keeps its
    addressable row-slice). For per-host-distinct data use
    :func:`shard_batch_process_local` instead.
    """
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), batch)


def shard_batch_process_local(mesh: Mesh, local_batch: Any,
                              axis: str = "dp") -> Any:
    """Assemble a global batch from per-process LOCAL rows: each host
    contributes ``local_batch`` (global_rows / process_count of them) as its
    own shard — the multi-host data path (each host's loader reads a
    disjoint shard; nothing is transferred between hosts)."""
    import numpy as np

    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)),
        local_batch)


def replicate(mesh: Mesh, tree: Any) -> Any:
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def make_dp_train_step(model: Module, optimizer: Optimizer,
                       loss_fn: Callable[[Any, dict], Any],
                       mesh: Mesh, axis: str = "dp", donate: bool = True,
                       grad_reduce: str = "fp32"):
    """Build ``step(state, batch) -> (state, metrics)`` with the batch
    sharded over ``axis`` and params/optimizer state replicated.

    ``grad_reduce="int8"`` swaps the gradient pmean for the EQuARX-style
    block-scaled int8 wire collective (parallel/quantized.py) — ~4x less
    ICI traffic per step at gradient-compression accuracy; loss/BN-stat
    reductions stay exact either way.
    """
    if grad_reduce not in ("fp32", "int8"):
        raise ValueError(f"grad_reduce must be fp32|int8, got {grad_reduce!r}")

    def per_replica(state: TrainState, batch: dict):
        variables, opt_state = state["variables"], state["opt_state"]
        rng, next_rng = jax.random.split(state["rng"])
        # Per-replica dropout keys; params stay replicated.
        step_rng = jax.random.fold_in(rng, lax.axis_index(axis))

        def compute_loss(params):
            out, new_state = model.apply(
                {"params": params, "state": variables["state"]},
                batch, training=True, rng=step_rng)
            return jnp.asarray(loss_fn(out, batch), jnp.float32), new_state

        (loss, new_state), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(variables["params"])

        # The DP collective: mean over the dp axis (reference: NCCL ring
        # all-reduce). XLA overlaps this with the tail of backward.
        from nezha_tpu.parallel.collectives import record_traced_collective
        if grad_reduce == "int8":
            from nezha_tpu import obs
            from nezha_tpu.parallel.quantized import (
                DEFAULT_MIN_NUMEL, quantized_all_reduce_mean,
                split_quantized_leaves, wire_payload_bytes)
            if obs.enabled():
                # Payload at actual wire width: quantized leaves count
                # int8+scale bytes, sub-cutoff leaves the exact pmean width.
                quant, exact = split_quantized_leaves(grads, DEFAULT_MIN_NUMEL)
                if quant:
                    obs.record_collective(
                        "all_reduce_int8",
                        sum(wire_payload_bytes(g.size) for g in quant))
                if exact:
                    obs.record_collective(
                        "all_reduce",
                        sum(g.size * g.dtype.itemsize for g in exact))
            grads = quantized_all_reduce_mean(grads, axis)
        else:
            record_traced_collective("all_reduce", grads)
            grads = jax.tree_util.tree_map(lambda g: lax.pmean(g, axis), grads)
        loss = lax.pmean(loss, axis)
        new_state = jax.tree_util.tree_map(lambda s: lax.pmean(s, axis), new_state)

        updates, opt_state = optimizer.update(grads, opt_state, variables["params"])
        params = apply_updates(variables["params"], updates)
        new_variables = {"params": params,
                         "state": merge_state(variables["state"], new_state)}
        new_train_state = {"variables": new_variables, "opt_state": opt_state,
                           "rng": next_rng}
        return new_train_state, {"loss": loss}

    def specs_like(tree, spec):
        return jax.tree_util.tree_map(lambda _: spec, tree)

    def build(state_template, batch_template):
        state_spec = specs_like(state_template, P())
        batch_spec = specs_like(batch_template, P(axis))
        mapped = shard_map(per_replica, mesh=mesh,
                           in_specs=(state_spec, batch_spec),
                           out_specs=(state_spec, P()))
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())

    _cache = {}

    def step(state: TrainState, batch: dict):
        key = tuple((k, tuple(v.shape), str(v.dtype)) for k, v in sorted(
            batch.items(), key=lambda kv: kv[0]))
        if key not in _cache:
            _cache[key] = build(state, batch)
        return _cache[key](state, batch)

    return step


def sync_batch_stats(stacked_state: Any) -> Any:
    """Average per-replica BatchNorm running stats.

    For custom train steps that keep per-replica stats as pmap-style stacked
    arrays (leading axis = replica): mean over that axis before eval. The
    built-in DP/ZeRO-1 steps pmean running stats every step, so they never
    need this.
    """
    return jax.tree_util.tree_map(
        lambda s: jnp.mean(jnp.asarray(s, jnp.float32), axis=0), stacked_state)
