"""Sequence parallelism: Ulysses all-to-all attention and the dp x sp
training step.

Complements ring attention (`nezha_tpu.parallel.ring_attention`): instead of
rotating K/V blocks, a single ``lax.all_to_all`` re-shards activations from
sequence-sharded to head-sharded, each rank runs FULL-sequence attention for
its subset of heads (dense MXU work, no per-hop latency), and a second
all-to-all restores sequence sharding. Preferred when num_heads %% world == 0
and the full sequence fits per-chip for 1/world of the heads.

``make_sp_train_step`` is the training path: the WHOLE model (not just
attention) runs inside shard_map over a (dp, sp) mesh with activations
sequence-sharded, attention crossing shards via ring/Ulysses collectives,
and gradients pmean'd over both axes. Per-chip activation memory is
O(S/sp) — the long-context scaling axis.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nezha_tpu.ops.attention import causal_mask, dot_product_attention
from nezha_tpu.parallel._compat import axis_size


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      use_flash: Optional[bool] = None):
    """q,k,v local: [B, H, S_local, D] sequence-sharded. Must run inside
    shard_map. Requires H % world == 0.

    ``use_flash=None`` auto-selects: the Pallas flash kernel on TPU backends,
    composed XLA attention elsewhere. Pass ``use_flash=True`` on CPU to force
    the flash path (the kernel runs in interpret mode there) — this is how CI
    executes the TPU branch's plumbing without a chip.
    """
    world = axis_size(axis_name)
    b, h, s_local, d = q.shape
    if h % world:
        raise ValueError(f"heads {h} not divisible by sequence world {world}")

    def seq_to_heads(x):
        # [B,H,S_loc,D] -> all_to_all: split heads across ranks, gather seq.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)  # [B,H/w,S,D]
    s_global = qh.shape[2]
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash:
        # Full-sequence attention per rank is exactly the flash kernel's
        # shape (shard_map hands it per-device blocks, so Mosaic is fine
        # here, unlike under the GSPMD auto-partitioner); at the long
        # sequences Ulysses exists for, composed attention's S x S scores
        # would dominate HBM.
        from nezha_tpu.ops.pallas import flash_attention
        out = flash_attention(qh, kh, vh, causal=causal)
    else:
        mask = causal_mask(s_global, s_global) if causal else None
        out = dot_product_attention(qh, kh, vh, mask=mask)
    return heads_to_seq(out)  # back to [B,H,S_loc,D]


# ---------------------------------------------------------------------------
# The sequence-parallel training step (dp x sp)


def shard_lm_batch(mesh: Mesh, batch: Dict[str, Any],
                   dp_axis: str = "dp", sp_axis: str = "sp") -> Dict[str, Any]:
    """{"tokens": [B, S+1]} -> {"inputs", "targets"}: both [B, S], batch
    sharded over ``dp_axis`` and sequence over ``sp_axis``.

    The shift happens host-side because [B, S+1] cannot shard evenly over
    the sequence axis; inputs/targets [B, S] can.
    """
    tokens = np.asarray(batch["tokens"])
    world = dict(zip(mesh.axis_names, mesh.devices.shape)).get(sp_axis, 1)
    s = tokens.shape[1] - 1
    if s % world:
        raise ValueError(f"sequence length {s} not divisible by "
                         f"{sp_axis}={world}")
    sharding = NamedSharding(mesh, P(dp_axis, sp_axis))
    return {"inputs": jax.device_put(tokens[:, :-1], sharding),
            "targets": jax.device_put(np.ascontiguousarray(tokens[:, 1:]),
                                      sharding)}


def make_sp_train_step(model, optimizer, mesh: Mesh,
                       loss_fn: Optional[Callable] = None,
                       dp_axis: str = "dp", sp_axis: str = "sp",
                       donate: bool = True):
    """Sequence-parallel train step: the full model runs inside shard_map
    over (dp, sp); attention must be built with ``attn_impl='ring'`` or
    ``'ulysses'`` (its collectives bind to ``sp_axis``). Params/optimizer
    state replicate; batches come from ``shard_lm_batch``; every shard holds
    the same number of tokens, so the global mean loss is the pmean of
    shard means and gradients pmean over both axes.
    """
    from nezha_tpu.ops.losses import lm_objective
    from nezha_tpu.optim.optimizers import apply_updates
    from nezha_tpu.parallel._compat import shard_map
    from nezha_tpu.train.loop import merge_state

    if loss_fn is None:
        # Handles dense logits AND the fused/MoE dict outputs.
        loss_fn = lm_objective
    axes = (dp_axis, sp_axis)

    def per_shard(state, batch):
        variables, opt_state = state["variables"], state["opt_state"]
        rng, next_rng = jax.random.split(state["rng"])
        shard_id = (lax.axis_index(dp_axis) * axis_size(sp_axis)
                    + lax.axis_index(sp_axis))
        step_rng = jax.random.fold_in(rng, shard_id)
        inputs, targets = batch["inputs"], batch["targets"]
        # Global position of this shard's first token — the model offsets
        # its position embeddings by it; ring/Ulysses attention handle the
        # causal mask in global coordinates themselves.
        offset = lax.axis_index(sp_axis) * inputs.shape[1]

        def compute_loss(params):
            out, new_state = model.apply(
                {"params": params, "state": variables["state"]},
                inputs, training=True, rng=step_rng, pos=offset)
            return jnp.asarray(loss_fn(out, targets), jnp.float32), new_state

        (loss, new_state), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(variables["params"])
        grads = jax.tree_util.tree_map(lambda g: lax.pmean(g, axes), grads)
        loss = lax.pmean(loss, axes)
        new_state = jax.tree_util.tree_map(
            lambda t: lax.pmean(t, axes), new_state)
        updates, opt_state = optimizer.update(grads, opt_state,
                                              variables["params"])
        params = apply_updates(variables["params"], updates)
        new_variables = {"params": params,
                         "state": merge_state(variables["state"], new_state)}
        return ({"variables": new_variables, "opt_state": opt_state,
                 "rng": next_rng}, {"loss": loss})

    def build(state_template, batch_template):
        tmap = jax.tree_util.tree_map
        state_spec = tmap(lambda _: P(), state_template)
        batch_spec = tmap(lambda _: P(dp_axis, sp_axis), batch_template)
        mapped = shard_map(per_shard, mesh=mesh,
                           in_specs=(state_spec, batch_spec),
                           out_specs=(state_spec, P()))
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())

    _cache = {}

    def step(state, batch):
        key = tuple((k, tuple(v.shape), str(v.dtype)) for k, v in sorted(
            batch.items(), key=lambda kv: kv[0]))
        if key not in _cache:
            _cache[key] = build(state, batch)
        return _cache[key](state, batch)

    return step
