"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

Complements ring attention (`nezha_tpu.parallel.ring_attention`): instead of
rotating K/V blocks, a single ``lax.all_to_all`` re-shards activations from
sequence-sharded to head-sharded, each rank runs FULL-sequence attention for
its subset of heads (dense MXU work, no per-hop latency), and a second
all-to-all restores sequence sharding. Preferred when num_heads %% world == 0
and the full sequence fits per-chip for 1/world of the heads.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax

from nezha_tpu.ops.attention import causal_mask, dot_product_attention


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True):
    """q,k,v local: [B, H, S_local, D] sequence-sharded. Must run inside
    shard_map. Requires H % world == 0."""
    world = lax.axis_size(axis_name)
    b, h, s_local, d = q.shape
    if h % world:
        raise ValueError(f"heads {h} not divisible by sequence world {world}")

    def seq_to_heads(x):
        # [B,H,S_loc,D] -> all_to_all: split heads across ranks, gather seq.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)  # [B,H/w,S,D]
    s_global = qh.shape[2]
    if jax.default_backend() == "tpu":
        # Full-sequence attention per rank is exactly the flash kernel's
        # shape (shard_map hands it per-device blocks, so Mosaic is fine
        # here, unlike under the GSPMD auto-partitioner); at the long
        # sequences Ulysses exists for, composed attention's S x S scores
        # would dominate HBM.
        from nezha_tpu.ops.pallas import flash_attention
        out = flash_attention(qh, kh, vh, causal=causal)
    else:
        mask = causal_mask(s_global, s_global) if causal else None
        out = dot_product_attention(qh, kh, vh, mask=mask)
    return heads_to_seq(out)  # back to [B,H,S_loc,D]
