"""GSPMD sharded training: annotate shardings, let XLA insert collectives.

Beyond the reference's DP-only scope (SURVEY.md §0: only DP + ZeRO-1
attested), this module is the idiomatic TPU scaling path: parameters carry
Megatron-style `PartitionSpec`s over a ``tp`` mesh axis, the batch shards
over ``dp``, the whole step is `jax.jit` with explicit in/out shardings, and
XLA's SPMD partitioner inserts the all-reduces/all-gathers onto ICI — no
hand-written collectives.

Rule tables map parameter *paths* (regexes over ``a/b/c`` flattened names)
to specs; unmatched leaves replicate.
"""

from __future__ import annotations

import contextlib
import contextvars
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nezha_tpu.nn.module import Module
from nezha_tpu.optim.optimizers import Optimizer, apply_updates
from nezha_tpu.train.loop import TrainState, merge_state

Rules = List[Tuple[str, P]]

# True while tracing inside make_gspmd_train_step's jit-with-shardings:
# XLA's SPMD auto-partitioner cannot partition Mosaic (Pallas) custom
# calls, so models consult this to avoid auto-choosing custom kernels.
_AUTO_PARTITIONED: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "nezha_gspmd_auto_partitioned", default=False)
_AUTO_MESH: contextvars.ContextVar = contextvars.ContextVar(
    "nezha_gspmd_auto_mesh", default=None)


def under_auto_partitioner() -> bool:
    return _AUTO_PARTITIONED.get()


def auto_partitioner_mesh():
    """The Mesh of the enclosing gspmd trace (None outside one). Lets
    model code open a NESTED shard_map region for ops XLA cannot
    auto-partition — e.g. per-device flash attention over tp-sharded
    heads (models.gpt2)."""
    return _AUTO_MESH.get()


def auto_partitioner_scope(mesh=None):
    """Public scope: trace model code as if under the GSPMD auto-
    partitioner, so ``attn_impl='auto'`` avoids Mosaic kernels that XLA
    cannot partition. Needed anywhere sharded params meet a fresh trace —
    e.g. eval over a gspmd/pipeline-laid-out state. Pass ``mesh`` to also
    enable nested-shard_map kernel regions (auto_partitioner_mesh)."""
    return _auto_partitioner_scope(mesh)


@contextlib.contextmanager
def _auto_partitioner_scope(mesh=None):
    token = _AUTO_PARTITIONED.set(True)
    mtoken = _AUTO_MESH.set(mesh)
    try:
        yield
    finally:
        _AUTO_MESH.reset(mtoken)
        _AUTO_PARTITIONED.reset(token)

# Megatron-style GPT-2 sharding: column-parallel qkv/fc (shard the output
# features), row-parallel proj (shard the input features), vocab-sharded
# embedding. LayerNorms and biases of row-parallel layers replicate.
GPT2_TP_RULES: Rules = [
    (r".*/qkv/w$", P(None, "tp")),
    (r".*/qkv/b$", P("tp")),
    (r".*/attn/proj/w$", P("tp", None)),
    (r".*/mlp/fc/w$", P(None, "tp")),
    (r".*/mlp/fc/b$", P("tp")),
    (r".*/mlp/proj/w$", P("tp", None)),
    (r"^wte/embedding$", P("tp", None)),
    # Explicitly-replicated tail so strict mode can prove full coverage:
    # row-parallel output biases, layernorms, position embeddings.
    (r".*/(attn|mlp)/proj/b$", P()),
    (r".*/ln_\d+/(scale|bias)$", P()),
    (r"^ln_f/(scale|bias)$", P()),
    (r"^wpe/embedding$", P()),
]

BERT_TP_RULES: Rules = [
    (r".*/qkv/w$", P(None, "tp")),
    (r".*/qkv/b$", P("tp")),
    (r".*/attn_out/w$", P("tp", None)),
    (r".*/fc/w$", P(None, "tp")),
    (r".*/fc/b$", P("tp")),
    (r".*/fc_out/w$", P("tp", None)),
    (r"^tok_emb/embedding$", P("tp", None)),
    (r".*/(attn_out|fc_out)/b$", P()),
    (r".*_ln/(scale|bias)$", P()),
    (r"^(pos|type)_emb/embedding$", P()),
    (r"^mlm_bias$", P()),
    (r"^mlm_dense/(w|b)$", P()),
]


def _leaf_path(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def param_specs_from_rules(params: Any, rules: Rules,
                           strict: bool = False) -> Any:
    """Pytree of PartitionSpecs matching ``params`` via first-match rules.

    Unmatched leaves replicate. With ``strict=True`` that silence becomes an
    error: every rule must match at least one parameter and every
    non-scalar parameter must be matched by some rule — a renamed layer
    fails loudly instead of silently replicating (and an obsolete rule
    can't linger in the table).
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    hits = [0] * len(compiled)
    unmatched: List[str] = []

    def spec_for(path, leaf):
        name = _leaf_path(path)
        for i, (pat, spec) in enumerate(compiled):
            if pat.match(name):
                hits[i] += 1
                return spec
        if getattr(leaf, "ndim", 1) > 0:
            unmatched.append(name)
        return P()

    specs = jax.tree_util.tree_map_with_path(spec_for, params)
    if strict:
        problems = []
        dead = [rules[i][0] for i, h in enumerate(hits) if h == 0]
        if dead:
            problems.append(f"rules matching no parameter: {dead}")
        if unmatched:
            problems.append(f"parameters matched by no rule: {unmatched}")
        if problems:
            raise ValueError(
                "strict sharding-rule check failed: " + "; ".join(problems))
    return specs


def scan_param_specs(params: Any, rules: Rules, num_layers: int,
                     prefix: str, stacked_key: str,
                     strict: bool = False) -> Any:
    """Partition specs for a scan-over-layers params layout, from the SAME
    rule table the unrolled layout uses (no second table to drift).

    The stacked subtree (``stacked_key``, leading [num_layers] dim on
    every leaf) is unstacked to the ``{prefix}{i}`` view, the rules are
    applied there (strict coverage checks included), and layer 0's trunk
    specs get a leading ``None`` (layers replicate along their own stack
    dim; TP shards the per-layer dims exactly as unrolled). This is the
    canonical TPU LLM sharding shape: lax.scan over stacked layers with
    GSPMD partitioning the scan body.
    """
    # Shape-only view: specs need leaf.ndim, not data — a real unstack
    # would transiently duplicate the whole trunk on device right before
    # sharding, the worst possible moment.
    drop_lead = lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype)
    unrolled = {k: jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), v)
                for k, v in params.items() if k != stacked_key}
    for i in range(num_layers):
        unrolled[f"{prefix}{i}"] = jax.tree_util.tree_map(
            drop_lead, params[stacked_key])
    specs = param_specs_from_rules(unrolled, rules, strict=strict)
    names = {f"{prefix}{i}" for i in range(num_layers)}
    trunk0 = specs[f"{prefix}0"]
    for i in range(1, num_layers):
        if specs[f"{prefix}{i}"] != trunk0:
            # A layer-anchored rule (e.g. "^h0/...") would otherwise be
            # silently flattened to layer 0's spec — fail loudly instead,
            # matching strict mode's contract.
            raise ValueError(
                f"scan_param_specs requires layer-uniform rules; layer {i} "
                f"resolved different specs than layer 0")
    out = {k: v for k, v in specs.items() if k not in names}
    out[stacked_key] = jax.tree_util.tree_map(
        lambda s: P(None, *s), trunk0,
        is_leaf=lambda x: isinstance(x, P))
    return out


def opt_state_specs(opt_state: Any, param_specs: Any) -> Any:
    """Optimizer stats inherit their parameter's spec; scalars replicate.

    Recurses into nested dicts whose structure does not match the param
    tree directly — optimizer WRAPPERS (e.g. accumulate_gradients) nest
    the inner optimizer's state under a key, and its mu/nu must stay
    sharded like their parameters, not silently replicate.

    Shared by the GSPMD and pipeline state-placement paths."""
    out = {}
    for key, sub in opt_state.items():
        if hasattr(sub, "ndim") and sub.ndim == 0:
            out[key] = P()
        elif isinstance(sub, dict) and jax.tree_util.tree_structure(
                sub) == jax.tree_util.tree_structure(param_specs):
            out[key] = param_specs
        elif isinstance(sub, dict):
            out[key] = opt_state_specs(sub, param_specs)
        else:
            out[key] = jax.tree_util.tree_map(lambda _: P(), sub)
    return out


def shard_train_state(state: TrainState, mesh: Mesh, param_specs: Any) -> TrainState:
    """Lay out an initialized TrainState across the mesh per the specs."""

    def put(tree, specs):
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs)

    return {
        "variables": {
            "params": put(state["variables"]["params"], param_specs),
            "state": jax.tree_util.tree_map(
                lambda x: jax.device_put(x, NamedSharding(mesh, P())),
                state["variables"]["state"]),
        },
        "opt_state": put(state["opt_state"],
                         opt_state_specs(state["opt_state"], param_specs)),
        "rng": jax.device_put(state["rng"], NamedSharding(mesh, P())),
    }


def make_gspmd_train_step(model: Module, optimizer: Optimizer,
                          loss_fn: Callable[[Any, dict], Any],
                          mesh: Mesh, param_specs: Any,
                          batch_axis: str = "dp", donate: bool = True):
    """jit-with-shardings train step: DP over ``batch_axis``, TP per
    ``param_specs``; XLA inserts every collective."""

    def step(state: TrainState, batch: dict):
        variables, opt_state = state["variables"], state["opt_state"]
        rng, next_rng = jax.random.split(state["rng"])

        def compute_loss(params):
            with _auto_partitioner_scope(mesh):  # trace-time flag + mesh
                out, new_state = model.apply(
                    {"params": params, "state": variables["state"]},
                    batch, training=True, rng=rng)
            return jnp.asarray(loss_fn(out, batch), jnp.float32), new_state

        (loss, new_state), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(variables["params"])
        updates, new_opt = optimizer.update(grads, opt_state, variables["params"])
        params = apply_updates(variables["params"], updates)
        return ({"variables": {"params": params,
                               "state": merge_state(variables["state"], new_state)},
                 "opt_state": new_opt, "rng": next_rng},
                {"loss": loss})

    def shardings_of(tree):
        # Reuse the committed layout of the (already-placed) state/batch.
        return jax.tree_util.tree_map(lambda x: x.sharding, tree)

    _cache: Dict = {}

    def stepper(state: TrainState, batch: dict):
        key = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                           for k, v in batch.items()))
        if key not in _cache:
            state_sh = shardings_of(state)
            batch_sh = jax.tree_util.tree_map(
                lambda v: NamedSharding(mesh, P(batch_axis)), batch)
            _cache[key] = jax.jit(
                step,
                in_shardings=(state_sh, batch_sh),
                out_shardings=(state_sh, NamedSharding(mesh, P())),
                donate_argnums=(0,) if donate else ())
        return _cache[key](state, batch)

    return stepper


def shard_batch_gspmd(mesh: Mesh, batch: Any, axis: str = "dp") -> Any:
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), batch)
