"""Ring attention — sequence/context parallelism for long sequences.

Not attested in the reference (SURVEY.md §0: only DP + ZeRO-1 observed), but
first-class here per the build brief: long-context must scale past one chip.

Design (blockwise attention on a ring, log-sum-exp stable):
the sequence axis is sharded over mesh axis ``sp``; each rank holds its
Q/K/V block. For ``world`` steps, every rank computes attention of its Q
block against the K/V block it currently holds, folds the partial result
into online-softmax accumulators, and passes the K/V block to its ring
neighbour with ``lax.ppermute`` (XLA lowers this to ICI neighbour DMA,
overlapped with the block matmuls). HBM per chip stays O(S/world); no rank
ever materialises full attention scores.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu.parallel._compat import axis_size

_NEG_BIG = -1e30  # finite "-inf" so fully-masked rows stay NaN-free


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: float | None = None,
                   use_flash: bool | None = None):
    """q,k,v: local blocks [B, H, S_local, D]; sequence sharded over
    ``axis_name``. Returns the local output block [B, H, S_local, D].
    Must be called inside shard_map with ``axis_name`` a mesh axis.

    ``use_flash=None`` auto-selects: per-hop Pallas flash blocks on TPU
    (O(block²) scratch instead of the composed path's O(S_local²) scores —
    the long-context enabler), composed XLA attention elsewhere. Pass
    ``use_flash=True`` on CPU to run the flash path in interpret mode
    (how CI executes it).
    """
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash:
        # scale=None passes through: the kernel layer owns the 1/sqrt(d)
        # default (flash_attention._flash_call), one place only.
        return _ring_flash(q, k, v, axis_name, causal, scale)
    world = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    local_pos = jnp.arange(s_local)
    q_pos = idx * s_local + local_pos  # global positions of our queries

    perm = [(i, (i + 1) % world) for i in range(world)]

    def attend_block(m, l, acc, k_cur, v_cur, src):
        """Fold one K/V block into the online-softmax accumulators. Dots
        take native-dtype inputs (bf16 on TPU: double MXU rate) with fp32
        accumulation — same recipe as the flash kernel."""
        k_pos = src * s_local + local_pos
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            allowed = k_pos[None, :] <= q_pos[:, None]  # [Sq, Sk] global causal
            scores = jnp.where(allowed[None, None], scores, _NEG_BIG)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    def body(i, carry):
        m, l, acc, k_cur, v_cur = carry
        # After i hops, the block we hold originated at rank (idx - i) mod world.
        src = (idx - i) % world

        if causal:
            # A block strictly from the future is fully masked: every score
            # is _NEG_BIG, so p underflows to exactly 0 and the fold is the
            # identity — skip the matmuls entirely (a real XLA conditional;
            # each rank takes its own branch). Saves ~half the ring's FLOPs.
            # The ppermute stays OUTSIDE the cond: it is a collective and
            # every rank must participate every hop.
            m, l, acc = lax.cond(
                src > idx,
                lambda ops_: ops_[:3],
                lambda ops_: attend_block(*ops_),
                (m, l, acc, k_cur, v_cur, src))
        else:
            m, l, acc = attend_block(m, l, acc, k_cur, v_cur, src)

        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return m, l, acc, k_next, v_next

    m0 = jnp.full((b, h, s_local, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    m, l, acc, _, _ = lax.fori_loop(0, world, body, (m0, l0, acc0, k, v))

    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention_lse(q, k, v, axis_name: str, causal: bool = True,
                       scale: float | None = None,
                       use_flash: bool | None = None):
    """:func:`ring_attention` that ALSO returns the per-row global
    log-sum-exp ``[B, H, S_local]`` fp32 — the merge handle a caller
    needs to fold this ring's result with attention computed elsewhere
    (the sequence-sharded serve prefill merges the chunk's ring output
    with per-shard paged-prefix attention via ``jnp.logaddexp``
    weights). Inference-only: no VJP (the flash path reuses the
    forward hop fold directly, bypassing the ring-level custom_vjp).

    Fully-masked rows (possible when ``causal=False`` is never the
    case here, but a caller may merge an EMPTY prefix) carry
    ``lse ~= -1e30`` so their merge weight underflows to exactly 0.
    """
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash:
        out, (_, _, _, _, lse) = _ring_flash_fwd(q, k, v, axis_name,
                                                 causal, scale)
        return out, lse
    world = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    local_pos = jnp.arange(s_local)
    q_pos = idx * s_local + local_pos
    perm = [(i, (i + 1) % world) for i in range(world)]

    def attend_block(m, l, acc, k_cur, v_cur, src):
        # Same fold as ring_attention.attend_block — kept in lockstep.
        k_pos = src * s_local + local_pos
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            allowed = k_pos[None, :] <= q_pos[:, None]
            scores = jnp.where(allowed[None, None], scores, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    def body(i, carry):
        m, l, acc, k_cur, v_cur = carry
        src = (idx - i) % world
        if causal:
            m, l, acc = lax.cond(
                src > idx,
                lambda ops_: ops_[:3],
                lambda ops_: attend_block(*ops_),
                (m, l, acc, k_cur, v_cur, src))
        else:
            m, l, acc = attend_block(m, l, acc, k_cur, v_cur, src)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return m, l, acc, k_next, v_next

    m0 = jnp.full((b, h, s_local, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    m, l, acc, _, _ = lax.fori_loop(0, world, body, (m0, l0, acc0, k, v))

    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    return out, lse


# ---------------------------------------------------------------------------
# Flash-ring: per-hop Pallas flash blocks under a ring-level custom VJP.
#
# Forward: each hop runs the flash kernel on (Q_local, K_src, V_src) —
# causal=True on the diagonal hop (src == idx), causal=False on
# fully-visible past hops, skipped on future hops — and merges the per-hop
# (out, lse) pairs log-sum-exp-stably. Backward is the classic ring
# backward: circulate K/V around the ring AGAIN together with dK/dV
# accumulators; each hop's flash_block_bwd uses the GLOBAL row lse (so the
# recomputed p is the true global softmax probability) and after `world`
# rotations every dK/dV block is back home. HBM per hop is the kernel's
# O(block_q x block_k) scratch, never S_local x S_local scores.


def _hop_case(idx, src, causal):
    """0 = skip (future), 1 = diagonal (flash causal), 2 = full (past)."""
    if not causal:
        return jnp.int32(2)
    return jnp.where(src > idx, 0, jnp.where(src == idx, 1, 2))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis_name: str, causal: bool,
                scale: float | None):
    out, _ = _ring_flash_fwd(q, k, v, axis_name, causal, scale)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale):
    from nezha_tpu.ops.pallas.flash_attention import flash_block_fwd

    world = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    perm = [(i, (i + 1) % world) for i in range(world)]

    def hop(i, carry):
        o, lse, k_cur, v_cur = carry
        src = (idx - i) % world

        def skip(_):
            return o, lse

        def attend(diag_causal):
            def fn(_):
                o_i, lse_i = flash_block_fwd(q, k_cur, v_cur,
                                             causal=diag_causal, scale=scale)
                new = jnp.logaddexp(lse, lse_i)
                w_old = jnp.exp(lse - new)[..., None]
                w_new = jnp.exp(lse_i - new)[..., None]
                return o * w_old + o_i.astype(jnp.float32) * w_new, new
            return fn

        o, lse = lax.switch(_hop_case(idx, src, causal),
                            [skip, attend(True), attend(False)], None)
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        return o, lse, k_cur, v_cur

    o0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    lse0 = jnp.full((b, h, s_local), _NEG_BIG, jnp.float32)
    o, lse, _, _ = lax.fori_loop(0, world, hop, (o0, lse0, k, v))
    out = o.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, residuals, g):
    from nezha_tpu.ops.pallas.flash_attention import flash_block_bwd

    q, k, v, out, lse = residuals
    world = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % world) for i in range(world)]
    g = g.astype(out.dtype)

    def hop(i, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (idx - i) % world

        def skip(_):
            return dq, dk_cur, dv_cur

        def attend(diag_causal):
            def fn(_):
                dqi, dki, dvi = flash_block_bwd(q, k_cur, v_cur, out, lse, g,
                                                causal=diag_causal,
                                                scale=scale)
                return (dq + dqi.astype(jnp.float32),
                        dk_cur + dki.astype(jnp.float32),
                        dv_cur + dvi.astype(jnp.float32))
            return fn

        dq, dk_cur, dv_cur = lax.switch(_hop_case(idx, src, causal),
                                        [skip, attend(True), attend(False)],
                                        None)
        # dK/dV travel WITH their K/V block; after `world` rotations each
        # accumulated gradient block is back at its owner.
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        return dq, k_cur, v_cur, dk_cur, dv_cur

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dkv0 = jnp.zeros(k.shape, jnp.float32)
    dq, _, _, dk, dv = lax.fori_loop(
        0, world, hop, (dq0, k, v, dkv0, jnp.zeros(v.shape, jnp.float32)))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_self_attention(mesh, q, k, v, axis: str = "sp", causal: bool = True,
                        use_flash: bool | None = None):
    """Convenience wrapper: shard [B,H,S,D] tensors over ``axis`` on the
    sequence dim and run ring attention, returning the full output.
    ``use_flash`` passes through to :func:`ring_attention` (None = auto)."""
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    fn = shard_map(
        partial(ring_attention, axis_name=axis, causal=causal,
                use_flash=use_flash),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
    )
    return jax.jit(fn)(q, k, v)
