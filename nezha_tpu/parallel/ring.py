"""Ring attention — sequence/context parallelism for long sequences.

Not attested in the reference (SURVEY.md §0: only DP + ZeRO-1 observed), but
first-class here per the build brief: long-context must scale past one chip.

Design (blockwise attention on a ring, log-sum-exp stable):
the sequence axis is sharded over mesh axis ``sp``; each rank holds its
Q/K/V block. For ``world`` steps, every rank computes attention of its Q
block against the K/V block it currently holds, folds the partial result
into online-softmax accumulators, and passes the K/V block to its ring
neighbour with ``lax.ppermute`` (XLA lowers this to ICI neighbour DMA,
overlapped with the block matmuls). HBM per chip stays O(S/world); no rank
ever materialises full attention scores.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

_NEG_BIG = -1e30  # finite "-inf" so fully-masked rows stay NaN-free


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: float | None = None):
    """q,k,v: local blocks [B, H, S_local, D]; sequence sharded over
    ``axis_name``. Returns the local output block [B, H, S_local, D].
    Must be called inside shard_map with ``axis_name`` a mesh axis.
    """
    world = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    local_pos = jnp.arange(s_local)
    q_pos = idx * s_local + local_pos  # global positions of our queries

    perm = [(i, (i + 1) % world) for i in range(world)]

    def attend_block(m, l, acc, k_cur, v_cur, src):
        """Fold one K/V block into the online-softmax accumulators. Dots
        take native-dtype inputs (bf16 on TPU: double MXU rate) with fp32
        accumulation — same recipe as the flash kernel."""
        k_pos = src * s_local + local_pos
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            allowed = k_pos[None, :] <= q_pos[:, None]  # [Sq, Sk] global causal
            scores = jnp.where(allowed[None, None], scores, _NEG_BIG)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    def body(i, carry):
        m, l, acc, k_cur, v_cur = carry
        # After i hops, the block we hold originated at rank (idx - i) mod world.
        src = (idx - i) % world

        if causal:
            # A block strictly from the future is fully masked: every score
            # is _NEG_BIG, so p underflows to exactly 0 and the fold is the
            # identity — skip the matmuls entirely (a real XLA conditional;
            # each rank takes its own branch). Saves ~half the ring's FLOPs.
            # The ppermute stays OUTSIDE the cond: it is a collective and
            # every rank must participate every hop.
            m, l, acc = lax.cond(
                src > idx,
                lambda ops_: ops_[:3],
                lambda ops_: attend_block(*ops_),
                (m, l, acc, k_cur, v_cur, src))
        else:
            m, l, acc = attend_block(m, l, acc, k_cur, v_cur, src)

        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return m, l, acc, k_next, v_next

    m0 = jnp.full((b, h, s_local, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    m, l, acc, _, _ = lax.fori_loop(0, world, body, (m0, l0, acc0, k, v))

    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_self_attention(mesh, q, k, v, axis: str = "sp", causal: bool = True):
    """Convenience wrapper: shard [B,H,S,D] tensors over ``axis`` on the
    sequence dim and run ring attention, returning the full output."""
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    fn = shard_map(
        partial(ring_attention, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
    )
    return jax.jit(fn)(q, k, v)
