"""Ring attention — sequence/context parallelism for long sequences.

Not attested in the reference (SURVEY.md §0: only DP + ZeRO-1 observed), but
first-class here per the build brief: long-context must scale past one chip.

Design (blockwise attention on a ring, log-sum-exp stable):
the sequence axis is sharded over mesh axis ``sp``; each rank holds its
Q/K/V block. For ``world`` steps, every rank computes attention of its Q
block against the K/V block it currently holds, folds the partial result
into online-softmax accumulators, and passes the K/V block to its ring
neighbour with ``lax.ppermute`` (XLA lowers this to ICI neighbour DMA,
overlapped with the block matmuls). HBM per chip stays O(S/world); no rank
ever materialises full attention scores.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

_NEG_BIG = -1e30  # finite "-inf" so fully-masked rows stay NaN-free


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: float | None = None):
    """q,k,v: local blocks [B, H, S_local, D]; sequence sharded over
    ``axis_name``. Returns the local output block [B, H, S_local, D].
    Must be called inside shard_map with ``axis_name`` a mesh axis.
    """
    world = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    q32 = q.astype(jnp.float32)
    local_pos = jnp.arange(s_local)
    q_pos = idx * s_local + local_pos  # global positions of our queries

    perm = [(i, (i + 1) % world) for i in range(world)]

    def body(i, carry):
        m, l, acc, k_cur, v_cur = carry
        # After i hops, the block we hold originated at rank (idx - i) mod world.
        src = (idx - i) % world
        k_pos = src * s_local + local_pos

        scores = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k_cur.astype(jnp.float32)) * scale
        if causal:
            allowed = k_pos[None, :] <= q_pos[:, None]  # [Sq, Sk] global causal
            scores = jnp.where(allowed[None, None], scores, _NEG_BIG)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))

        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, acc_new, k_next, v_next

    m0 = jnp.full((b, h, s_local, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    m, l, acc, _, _ = lax.fori_loop(0, world, body, (m0, l0, acc0, k, v))

    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_self_attention(mesh, q, k, v, axis: str = "sp", causal: bool = True):
    """Convenience wrapper: shard [B,H,S,D] tensors over ``axis`` on the
    sequence dim and run ring attention, returning the full output."""
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    fn = shard_map(
        partial(ring_attention, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
    )
    return jax.jit(fn)(q, k, v)
