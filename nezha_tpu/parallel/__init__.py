"""Parallelism layer: device meshes, XLA collectives, data parallelism,
ZeRO-1 optimizer sharding, and sequence/ring-attention parallelism.

TPU-native replacement for the reference's `pkg/nccl` cgo ring collectives
(SURVEY.md §2: ring all-reduce/all-gather, reduce-scatter for ZeRO-1).
Design: collectives are never hand-scheduled rings — they are XLA collective
ops (`psum`, `psum_scatter`, `all_gather`, `ppermute`) emitted inside
`shard_map` over a `jax.sharding.Mesh`, compiled by XLA to ride ICI.
"""

from nezha_tpu.parallel.mesh import make_mesh, make_cpu_mesh, local_mesh_axes
from nezha_tpu.parallel.collectives import (
    all_reduce_mean,
    all_reduce_sum,
    all_gather,
    reduce_scatter,
    ring_permute,
    barrier,
)
from nezha_tpu.parallel.data_parallel import (
    make_dp_train_step,
    shard_batch,
    shard_batch_process_local,
    replicate,
    sync_batch_stats,
)
from nezha_tpu.parallel.zero1 import make_zero1_train_step, zero1_init_opt_state
from nezha_tpu.parallel.gspmd import (
    GPT2_TP_RULES,
    BERT_TP_RULES,
    param_specs_from_rules,
    scan_param_specs,
    shard_train_state,
    make_gspmd_train_step,
)

__all__ = [
    "make_mesh", "make_cpu_mesh", "local_mesh_axes",
    "all_reduce_mean", "all_reduce_sum", "all_gather", "reduce_scatter",
    "ring_permute", "barrier",
    "make_dp_train_step", "shard_batch", "shard_batch_process_local",
    "replicate", "sync_batch_stats",
    "make_zero1_train_step", "zero1_init_opt_state",
    "GPT2_TP_RULES", "BERT_TP_RULES", "param_specs_from_rules",
    "scan_param_specs",
    "shard_train_state", "make_gspmd_train_step",
]


def __getattr__(name):
    import importlib

    if name in ("ring_attention", "ring_self_attention"):
        mod = importlib.import_module("nezha_tpu.parallel.ring")
        return getattr(mod, name)
    if name in ("ulysses_attention", "make_sp_train_step", "shard_lm_batch"):
        mod = importlib.import_module("nezha_tpu.parallel.sequence_parallel")
        return getattr(mod, name)
    if name in ("PipelineSpec", "pipeline_blocks", "pipelined_forward",
                "init_pipeline_state", "make_pipeline_train_step",
                "merge_pipeline_params", "gpt2_pipeline_spec",
                "stack_block_params", "unstack_block_params"):
        mod = importlib.import_module("nezha_tpu.parallel.pipeline")
        return getattr(mod, name)
    if name in ("MoE", "MoEConfig", "MOE_EP_RULES", "shard_moe_params",
                "dryrun_moe_step", "gpt2_moe_gspmd_rules"):
        mod = importlib.import_module("nezha_tpu.parallel.expert")
        return getattr(mod, name)
    if name in ("quantized_all_reduce_mean", "quantize_roundtrip",
                "quantized_wire_bytes", "quantized_reduce_scatter_mean",
                "quantized_all_gather"):
        mod = importlib.import_module("nezha_tpu.parallel.quantized")
        return getattr(mod, name)
    raise AttributeError(name)
