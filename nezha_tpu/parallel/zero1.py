"""ZeRO-1: optimizer-state sharding over the data-parallel axis.

Reference parity: BASELINE.json config 4 — "BERT-base with grad
reduce-scatter + weight all-gather (ZeRO-1-style)" (SURVEY.md §2). The TPU
mapping, per-step inside one shard_map:

  1. local backward produces full gradients per replica;
  2. each gradient leaf is flattened, padded to a multiple of the world
     size, and ``lax.psum_scatter`` (XLA reduce-scatter over ICI) hands each
     rank the summed 1/world-th slice — the NCCL reduce-scatter equivalent;
  3. the optimizer updates ONLY that slice (its optimizer state lives
     sharded: each HBM holds 1/world of mu/nu/velocity);
  4. ``lax.all_gather`` (tiled) reassembles the full update — the NCCL
     weight all-gather equivalent — and the replicated params are updated.

Memory: optimizer state per chip drops by ~world×; wire traffic per step is
the same bytes as plain all-reduce (reduce-scatter + all-gather IS the ring
all-reduce, split in half around the optimizer).
"""

from __future__ import annotations

import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nezha_tpu.nn.module import Module
from nezha_tpu.optim.optimizers import Optimizer, apply_updates
from nezha_tpu.parallel._compat import shard_map
from nezha_tpu.train.loop import TrainState, merge_state


def _padded_size(n: int, world: int) -> int:
    return math.ceil(n / world) * world


def _flat_pad(x, world: int):
    flat = x.reshape(-1)
    pad = _padded_size(flat.size, world) - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat


def zero1_init_opt_state(optimizer: Optimizer, params: Any, mesh: Mesh,
                         axis: str = "dp") -> Any:
    """Optimizer state over flat-padded params, laid out sharded over ``axis``.

    Global layout: every stat leaf is a 1-D array of the padded param size,
    sharded along dim 0 — each rank's HBM holds only its slice (ZeRO-1).
    """
    world = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    flat_params = jax.tree_util.tree_map(
        lambda p: _flat_pad(p.astype(jnp.float32), world), params)
    opt_state = optimizer.init(flat_params)

    def place(x):
        if x.ndim == 0:  # step counters stay replicated
            return jax.device_put(x, NamedSharding(mesh, P()))
        return jax.device_put(x, NamedSharding(mesh, P(axis)))

    return jax.tree_util.tree_map(place, opt_state)


def _opt_state_specs(opt_state: Any, axis: str) -> Any:
    return jax.tree_util.tree_map(
        lambda x: P() if x.ndim == 0 else P(axis), opt_state)


def make_zero1_train_step(model: Module, optimizer: Optimizer,
                          loss_fn: Callable[[Any, dict], Any],
                          mesh: Mesh, axis: str = "dp", donate: bool = True,
                          grad_reduce: str = "fp32",
                          quant_min_numel: int = 4096):
    """Build the ZeRO-1 train step. ``state["opt_state"]`` must come from
    ``zero1_init_opt_state``. Params stay replicated; batch sharded.

    ``grad_reduce="int8"`` puts block-scaled int8 on the wire for BOTH
    collectives of leaves >= ``quant_min_numel`` — the gradient
    reduce-scatter and the update all-gather (parallel/quantized.py;
    ZeRO++-style). Optimizer math stays fp32 on the exact-summed shard;
    small leaves ride the exact path.
    """
    if grad_reduce not in ("fp32", "int8"):
        raise ValueError(f"grad_reduce must be fp32|int8, got {grad_reduce!r}")
    world = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]

    def per_replica(state: TrainState, batch: dict):
        variables, opt_state = state["variables"], state["opt_state"]
        rng, next_rng = jax.random.split(state["rng"])
        step_rng = jax.random.fold_in(rng, lax.axis_index(axis))
        idx = lax.axis_index(axis)

        def compute_loss(params):
            out, new_state = model.apply(
                {"params": params, "state": variables["state"]},
                batch, training=True, rng=step_rng)
            return jnp.asarray(loss_fn(out, batch), jnp.float32), new_state

        (loss, new_state), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(variables["params"])
        loss = lax.pmean(loss, axis)
        new_state = jax.tree_util.tree_map(lambda s: lax.pmean(s, axis), new_state)

        # (2) grad reduce-scatter: each rank ends with its mean slice.
        # Both wire phases gate on the same shared predicate over the SAME
        # leaf size (params and their grads are shaped alike), so a leaf is
        # either quantized in both phases or neither.
        from nezha_tpu import obs
        from nezha_tpu.parallel.quantized import should_quantize

        if obs.enabled():
            # One record per op per traced program (the dp/wrapper
            # convention — not per leaf), at actual wire width: int8 leaves
            # count int8+scale bytes, exact leaves fp32. Chunk sizes mirror
            # _flat_pad (world-padded) and the block padding inside the
            # quantized collectives.
            from nezha_tpu.parallel.quantized import (split_quantized_leaves,
                                                      wire_payload_bytes)
            quant, exact = (split_quantized_leaves(grads, quant_min_numel)
                            if grad_reduce == "int8"
                            else ([], jax.tree_util.tree_leaves(grads)))
            chunks_q = [-(-g.size // world) for g in quant]
            chunks_e = [-(-g.size // world) for g in exact]
            for op, payload in (
                    ("reduce_scatter", sum(c * world * 4 for c in chunks_e)),
                    ("reduce_scatter_int8",
                     sum(world * wire_payload_bytes(c) for c in chunks_q)),
                    ("all_gather", sum(c * 4 for c in chunks_e)),
                    ("all_gather_int8",
                     sum(wire_payload_bytes(c) for c in chunks_q))):
                if payload:
                    obs.record_collective(op, payload)

        def to_chunk(g):
            flat = _flat_pad(g.astype(jnp.float32), world)
            if grad_reduce == "int8" and should_quantize(g, quant_min_numel):
                from nezha_tpu.parallel.quantized import (
                    quantized_reduce_scatter_mean)
                return quantized_reduce_scatter_mean(flat, axis)
            return lax.psum_scatter(flat, axis, scatter_dimension=0,
                                    tiled=True) / world

        grad_chunks = jax.tree_util.tree_map(to_chunk, grads)

        # Param slice matching this rank's shard.
        def param_chunk(p):
            flat = _flat_pad(p.astype(jnp.float32), world)
            chunk = flat.size // world
            return lax.dynamic_slice(flat, (idx * chunk,), (chunk,))

        param_chunks = jax.tree_util.tree_map(param_chunk, variables["params"])

        # (3) shard-local optimizer update.
        update_chunks, opt_state = optimizer.update(
            grad_chunks, opt_state, param_chunks)

        # (4) weight all-gather of the updates, then apply to full params.
        def to_full(u, p):
            if grad_reduce == "int8" and should_quantize(p, quant_min_numel):
                from nezha_tpu.parallel.quantized import quantized_all_gather
                full = quantized_all_gather(u, axis)
            else:
                full = lax.all_gather(u, axis, axis=0, tiled=True)
            return full[:p.size].reshape(p.shape)

        updates = jax.tree_util.tree_map(to_full, update_chunks,
                                         variables["params"])
        params = apply_updates(variables["params"], updates)

        new_variables = {"params": params,
                         "state": merge_state(variables["state"], new_state)}
        return ({"variables": new_variables, "opt_state": opt_state,
                 "rng": next_rng}, {"loss": loss})

    def build(state_template, batch_template):
        tmap = jax.tree_util.tree_map
        var_spec = tmap(lambda _: P(), state_template["variables"])
        opt_spec = _opt_state_specs(state_template["opt_state"], axis)
        rng_spec = P()
        state_spec = {"variables": var_spec, "opt_state": opt_spec,
                      "rng": rng_spec}
        batch_spec = tmap(lambda _: P(axis), batch_template)
        mapped = shard_map(per_replica, mesh=mesh,
                           in_specs=(state_spec, batch_spec),
                           out_specs=(state_spec, P()))
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())

    _cache = {}

    def step(state: TrainState, batch: dict):
        key = tuple((k, tuple(v.shape), str(v.dtype)) for k, v in sorted(
            batch.items(), key=lambda kv: kv[0]))
        if key not in _cache:
            _cache[key] = build(state, batch)
        return _cache[key](state, batch)

    return step
