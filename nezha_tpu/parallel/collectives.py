"""Collective primitives (to be used inside shard_map over a named mesh axis).

TPU-native replacement for `pkg/nccl`'s cgo ring collectives (SURVEY.md §2).
Each wrapper emits the XLA collective HLO; XLA's collective scheduler picks
the ring/tree algorithm and overlaps it with compute — nothing is
hand-scheduled. Bus-bandwidth accounting helpers mirror the reference's
"all-reduce bus bw" metric of record (BASELINE.json `metric`).

Telemetry: every wrapper (and the dp/zero1 train-step collectives) reports
its op + payload bytes to the process-wide registry — the wrappers via
:func:`record_traced_collective`, the int8-wire train-step paths directly
at their actual wire width (int8 + scales; see parallel/quantized.py
``wire_payload_bytes``). Shapes are static under tracing, so the
recording happens at TRACE time — the counters measure the bytes one
execution of each compiled program moves, not bytes x steps (the run
report states the convention). Zero cost while telemetry is disabled: the
guard is one flag check before any tree traversal.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu import obs
from nezha_tpu.parallel._compat import axis_size


def record_traced_collective(op: str, tree: Any) -> None:
    """Account a collective emitted during tracing: per-device payload
    bytes of ``tree`` (leaf shapes are static on tracers). No-op when
    telemetry is disabled."""
    if not obs.enabled():
        return
    payload = sum(x.size * x.dtype.itemsize
                  for x in jax.tree_util.tree_leaves(tree)
                  if hasattr(x, "size") and hasattr(x, "dtype"))
    obs.record_collective(op, payload)


def all_reduce_sum(tree: Any, axis_name: str) -> Any:
    record_traced_collective("all_reduce", tree)
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), tree)


def all_reduce_mean(tree: Any, axis_name: str) -> Any:
    record_traced_collective("all_reduce", tree)
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)


def all_gather(tree: Any, axis_name: str, axis: int = 0, tiled: bool = True) -> Any:
    """Gather shards along ``axis`` from every rank (concatenated if tiled)."""
    record_traced_collective("all_gather", tree)
    return jax.tree_util.tree_map(
        lambda x: lax.all_gather(x, axis_name, axis=axis, tiled=tiled), tree)


def reduce_scatter(tree: Any, axis_name: str, axis: int = 0) -> Any:
    """Sum-reduce then scatter shards along ``axis`` (ZeRO-1 gradient path)."""
    record_traced_collective("reduce_scatter", tree)
    return jax.tree_util.tree_map(
        lambda x: lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True),
        tree)


def ring_permute(x, axis_name: str, shift: int = 1):
    """Send to the next rank on the ring (ring attention / pipeline edges)."""
    record_traced_collective("ppermute", x)
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def barrier(mesh) -> None:
    """Host-level device barrier: an all-reduce of one scalar per device.

    The reference used its gRPC coordinator for barriers (SURVEY.md §1); on
    TPU a trivial psum over the whole mesh is the native equivalent.
    """
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    ones = jnp.ones((mesh.devices.size,), jnp.float32)
    axes = tuple(mesh.axis_names)

    def _sum(x):
        s = x
        for a in axes:
            s = lax.psum(s, a)
        return s

    out = jax.jit(shard_map(_sum, mesh=mesh, in_specs=P(axes), out_specs=P(axes)))(ones)
    jax.block_until_ready(out)


def allreduce_bus_bandwidth(payload_bytes: int, seconds: float, world: int) -> float:
    """NCCL-convention bus bandwidth for ring all-reduce:
    busBW = (bytes * 2*(n-1)/n) / time."""
    if seconds <= 0:
        return 0.0
    return payload_bytes * (2.0 * (world - 1) / world) / seconds
