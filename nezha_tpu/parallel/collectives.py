"""Collective primitives (to be used inside shard_map over a named mesh axis).

TPU-native replacement for `pkg/nccl`'s cgo ring collectives (SURVEY.md §2).
Each wrapper emits the XLA collective HLO; XLA's collective scheduler picks
the ring/tree algorithm and overlaps it with compute — nothing is
hand-scheduled. Bus-bandwidth accounting helpers mirror the reference's
"all-reduce bus bw" metric of record (BASELINE.json `metric`).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce_sum(tree: Any, axis_name: str) -> Any:
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), tree)


def all_reduce_mean(tree: Any, axis_name: str) -> Any:
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)


def all_gather(tree: Any, axis_name: str, axis: int = 0, tiled: bool = True) -> Any:
    """Gather shards along ``axis`` from every rank (concatenated if tiled)."""
    return jax.tree_util.tree_map(
        lambda x: lax.all_gather(x, axis_name, axis=axis, tiled=tiled), tree)


def reduce_scatter(tree: Any, axis_name: str, axis: int = 0) -> Any:
    """Sum-reduce then scatter shards along ``axis`` (ZeRO-1 gradient path)."""
    return jax.tree_util.tree_map(
        lambda x: lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True),
        tree)


def ring_permute(x, axis_name: str, shift: int = 1):
    """Send to the next rank on the ring (ring attention / pipeline edges)."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def barrier(mesh) -> None:
    """Host-level device barrier: an all-reduce of one scalar per device.

    The reference used its gRPC coordinator for barriers (SURVEY.md §1); on
    TPU a trivial psum over the whole mesh is the native equivalent.
    """
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    ones = jnp.ones((mesh.devices.size,), jnp.float32)
    axes = tuple(mesh.axis_names)

    def _sum(x):
        s = x
        for a in axes:
            s = lax.psum(s, a)
        return s

    out = jax.jit(shard_map(_sum, mesh=mesh, in_specs=P(axes), out_specs=P(axes)))(ones)
    jax.block_until_ready(out)


def allreduce_bus_bandwidth(payload_bytes: int, seconds: float, world: int) -> float:
    """NCCL-convention bus bandwidth for ring all-reduce:
    busBW = (bytes * 2*(n-1)/n) / time."""
    if seconds <= 0:
        return 0.0
    return payload_bytes * (2.0 * (world - 1) / world) / seconds
