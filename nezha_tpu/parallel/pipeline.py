"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp`` mesh axis.

Not attested in the reference (SURVEY.md §0: only DP + ZeRO-1 observed), but
first-class here per the build brief: model depth must scale past one chip.

TPU-first design — the whole schedule is ONE SPMD program, not a host-side
scheduler like GPU pipeline runtimes:

- Block parameters are *stacked* along a leading layer axis and sharded over
  the ``pp`` mesh axis, so each pipeline rank holds a contiguous slab of
  layers (its *stage*) and the optimizer update for its slab stays local.
- The schedule is a ``lax.scan`` over ticks inside ``shard_map``. Each tick,
  every rank applies its stage to the activation it holds and hands the
  result to its ring neighbour with ``lax.ppermute`` (XLA lowers this to an
  ICI neighbour DMA overlapped with the next tick's matmuls).
- Backward is plain ``jax.grad`` through the scan: shard_map transposes
  ``ppermute`` to the reverse hop, so the backward pipeline runs the ring in
  the opposite direction automatically — no hand-written backward schedule.
- The embed/head ("outer") parameters run replicated outside the pipelined
  region under normal GSPMD, so they compose with dp sharding of the batch.

Bubble fraction is the usual GPipe (S-1)/(M+S-1); raise ``num_microbatches``
to amortize.
"""

from __future__ import annotations

import re
from functools import partial
from typing import Any, Callable, Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nezha_tpu.optim.optimizers import Optimizer, apply_updates
from nezha_tpu.parallel._compat import axis_size, shard_map

PyTree = Any


class PipelineSpec(NamedTuple):
    """How to pipeline a model of shape embed -> N identical blocks -> head.

    - ``embed_fn(outer_params, batch, rng=None) -> x``: pre-pipeline compute
      (token + position embedding [+ embed dropout when rng is given]),
      replicated over pp, GSPMD-sharded over dp.
    - ``block_fn(block_params, x, rng=None) -> x``: apply ONE block; scanned
      over each stage's layer slab inside the pipeline. ``rng`` (when the
      step passes one) is already unique per (layer, microbatch, dp-rank).
    - ``head_fn(outer_params, x) -> out``: post-pipeline compute (final norm
      + LM head).
    - ``split(params) -> (outer, [block_params, ...])`` and
      ``merge(outer, [block_params, ...]) -> params`` convert between the
      model's native param tree and the pipelined layout.

    The rng parameters are only exercised by steps built with
    ``make_pipeline_train_step(..., dropout_rng=True)`` — deterministic
    specs may ignore them.
    """

    embed_fn: Callable[[PyTree, Any], jax.Array]
    block_fn: Callable[[PyTree, jax.Array], jax.Array]
    head_fn: Callable[[PyTree, jax.Array], jax.Array]
    split: Callable[[PyTree], Tuple[PyTree, List[PyTree]]]
    merge: Callable[[PyTree, List[PyTree]], PyTree]
    # The model's dropout rate: lets the step factory refuse a dropout>0
    # spec without dropout_rng=True (which would silently train dropless).
    dropout: float = 0.0
    # The model's remat request: the step factory maps it onto per-tick
    # stage checkpointing (GPT2Config.remat wraps blocks outside pp; inside
    # pp the schedule owns rematerialization).
    remat: bool = False


def stack_block_params(blocks: List[PyTree]) -> PyTree:
    """Stack per-layer param trees into leading-axis arrays [L, ...]."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)


def unstack_block_params(stacked: PyTree) -> List[PyTree]:
    n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return [jax.tree_util.tree_map(lambda a: a[i], stacked) for i in range(n)]


def pipeline_blocks(stage_params: PyTree, x: jax.Array, rng=None, *,
                    block_fn: Callable[..., jax.Array],
                    num_microbatches: int, axis_name: str = "pp",
                    dp_axis: str = None, remat: bool = False) -> jax.Array:
    """The SPMD pipeline body. Call inside shard_map over ``axis_name``.

    ``stage_params``: this rank's slab of stacked layer params [L_stage, ...].
    ``x``: the local batch of activations [B_local, ...]; split into
    ``num_microbatches`` microbatches internally. Returns [B_local, ...].

    ``rng`` (optional): dropout key. Each block application receives a key
    folded with (global layer index, microbatch index, dp rank) so masks
    are independent across layers, microbatches, steps, and data-parallel
    shards — bubble-tick applications draw keys too but their outputs are
    masked away, so they cost nothing and corrupt nothing.
    """
    world = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = num_microbatches
    b_local = x.shape[0]
    if b_local % m:
        raise ValueError(f"local batch {b_local} not divisible by "
                         f"num_microbatches {m}")
    xs = x.reshape(m, b_local // m, *x.shape[1:])
    if rng is not None and dp_axis is not None:
        rng = jax.random.fold_in(rng, lax.axis_index(dp_axis))

    n_layers_stage = jax.tree_util.tree_leaves(stage_params)[0].shape[0]

    def stage_fn(params_slab, h, mb_idx):
        def body(h, scanned):
            layer_params, li = scanned
            if rng is None:
                return block_fn(layer_params, h), None
            key = jax.random.fold_in(
                jax.random.fold_in(rng, stage * n_layers_stage + li), mb_idx)
            return block_fn(layer_params, h, key), None

        h, _ = lax.scan(body, h, (params_slab, jnp.arange(n_layers_stage)))
        return h

    if remat:
        # GPipe's memory cliff is the M microbatch activations saved per
        # tick; checkpointing the stage application keeps only each tick's
        # input and recomputes the stage in backward (~1/3 extra FLOPs for
        # O(M)->O(1) per-tick residuals). rng replays through the
        # recompute, so dropout masks are identical.
        stage_fn = jax.checkpoint(stage_fn)

    perm = [(i, (i + 1) % world) for i in range(world)]
    ticks = m + world - 1

    def tick(carry, t):
        state, outputs = carry
        x_in = lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, m - 1), 0,
                                        keepdims=False)
        inp = jnp.where(stage == 0, x_in, state)
        out = stage_fn(stage_params, inp, jnp.clip(t - stage, 0, m - 1))
        out_idx = jnp.clip(t - (world - 1), 0, m - 1)
        valid = jnp.logical_and(stage == world - 1, t >= world - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, out, cur), out_idx, 0)
        state = lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    state0 = jnp.zeros_like(xs[0])
    outputs0 = jnp.zeros_like(xs)
    (_, outputs), _ = lax.scan(tick, (state0, outputs0), jnp.arange(ticks))

    # Only the last stage's buffer is real; broadcast it to every pp rank
    # (masked psum — the transpose under grad is the matching masked psum).
    outputs = lax.psum(
        jnp.where(stage == world - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape(b_local, *x.shape[1:])


def pipelined_forward(spec: PipelineSpec, pparams: Dict[str, PyTree],
                      batch_inputs: Any, mesh: Mesh, num_microbatches: int,
                      pp_axis: str = "pp", dp_axis: str = "dp",
                      rng=None, remat: bool = False) -> jax.Array:
    """Full forward: embed (GSPMD) -> pipelined blocks (shard_map) -> head.

    ``pparams``: {"outer": outer_params, "blocks": stacked [L, ...] tree}.
    ``rng``: dropout key threaded to embed_fn and (per layer/microbatch)
    into the pipelined region; None = deterministic forward.
    """
    dp_in_mesh = dp_axis in mesh.axis_names
    xspec = P(dp_axis) if dp_in_mesh else P()
    body = partial(pipeline_blocks, block_fn=spec.block_fn,
                   num_microbatches=num_microbatches, axis_name=pp_axis,
                   dp_axis=dp_axis if dp_in_mesh else None, remat=remat)
    if rng is None:
        x = spec.embed_fn(pparams["outer"], batch_inputs)
        run = shard_map(body, mesh=mesh, in_specs=(P(pp_axis), xspec),
                        out_specs=xspec)
        y = run(pparams["blocks"], x)
    else:
        embed_rng, block_rng = jax.random.split(rng)
        x = spec.embed_fn(pparams["outer"], batch_inputs, embed_rng)
        run = shard_map(body, mesh=mesh,
                        in_specs=(P(pp_axis), xspec, P()), out_specs=xspec)
        y = run(pparams["blocks"], x, block_rng)
    return spec.head_fn(pparams["outer"], y)


def init_pipeline_state(variables: PyTree, spec: PipelineSpec,
                        optimizer: Optimizer, mesh: Mesh, rng: jax.Array,
                        pp_axis: str = "pp") -> Dict[str, Any]:
    """Build + place the pipelined TrainState.

    Outer params replicate; stacked block params shard over ``pp`` on the
    layer axis (each rank gets its stage slab); optimizer slots follow their
    parameter's layout.
    """
    outer, blocks = spec.split(variables["params"])
    if len(blocks) % mesh.shape[pp_axis]:
        raise ValueError(f"{len(blocks)} layers not divisible by pp="
                         f"{mesh.shape[pp_axis]}")
    pparams = {"outer": outer, "blocks": stack_block_params(blocks)}
    opt_state = optimizer.init(pparams)

    def specs_like(tree, is_blocks):
        sp = P(pp_axis) if is_blocks else P()
        return jax.tree_util.tree_map(lambda _: sp, tree)

    def place(tree, spec_tree):
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            tree, spec_tree)

    param_specs = {"outer": specs_like(outer, False),
                   "blocks": specs_like(pparams["blocks"], True)}

    from nezha_tpu.parallel.gspmd import opt_state_specs

    return {
        "pparams": place(pparams, param_specs),
        "opt_state": place(opt_state, opt_state_specs(opt_state, param_specs)),
        "rng": jax.device_put(rng, NamedSharding(mesh, P())),
    }


def make_pipeline_train_step(spec: PipelineSpec, optimizer: Optimizer,
                             loss_fn: Callable[[jax.Array, dict], jax.Array],
                             mesh: Mesh, num_microbatches: int,
                             pp_axis: str = "pp", dp_axis: str = "dp",
                             donate: bool = True, dropout_rng: bool = False,
                             remat: bool = None):
    """jit'd train step over {"pparams", "opt_state", "rng"} state.

    Batch dicts shard over ``dp_axis`` (when present in the mesh); grads of
    stage slabs stay pp-local, grads of outer params are psum'd by the SPMD
    partitioner. ``dropout_rng=True`` threads a per-step key through the
    spec's embed/block fns (which must then accept one) so dropout>0 models
    pipeline correctly. Returns ``step(state, batch) -> (state, metrics)``.
    """
    if spec.dropout and not dropout_rng:
        # Without keys the blocks run deterministically — a dropout>0 model
        # would silently train with dropout off. Refuse loudly.
        raise ValueError(
            f"spec carries dropout={spec.dropout} but dropout_rng=False; "
            f"pass make_pipeline_train_step(..., dropout_rng=True)")
    # remat defaults to the spec's own request (cfg.remat), so a model
    # built for rematerialization can't silently hit the GPipe memory
    # cliff; pass remat=False explicitly to override.
    remat = spec.remat if remat is None else remat

    def step(state, batch):
        if dropout_rng:
            step_rng, next_rng = jax.random.split(state["rng"])
        else:
            # Deterministic forward; the state rng still advances so
            # interleaving with stochastic steps stays reproducible.
            step_rng = None
            next_rng = jax.random.fold_in(state["rng"], 0)

        def compute_loss(pparams):
            out = pipelined_forward(spec, pparams, batch, mesh,
                                    num_microbatches, pp_axis, dp_axis,
                                    rng=step_rng, remat=remat)
            return jnp.asarray(loss_fn(out, batch), jnp.float32)

        loss, grads = jax.value_and_grad(compute_loss)(state["pparams"])
        updates, new_opt = optimizer.update(grads, state["opt_state"],
                                            state["pparams"])
        pparams = apply_updates(state["pparams"], updates)
        return ({"pparams": pparams, "opt_state": new_opt, "rng": next_rng},
                {"loss": loss})

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def merge_pipeline_params(spec: PipelineSpec, pparams: Dict[str, PyTree]) -> PyTree:
    """Back to the model's native param tree (for eval/checkpoint export)."""
    return spec.merge(pparams["outer"], unstack_block_params(pparams["blocks"]))


# ---------------------------------------------------------------------------
# Model adapters


def gpt2_pipeline_spec(model) -> PipelineSpec:
    """PipelineSpec for ``nezha_tpu.models.gpt2.GPT2``. dropout>0 configs
    need a step built with ``dropout_rng=True`` (the CLI does this
    automatically) so the per-(layer, microbatch) keys reach the blocks;
    without a key the blocks run deterministically."""
    from nezha_tpu.nn.module import child_vars

    cfg = model.cfg
    if cfg.moe_experts:
        raise ValueError("gpt2_pipeline_spec cannot pipeline MoE blocks "
                         "(heterogeneous stage slabs)")
    template = model.h[0]

    def embed_fn(outer, batch, rng=None):
        tokens = batch["tokens"][:, :-1] if isinstance(batch, dict) else batch
        variables = {"params": outer, "state": {}}
        pos = jnp.arange(tokens.shape[1])[None, :]
        x, _ = model.wte.apply(child_vars(variables, "wte"), tokens)
        pe, _ = model.wpe.apply(child_vars(variables, "wpe"), pos)
        x = x + pe
        if rng is not None:
            x, _ = model.drop.apply({"params": {}, "state": {}}, x,
                                    training=True, rng=rng)
        return x

    def block_fn(block_params, x, rng=None):
        out, _ = template.apply({"params": block_params, "state": {}}, x,
                                training=rng is not None, rng=rng)
        return out

    def head_fn(outer, x):
        variables = {"params": outer, "state": {}}
        x, _ = model.ln_f.apply(child_vars(variables, "ln_f"), x)
        if cfg.fused_loss_chunk:
            # Same fused-head protocol as GPT2.apply: the loss (lm_loss ->
            # lm_objective) computes bf16 logits with the fp32 upcast fused
            # into logsumexp — the pipeline otherwise materializes the full
            # fp32 [B,S,V] on the last stage's exit.
            wte = child_vars(variables, "wte")["params"]["embedding"]
            return {"hidden": x, "wte": wte, "chunk": cfg.fused_loss_chunk}
        logits = model.wte.attend(child_vars(variables, "wte"), x)
        return jnp.asarray(logits, jnp.float32)

    def split(params):
        pat = re.compile(r"^h(\d+)$")
        blocks = [params[f"h{i}"] for i in range(cfg.num_layers)]
        outer = {k: v for k, v in params.items() if not pat.match(k)}
        return outer, blocks

    def merge(outer, blocks):
        p = dict(outer)
        for i, b in enumerate(blocks):
            p[f"h{i}"] = b
        return p

    return PipelineSpec(embed_fn, block_fn, head_fn, split, merge,
                        dropout=cfg.dropout, remat=cfg.remat)
