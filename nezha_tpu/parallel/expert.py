"""Mixture-of-experts with expert parallelism over an ``ep`` mesh axis.

Not attested in the reference (SURVEY.md §0: only DP + ZeRO-1 observed);
included per the build brief (dp/tp/pp/sp/ep are all first-class).

TPU-first design — the Mesh-TensorFlow/Flaxformer dense-dispatch
formulation rather than gather/scatter token shuffling:

- Routing produces *static-shape* one-hot dispatch/combine tensors
  [T, E, C] (top-k gating, fixed capacity C per expert). No dynamic shapes,
  so the whole layer stays inside one XLA program.
- Dispatch, expert compute, and combine are einsums — MXU work, not
  scalar indexing.
- Expert weights are stacked [E, d, f] and sharded over ``ep`` with GSPMD
  PartitionSpecs; XLA's SPMD partitioner inserts the token all-to-alls
  between the dp-sharded token axis and the ep-sharded expert axis (the
  TPU-native equivalent of NCCL all-to-all in GPU MoE stacks).
- Tokens over capacity are *dropped* (standard Switch behavior) and the
  load-balance auxiliary loss keeps the router near-uniform.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nezha_tpu import nn
from nezha_tpu.nn import initializers as init_lib
from nezha_tpu.nn.module import Module, Variables, make_variables
from nezha_tpu.tensor.policy import DEFAULT_POLICY, Policy


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.5
    aux_loss_weight: float = 0.01


def _top_k_gating(router_logits: jax.Array, top_k: int, num_experts: int,
                  capacity: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Return (dispatch [T,E,C] one-hot, combine [T,E,C], aux_loss scalar)."""
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [T,E]
    t = probs.shape[0]

    gate_list, mask_list = [], []
    remaining = probs
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                       # [T]
        onehot = jax.nn.one_hot(idx, num_experts, dtype=probs.dtype)
        gate_list.append(jnp.sum(probs * onehot, axis=-1))         # [T]
        mask_list.append(onehot)
        remaining = remaining * (1.0 - onehot)

    # Position of each token within its expert's capacity buffer: cumsum of
    # the selection mask over tokens, counting earlier top-k passes first.
    dispatch = jnp.zeros((t, num_experts, capacity), probs.dtype)
    combine = jnp.zeros((t, num_experts, capacity), probs.dtype)
    prior = jnp.zeros((num_experts,), probs.dtype)
    for gate, mask in zip(gate_list, mask_list):
        pos = jnp.cumsum(mask, axis=0) - mask + prior[None, :]     # [T,E]
        in_cap = (pos < capacity) & (mask > 0)
        pos_clamped = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
        onehot_cap = jax.nn.one_hot(pos_clamped, capacity, dtype=probs.dtype)
        sel = onehot_cap * in_cap[..., None] * mask[..., None]     # [T,E,C]
        dispatch = dispatch + sel
        combine = combine + sel * gate[:, None, None]
        prior = prior + jnp.sum(mask, axis=0)

    # Switch-style load-balance loss: E * sum_e fraction_e * router_prob_e.
    frac = jnp.mean(mask_list[0], axis=0)          # top-1 assignment fraction
    prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac * prob)
    return dispatch, combine, aux


class MoE(Module):
    """Top-k routed mixture of expert MLPs (GELU two-layer experts).

    ``apply`` returns ``(y, state)`` where ``state['aux_loss']`` carries the
    load-balance loss — add ``cfg.aux_loss_weight * aux_loss`` to the
    training objective.
    """

    def __init__(self, cfg: MoEConfig, policy: Policy = DEFAULT_POLICY,
                 name: Optional[str] = None):
        self.cfg = cfg
        self.policy = policy
        self.router = nn.Linear(cfg.d_model, cfg.num_experts,
                                kernel_init=init_lib.normal(0.02),
                                use_bias=False, policy=policy)

    def init(self, rng: jax.Array) -> Variables:
        cfg = self.cfg
        r_router, r_in, r_out = jax.random.split(rng, 3)
        k_in = init_lib.normal(0.02)(
            r_in, (cfg.num_experts, cfg.d_model, cfg.d_ff), jnp.float32)
        k_out = init_lib.normal(0.02)(
            r_out, (cfg.num_experts, cfg.d_ff, cfg.d_model), jnp.float32)
        return make_variables({
            "router": self.router.init(r_router)["params"],
            "w_in": k_in,
            "w_out": k_out,
        })

    def capacity(self, num_tokens: int) -> int:
        cfg = self.cfg
        return max(1, int(cfg.capacity_factor * cfg.top_k * num_tokens
                          / cfg.num_experts))

    def apply(self, variables: Variables, x, training: bool = False, rng=None):
        cfg = self.cfg
        params = variables["params"]
        b, s, d = x.shape
        tokens = x.reshape(b * s, d)
        num_tokens = b * s
        cap = self.capacity(num_tokens)

        logits, _ = self.router.apply({"params": params["router"], "state": {}},
                                      tokens)
        dispatch, combine, aux = _top_k_gating(
            logits, cfg.top_k, cfg.num_experts, cap)

        compute_dtype = self.policy.compute_dtype
        xin = jnp.einsum("tec,td->ecd", dispatch.astype(compute_dtype),
                         tokens.astype(compute_dtype))
        h = jnp.einsum("ecd,edf->ecf", xin,
                       params["w_in"].astype(compute_dtype))
        h = jax.nn.gelu(h)
        out = jnp.einsum("ecf,efd->ecd", h,
                         params["w_out"].astype(compute_dtype))
        y = jnp.einsum("tec,ecd->td", combine.astype(compute_dtype), out)
        y = y.reshape(b, s, d).astype(x.dtype)
        return y, {"aux_loss": aux}


def moe_ep_rules(ep_axis: str = "ep"):
    """GSPMD rules: stacked expert weights shard over ``ep_axis`` on the
    expert axis; the router (and everything else) replicates."""
    return [
        (r".*w_in$", P(ep_axis, None, None)),
        (r".*w_out$", P(ep_axis, None, None)),
    ]


MOE_EP_RULES = moe_ep_rules()


def gpt2_moe_gspmd_rules(tp_rules=None, ep_axis: str = "ep"):
    """First-match GSPMD rule table for the MoE GPT-2 param tree: stacked
    expert weights shard over ``ep_axis``, the router replicates, and the
    dense remainder (attention, dense-block MLPs, embeddings, norms)
    follows ``tp_rules`` — pass ``parallel.GPT2_TP_RULES`` for a
    dp x tp x ep launch (tp=1 degrades gracefully to dp x ep). Strict-mode
    compatible: every MoE-specific leaf is matched here, every dense leaf
    by the appended table."""
    return (moe_ep_rules(ep_axis)  # single source of truth for expert specs
            + [(r".*/mlp/router/w$", P())]
            + list(tp_rules or []))


def shard_moe_params(params: Any, mesh: Mesh, ep_axis: str = "ep") -> Any:
    """Place a MoE param tree per ``moe_ep_rules`` (single source of truth
    with the exported rule table)."""
    from nezha_tpu.parallel.gspmd import param_specs_from_rules

    specs = param_specs_from_rules(params, moe_ep_rules(ep_axis))
    return jax.tree_util.tree_map(
        lambda leaf, s: jax.device_put(leaf, NamedSharding(mesh, s)),
        params, specs)


def dryrun_moe_step(mesh: Mesh, n_experts: int, ep_axis: str = "ep",
                    dp_axis: str = "dp") -> float:
    """One expert-parallel MoE train step on tiny shapes (driver dry-run):
    dp-sharded tokens x ep-sharded experts, full fwd+bwd+SGD update."""
    cfg = MoEConfig(d_model=16, d_ff=32, num_experts=n_experts)
    layer = MoE(cfg)
    variables = layer.init(jax.random.PRNGKey(0))
    params = shard_moe_params(variables["params"], mesh, ep_axis)

    dp = mesh.shape.get(dp_axis, 1)
    x = jax.random.normal(jax.random.PRNGKey(1), (2 * dp, 8, cfg.d_model))
    x = jax.device_put(x, NamedSharding(mesh, P(dp_axis)))

    def loss_fn(p, x):
        y, st = layer.apply({"params": p, "state": {}}, x)
        return jnp.mean((y - x) ** 2) + cfg.aux_loss_weight * st["aux_loss"]

    @jax.jit
    def step(p, x):
        loss, grads = jax.value_and_grad(loss_fn)(p, x)
        p = jax.tree_util.tree_map(lambda w, g: w - 1e-2 * g, p, grads)
        return loss, p

    loss, params = step(params, x)
    jax.block_until_ready(loss)
    return float(loss)
