"""shard_map / axis_size shims across JAX versions.

Newer JAX enforces static "varying-over-mesh-axes" (vma) inference; outputs
produced by all_gather are mathematically replicated but the checker can't
prove it, so we disable the check here (kwarg name differs across versions).

``lax.axis_size`` only exists on newer JAX; older versions (0.4.x) spell
the same static lookup ``lax.psum(1, axis_name)`` — under shard_map a
constant-int psum folds to a plain Python int at trace time, so call
sites may still use the result in shape arithmetic and ``range()``.
"""

import inspect

from jax import lax

try:  # jax >= 0.6-ish exposes it at top level
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

_kwargs = {}
_sig_params = inspect.signature(_shard_map).parameters
if "check_vma" in _sig_params:
    _kwargs = {"check_vma": False}
elif "check_rep" in _sig_params:  # pragma: no cover
    _kwargs = {"check_rep": False}


def shard_map(f, *, mesh, in_specs, out_specs):
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **_kwargs)


if hasattr(lax, "axis_size"):
    def axis_size(axis_name):
        """Number of devices along ``axis_name`` (static int)."""
        return lax.axis_size(axis_name)
else:  # pragma: no cover — exercised on jax < 0.6 installs
    def axis_size(axis_name):
        """Number of devices along ``axis_name``. ``psum`` of a constant
        int folds to a plain Python int at trace time, so this is the
        same static value newer JAX's ``lax.axis_size`` returns."""
        return lax.psum(1, axis_name)


__all__ = ["shard_map", "axis_size"]
