"""shard_map shim across JAX versions.

Newer JAX enforces static "varying-over-mesh-axes" (vma) inference; outputs
produced by all_gather are mathematically replicated but the checker can't
prove it, so we disable the check here (kwarg name differs across versions).
"""

import inspect

try:  # jax >= 0.6-ish exposes it at top level
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

_kwargs = {}
_sig_params = inspect.signature(_shard_map).parameters
if "check_vma" in _sig_params:
    _kwargs = {"check_vma": False}
elif "check_rep" in _sig_params:  # pragma: no cover
    _kwargs = {"check_rep": False}


def shard_map(f, *, mesh, in_specs, out_specs):
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **_kwargs)


__all__ = ["shard_map"]
