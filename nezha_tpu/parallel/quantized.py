"""Quantized (int8-on-the-wire) gradient collectives.

The reference's collective layer moves fp32 gradients over NCCL rings
(SURVEY.md §2 `pkg/nccl`). On TPU the equivalent wire is ICI, and the
bandwidth knob the hardware gives us is *payload width*: EQuARX-style
block-scaled int8 all-reduce (PAPERS.md, arxiv 2506.17615) moves ~4x fewer
bytes per hop at gradient-compression accuracy that is established to be
training-neutral for DP.

XLA's ``psum`` cannot requantize per hop, so the quantized all-reduce is
composed from two collectives the compiler *can* schedule on ICI, mirroring
the classic ring decomposition all_reduce = reduce_scatter + all_gather:

1. **reduce phase** — each rank block-quantizes its gradient, splits it into
   ``n`` rank-chunks and ``all_to_all``s them (int8 + per-block scales on
   the wire); every rank dequantizes the ``n`` received chunks and sums them
   in fp32, ending with the exact-summed shard it owns.
2. **broadcast phase** — the owned shard is requantized and ``all_gather``ed
   (int8 + scales on the wire again), then dequantized.

Per element the wire carries ``1 + 4/block`` bytes per phase instead of 4,
a ~3.9x bus-bandwidth win at block=512. Accumulation stays fp32 (only the
wire is int8), so error is two rounding stages bounded by ``amax/127`` per
block — the property tests pin this down.

Small leaves (biases, norm scales) skip quantization entirely: below
``min_numel`` the scale overhead and accuracy risk buy nothing, so they ride
a plain ``pmean`` — same policy as EQuARX's size cutoff.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

# The quantize/dequantize core lives in nezha_tpu.ops.quant — ONE
# audited implementation shared with the int8 KV-cache path
# (serve/slots.py), regression-pinned bit-identical to the
# pre-extraction in-module version. The private aliases keep this
# module's internal call sites (and any external ones) stable.
from nezha_tpu.ops.quant import QMAX as _QMAX
from nezha_tpu.parallel._compat import axis_size
from nezha_tpu.ops.quant import dequantize as _dequantize
from nezha_tpu.ops.quant import quantize_blocks as _quantize_blocks

# Leaves below this ride the exact path (EQuARX-style size cutoff); shared
# default for quantized_all_reduce_mean and its telemetry accounting.
DEFAULT_MIN_NUMEL = 4096


def quantize_roundtrip(x: jax.Array, block: int = 512) -> jax.Array:
    """Quantize-dequantize ``x`` once (test/diagnostic helper): the error a
    single wire hop introduces."""
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    pad = (-flat.size) % block
    flat = jnp.pad(flat, (0, pad))
    q, s = _quantize_blocks(flat, block)
    out = _dequantize(q, s).reshape(-1)[:x.size].reshape(x.shape)
    return out.astype(x.dtype)


def should_quantize(leaf: jax.Array, min_numel: int) -> bool:
    """The size/dtype cutoff policy, shared by the dp and zero1 paths:
    quantize float leaves of at least ``min_numel`` elements; everything
    else rides the exact collective."""
    return bool(jnp.issubdtype(leaf.dtype, jnp.floating)
                and leaf.size >= min_numel)


def split_quantized_leaves(tree: Any, min_numel: int):
    """Partition ``tree``'s leaves by the wire cutoff: ``(quantized,
    exact)`` — the one classification the dp/zero1 collectives AND their
    telemetry accounting share, so payload tables can never disagree with
    what actually rides the int8 wire."""
    quant, exact = [], []
    for leaf in jax.tree_util.tree_leaves(tree):
        (quant if should_quantize(leaf, min_numel) else exact).append(leaf)
    return quant, exact


def _qar_mean(x: jax.Array, axis_name: str, block: int) -> jax.Array:
    """int8-wire all-reduce-mean of one array (inside shard_map): the ring
    decomposition reduce_scatter + all_gather, each phase quantized."""
    n = axis_size(axis_name)
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    per = -(-flat.size // (n * block)) * block  # chunk per rank, block-aligned
    flat = jnp.pad(flat, (0, n * per - flat.size))
    owned = quantized_reduce_scatter_mean(flat, axis_name, block)
    out = quantized_all_gather(owned, axis_name, block)[:x.size]
    return out.reshape(x.shape).astype(x.dtype)


def quantized_all_reduce_mean(tree: Any, axis_name: str, block: int = 512,
                              min_numel: int = DEFAULT_MIN_NUMEL) -> Any:
    """Tree-wide gradient mean over ``axis_name`` with int8 payloads for
    every float leaf of at least ``min_numel`` elements; small or integer
    leaves take the exact ``pmean`` path."""
    def one(g):
        if not should_quantize(g, min_numel):
            return lax.pmean(g, axis_name)
        return _qar_mean(g, axis_name, block)

    return jax.tree_util.tree_map(one, tree)


def quantized_reduce_scatter_mean(flat: jax.Array, axis_name: str,
                                  block: int = 512) -> jax.Array:
    """int8-wire mean reduce-scatter: ``flat`` [world*chunk] fp32 -> this
    rank's mean chunk [chunk] (the ZeRO-1 gradient phase; ZeRO++'s qgZ in
    XLA-collective form). Row padding to the block size happens internally,
    so callers keep the exact-path layout (chunk = size/world)."""
    n = axis_size(axis_name)
    rows = jnp.asarray(flat, jnp.float32).reshape(n, -1)
    chunk = rows.shape[1]
    rows = jnp.pad(rows, ((0, 0), (0, (-chunk) % block)))
    q, s = _quantize_blocks(rows, block)
    qt = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=True)
    st = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=True)
    owned = jnp.sum(_dequantize(qt, st), axis=0) / n
    return owned.reshape(-1)[:chunk]


def quantized_all_gather(chunk_arr: jax.Array, axis_name: str,
                         block: int = 512) -> jax.Array:
    """int8-wire tiled all-gather of a per-rank [chunk] array ->
    [world*chunk] fp32 (the ZeRO-1 weight/update broadcast phase)."""
    n = axis_size(axis_name)
    chunk = chunk_arr.size
    x = jnp.pad(jnp.asarray(chunk_arr, jnp.float32).reshape(-1),
                (0, (-chunk) % block))
    q, s = _quantize_blocks(x.reshape(1, -1), block)
    qg = lax.all_gather(q, axis_name, axis=0, tiled=True)
    sg = lax.all_gather(s, axis_name, axis=0, tiled=True)
    return _dequantize(qg, sg).reshape(n, -1)[:, :chunk].reshape(-1)


def wire_payload_bytes(numel: int, block: int = 512) -> int:
    """Bytes of the int8 wire form of ``numel`` fp32 elements for ONE
    quantized phase: block-padded int8 data plus one fp32 scale per block
    — the telemetry payload accounting (vs 4 bytes/element exact). See
    ``quantized_wire_bytes`` for the full two-phase ring-bus total."""
    padded = -(-numel // block) * block
    return padded + (padded // block) * 4


def quantized_wire_bytes(numel: int, block: int = 512, world: int = 8) -> int:
    """Bytes one rank puts on the wire for one quantized all-reduce of
    ``numel`` fp32 elements (both phases, (n-1)/n of the payload leaves the
    chip) — the accounting mirror of ``allreduce_bus_bandwidth``."""
    per = -(-numel // (world * block)) * block
    payload = world * per * 1 + world * (per // block) * 4  # int8 + scales
    return int(2 * payload * (world - 1) / world)
