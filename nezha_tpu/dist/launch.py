"""Bridging the control plane to jax.distributed (multi-host TPU).

The reference's coordinator broadcast an NCCL unique id so every rank could
build the communicator (SURVEY.md §3 call stack 1). The TPU-native
equivalent blob is the jax.distributed coordination address: rank 0 decides
it, the coordinator KV store carries it, and every process calls
``jax.distributed.initialize`` with it — after which XLA owns the
collectives over ICI/DCN and no further host involvement is needed on the
data path.
"""

from __future__ import annotations

import socket
from typing import Optional

from nezha_tpu.dist.coordinator import ProcessGroup


def _my_ip() -> str:
    # The address other hosts can reach us at: the source IP of a UDP
    # "connection" to a public address (no packet is actually sent).
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def initialize_jax_distributed(group: ProcessGroup,
                               coord_port: int = 8476,
                               timeout_s: Optional[float] = 120.0) -> None:
    """Initialize jax.distributed across the group's processes.

    Rank 0 advertises ``<its-ip>:coord_port`` through the coordinator's KV
    store; every rank then enters ``jax.distributed.initialize`` with the
    same address, its coordinator-assigned rank, and the group size.
    """
    import jax

    if group.rank == 0:
        addr = f"{_my_ip()}:{coord_port}"
        group.put("__jax_coord_addr", addr.encode())
    addr = group.get("__jax_coord_addr", timeout_s).decode()
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=group.world_size,
        process_id=group.rank,
    )
