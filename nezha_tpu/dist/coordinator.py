"""Python API over the native coordinator (csrc/coordinator.cpp).

Mirrors the reference's gRPC coordinator semantics (SURVEY.md §3 call
stack 1: "dial gRPC coordinator (rank/world rendezvous, NCCL unique-id
exchange)"): processes ``join()`` a coordinator address, receive a rank,
then use the group for barriers, KV-based topology exchange, and failure
detection. All blocking native calls release the GIL, so the heartbeat
and any Python-side work proceed concurrently.
"""

from __future__ import annotations

import ctypes
import random
import time
from typing import List, Optional

from nezha_tpu import faults, obs
from nezha_tpu.runtime.native import load_library


class CoordinatorError(RuntimeError):
    pass


class JoinTimeout(CoordinatorError):
    """:func:`join` exhausted its retry budget without a successful
    rendezvous. Typed (and a CoordinatorError, so existing handlers
    still catch it) so supervisors can tell "the coordinator never came
    up" from in-band control-plane failures."""


class Coordinator:
    """The rendezvous server. Run one instance per job (typically on the
    rank-0 host, like the reference's coordinator process)."""

    def __init__(self, world_size: int, port: int = 0,
                 heartbeat_timeout_s: float = 10.0):
        self._lib = load_library()
        self._h = self._lib.nz_coord_start(
            int(port), int(world_size), int(heartbeat_timeout_s * 1000))
        if not self._h:
            raise CoordinatorError(
                self._lib.nz_last_error().decode() or "coordinator start failed")
        self.world_size = world_size
        self.port = self._lib.nz_coord_port(self._h)

    def stop(self) -> None:
        if self._h:
            self._lib.nz_coord_stop(self._h)
            self._h = None

    def __enter__(self) -> "Coordinator":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class ProcessGroup:
    """A joined member of the world: rank, world size, and control-plane
    primitives (barrier / put / get / broadcast / all_gather / failures)."""

    def __init__(self, handle, lib):
        self._h = handle
        self._lib = lib
        self.rank = lib.nz_client_rank(handle)
        self.world_size = lib.nz_client_world(handle)
        self._last_failed: List[int] = []  # dedup for failure-event spans

    def _round(self, tag: str) -> int:
        """This rank's collective round for ``tag``. KV keys are never
        deleted, so repeated broadcast/all_gather calls must write fresh
        keys. The counter is a server-side fetch-and-increment keyed by
        (tag, rank): a crashed-and-rejoined rank resumes at the world's
        current round instead of restarting from 0. Like any collective,
        every rank must make these calls in the same order."""
        return self.incr(f"__round/{tag}/{self.rank}")

    def incr(self, key: str) -> int:
        """Server-side atomic fetch-and-increment; returns previous value."""
        v = self._lib.nz_client_incr(self._h, key.encode())
        if v < 0:
            raise CoordinatorError(self._lib.nz_last_error().decode())
        return v

    # ---------------------------------------------------------------- KV
    def put(self, key: str, value: bytes) -> None:
        r = self._lib.nz_client_put(
            self._h, key.encode(), value, len(value))
        if r != 0:
            raise CoordinatorError(self._lib.nz_last_error().decode())

    def get(self, key: str, timeout_s: Optional[float] = None) -> bytes:
        """Blocks until `key` exists (or timeout)."""
        timeout_ms = -1 if timeout_s is None else int(timeout_s * 1000)
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.nz_client_get(
                self._h, key.encode(), buf, cap, timeout_ms)
            if n < 0:
                raise CoordinatorError(self._lib.nz_last_error().decode())
            if n <= cap:
                return buf.raw[:n]
            cap = n  # value larger than buffer: retry exactly-sized

    # ----------------------------------------------------------- control
    def barrier(self, timeout_s: Optional[float] = None) -> None:
        timeout_ms = -1 if timeout_s is None else int(timeout_s * 1000)
        with obs.span("dist.barrier", rank=self.rank):
            if self._lib.nz_client_barrier(self._h, timeout_ms) != 0:
                raise CoordinatorError(self._lib.nz_last_error().decode())

    def broadcast(self, value: Optional[bytes], root: int = 0,
                  timeout_s: Optional[float] = None,
                  tag: str = "bcast") -> bytes:
        """Root puts, everyone gets. Collective: all ranks must call, in
        the same order relative to other collectives with the same tag."""
        key = f"__{tag}/{self._round(tag)}/{root}"
        if self.rank == root:
            if value is None:
                raise ValueError("root must provide a value")
            self.put(key, value)
        return self.get(key, timeout_s)

    def all_gather(self, value: bytes, timeout_s: Optional[float] = None,
                   tag: str = "gather") -> List[bytes]:
        """Each rank contributes a blob; returns all blobs rank-ordered.
        Collective: all ranks must call, in the same order."""
        rnd = self._round(tag)
        self.put(f"__{tag}/{rnd}/{self.rank}", value)
        return [self.get(f"__{tag}/{rnd}/{r}", timeout_s)
                for r in range(self.world_size)]

    def failed_ranks(self) -> List[int]:
        """Ranks the coordinator considers dead: dropped their connection
        without leaving, or silent past the heartbeat timeout. Heartbeat
        loss is a COUNTED, span-recorded event here (the reacting layer
        — Trainer, supervisor — decides whether it is fatal), not a bare
        exception."""
        cap = max(self.world_size, 1)
        arr = (ctypes.c_int32 * cap)()
        n = self._lib.nz_client_failed(self._h, arr, cap)
        if n < 0:
            raise CoordinatorError(self._lib.nz_last_error().decode())
        failed = sorted(arr[i] for i in range(min(n, cap)))
        if failed != self._last_failed:
            # Heartbeat-failure EVENT (zero-duration span + counter),
            # recorded once per transition — the poll itself runs every
            # few steps. Newly-dead ranks only; a rank that rejoins and
            # dies again counts again.
            newly = [r for r in failed if r not in self._last_failed]
            self._last_failed = failed
            if newly:
                obs.counter("dist.heartbeat_lost_total").inc(len(newly))
                with obs.span("dist.failure", rank=self.rank,
                              failed=failed):
                    pass
        return failed

    # ---------------------------------------------------------- lifecycle
    def leave(self) -> None:
        """Graceful departure — not counted as a failure."""
        if self._h:
            with obs.span("dist.leave", rank=self.rank):
                self._lib.nz_client_leave(self._h)
                self._lib.nz_client_close(self._h)
            self._h = None

    def close(self) -> None:
        """Abrupt close — surviving ranks will see this rank as failed."""
        if self._h:
            self._lib.nz_client_close(self._h)
            self._h = None

    def __enter__(self) -> "ProcessGroup":
        return self

    def __exit__(self, *exc) -> None:
        self.leave()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def join(host: str, port: int, rank_hint: int = -1,
         timeout_s: float = 60.0,
         heartbeat_interval_s: float = 2.0,
         attempt_timeout_s: float = 10.0,
         backoff_base_s: float = 0.25,
         backoff_max_s: float = 5.0,
         jitter: float = 0.5) -> ProcessGroup:
    """Join the coordinator at host:port; returns a ProcessGroup with an
    assigned rank.

    The dial is a bounded RETRY ENVELOPE, not a single attempt: each
    native connect gets at most ``attempt_timeout_s`` (the native layer
    already rides out refused connections inside that window — launch
    skew), failures back off exponentially from ``backoff_base_s`` up to
    ``backoff_max_s`` with ±``jitter`` fractional randomization (OS
    entropy) so a mass-restarted world doesn't redial in lockstep,
    and once ``timeout_s`` is spent the typed :class:`JoinTimeout`
    surfaces. Every failed attempt counts into
    ``dist.join_retries_total`` (pre-registered here, with
    ``dist.heartbeat_lost_total``, so any joined run's summary carries
    both — the schema tools/check_telemetry_schema.py pins).
    """
    lib = load_library()
    obs.counter("dist.join_retries_total")
    obs.counter("dist.heartbeat_lost_total")
    # OS-entropy RNG: pid-derived seeds collapse in containers (every
    # rank is pid 1 dialing the same port), which would re-correlate
    # the very redial herd the jitter is here to break up.
    rng = random.SystemRandom()
    deadline = time.monotonic() + timeout_s
    attempt = 0
    last_err: Optional[BaseException] = None
    with obs.span("dist.join", host=host, port=port) as sp:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise JoinTimeout(
                    f"could not join coordinator at {host}:{port} within "
                    f"{timeout_s:.1f}s ({attempt} failed attempt(s)"
                    f"{f'; last: {last_err}' if last_err else ''})") \
                    from last_err
            try:
                faults.point("dist.join")
                h = lib.nz_client_connect(
                    host.encode(), int(port), int(rank_hint),
                    int(min(remaining, attempt_timeout_s) * 1000),
                    int(heartbeat_interval_s * 1000))
                if not h:
                    raise CoordinatorError(
                        lib.nz_last_error().decode() or "join failed")
            except (CoordinatorError, faults.InjectedFault) as e:
                attempt += 1
                last_err = e
                obs.counter("dist.join_retries_total").inc()
                sp.set(retries=attempt)
                delay = min(backoff_max_s,
                            backoff_base_s * (2.0 ** (attempt - 1)))
                delay *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
                # Never abandon budget early: when the backoff would
                # overrun the deadline, shrink it so a final dial slice
                # (up to 1s) remains — a coordinator coming up late in
                # the window still gets attempted before JoinTimeout.
                reserve = min(attempt_timeout_s, 1.0)
                delay = min(delay,
                            deadline - time.monotonic() - reserve)
                if delay > 0:
                    time.sleep(delay)
                continue
            group = ProcessGroup(h, lib)
            sp.set(rank=group.rank, world=group.world_size,
                   retries=attempt)
            return group
