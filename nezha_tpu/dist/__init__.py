"""Multi-process coordination — the reference's gRPC-coordinator role
(SURVEY.md §1 "Distributed runtime", §2).

The wire implementation is native C++ (csrc/coordinator.cpp) loaded via
ctypes; this package is the Python face: rendezvous into a ``ProcessGroup``
with rank/world, a key-value store for topology exchange (the job NCCL
unique-id broadcast did in the reference — here it carries the
jax.distributed / PJRT coordination address), barriers, broadcast /
all-gather of small host blobs, and heartbeat-based failure detection.

Device-side collectives stay in ``nezha_tpu.parallel`` (XLA over ICI);
this layer is strictly host-side control plane.
"""

from nezha_tpu.dist.coordinator import (
    Coordinator,
    CoordinatorError,
    JoinTimeout,
    ProcessGroup,
    join,
)
from nezha_tpu.dist.launch import initialize_jax_distributed

__all__ = [
    "Coordinator",
    "CoordinatorError",
    "JoinTimeout",
    "ProcessGroup",
    "join",
    "initialize_jax_distributed",
]
