"""Shared CLI helpers (nezha-train / nezha-generate / nezha-export)."""

from __future__ import annotations

import sys


def setup_jax(args) -> None:
    """The common jax preamble for every CLI entry: optional platform
    override (must precede backend init), then the same-machine persistent
    compile cache (re-runs of a config skip the 20-40 s TPU first
    compile). One place so the entries cannot drift."""
    import jax

    if getattr(args, "platform", None):
        jax.config.update("jax_platforms", args.platform)
    from nezha_tpu.utils import enable_persistent_compile_cache
    enable_persistent_compile_cache()


def restore_variables_any(ckpt_dir: str, model, optimizer):
    """Model variables from EITHER checkpoint format a `nezha-train` run
    may have written: dense npz (single/dp/sp) or per-shard
    (zero1/gspmd/pp). The sgd-or-whatever template trick: restore walks
    TEMPLATE leaves only, and every optimizer's state carries ``step`` at
    the same path, so a minimal-optimizer template reads any checkpoint.
    Raises SystemExit when neither format is present."""
    import jax

    from nezha_tpu.train import checkpoint as ckpt
    from nezha_tpu.train import sharded_checkpoint as sckpt
    from nezha_tpu.train.loop import init_train_state

    template = init_train_state(model, optimizer, jax.random.PRNGKey(0))
    if _is_graph_layout(ckpt_dir, ckpt):
        # Graph-engine trainers write {"params", ...optimizer slots}
        # (AdamW: mu/nu/step; momentum: vel) with module-layout params.
        # A params-only template restores just what the callers consume —
        # restore ignores npz keys the template doesn't name, so the
        # optimizer slots are never reconstructed.
        p = template["variables"]["params"]
        g_restored, step = ckpt.try_restore(ckpt_dir, {"params": p})
        print(f"restored step {step} (graph-engine layout) from "
              f"{ckpt_dir}", file=sys.stderr)
        return {"params": g_restored["params"], "state": {}}
    restored, step = ckpt.try_restore(ckpt_dir, template)
    if restored is None:
        restored, step = sckpt.try_restore_sharded(ckpt_dir, template)
    if restored is None:
        raise SystemExit(f"no checkpoint (npz or sharded) in {ckpt_dir}")
    print(f"restored step {step} from {ckpt_dir}", file=sys.stderr)
    return restored["variables"]


def _is_graph_layout(ckpt_dir: str, ckpt) -> bool:
    """True when the newest npz checkpoint carries graph-engine keys.

    Reads only the zip directory (``z.files``), not the arrays — layout
    dispatch must not cost a full decompress of a GB-scale checkpoint."""
    import os

    import numpy as np

    step = ckpt.latest_step(ckpt_dir)
    if step is None:
        return False
    path = os.path.join(ckpt_dir, f"step_{step:08d}.npz")
    with np.load(path) as z:
        return not any(k.startswith("variables/") for k in z.files)


def ckpt_has_scan_trunk(ckpt_dir: str) -> bool:
    """True when the newest checkpoint in ``ckpt_dir`` (either format)
    stores trunk params in the scan layout (``h_scan`` for GPT-2,
    ``layers_scan`` for BERT — a ``--scan-layers`` training run). Lets
    nezha-generate/nezha-export rebuild the model with the matching
    layout instead of failing to match ``h0..hN`` template leaves. Reads
    directory listings / zip indexes only, never the arrays."""
    import os
    from pathlib import Path

    import numpy as np

    from nezha_tpu.train import checkpoint as ckpt

    def scan_key(k: str) -> bool:
        return any(f"/{s}/" in k or k.startswith(f"{s}/")
                   for s in ("h_scan", "layers_scan"))

    step = ckpt.latest_step(ckpt_dir)
    if step is not None:
        path = os.path.join(ckpt_dir, f"step_{step:08d}.npz")
        with np.load(path) as z:
            return any(scan_key(k) for k in z.files)
    # Sharded layout: leaf paths live in the meta_p*.json indexes. Use
    # the sharded latest_step (honors COMPLETE markers) so detection
    # looks at the SAME checkpoint restore will read — a torn newer dir
    # must not flip the layout decision.
    from nezha_tpu.train import sharded_checkpoint as sckpt

    sstep = sckpt.latest_step(ckpt_dir)
    if sstep is None:
        return False
    sdir = Path(ckpt_dir) / f"step_{sstep:08d}.sharded"
    for meta in sdir.glob("meta_p*.json"):
        try:
            text = meta.read_text()
        except OSError:
            continue
        # Each meta names every leaf path prefix.
        return "h_scan" in text or "layers_scan" in text
    return False


def gpt2_for_preset(preset: str, *, scan_layers: bool = False):
    """THE preset -> GPT2 model mapping for every inference CLI
    (`nezha-generate`, `nezha-serve`, `nezha-reshard` — one site, so
    the serve/reshard/load paths can never build models with drifting
    configs or numerics): full decodes bf16 (the checkpoint's training
    policy), tiny fp32."""
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config
    from nezha_tpu.tensor import bf16_policy

    if preset == "full":
        return GPT2(GPT2Config(scan_layers=scan_layers),
                    policy=bf16_policy())
    from nezha_tpu.cli.train import TINY_GPT2_KW
    return GPT2(GPT2Config(**TINY_GPT2_KW, scan_layers=scan_layers))


def load_gpt2_for_inference(args):
    """(model, variables) for the inference CLIs (`nezha-generate`,
    `nezha-serve`) from any of their three weight sources: --hf-dir
    (transformers checkpoint), --ckpt-dir (either nezha-train format,
    scan-layers auto-detected and unstacked ONCE to the unrolled decode
    layout), or --random-init. Policies mirror nezha-train's presets:
    full decodes bf16, tiny fp32 — greedy decode must run the same
    compute numerics as the checkpoint's training run."""
    import jax

    from nezha_tpu.models.gpt2 import GPT2

    if getattr(args, "hf_dir", None):
        import transformers

        hf = transformers.GPT2LMHeadModel.from_pretrained(args.hf_dir)
        from nezha_tpu.models.convert import gpt2_from_hf
        return gpt2_from_hf(hf)

    # --scan-layers checkpoints store the trunk under h_scan with a
    # leading layer dim; restore with the matching template, then
    # unstack ONCE to the unrolled layout for decode — the scan model's
    # cache path would otherwise slice every stacked param per decode
    # step (doubling param traffic in the latency-bound loop).
    scan = False
    if getattr(args, "ckpt_dir", None):
        scan = ckpt_has_scan_trunk(args.ckpt_dir)
    model = gpt2_for_preset(args.model_preset, scan_layers=scan)
    if getattr(args, "ckpt_dir", None):
        # Either checkpoint format: dense npz OR the per-shard layout
        # that zero1/gspmd/pp training writes. Generation needs the
        # variables leaf only (optimizer state is ignored); no point
        # materializing a random init just to overwrite it.
        from nezha_tpu import optim
        variables = restore_variables_any(args.ckpt_dir, model,
                                          optim.sgd(0.1))
        if scan:
            import dataclasses as _dc

            from nezha_tpu.models.gpt2 import unstack_layer_params
            variables = {
                "params": unstack_layer_params(
                    variables["params"], model.cfg.num_layers),
                "state": variables.get("state", {})}
            model = GPT2(_dc.replace(model.cfg, scan_layers=False),
                         policy=model.policy)
    else:
        variables = model.init(jax.random.PRNGKey(args.seed))
    return model, variables


def resolve_eos_id(explicit, tokenizer, vocab: int, flag: str = "--eos-id"):
    """ONE EOS policy for the inference CLIs (generate + serve): an
    explicit flag wins and is validated hard (out-of-vocab = user
    error); otherwise the loaded tokenizer's natural EOS, which quietly
    disables (stderr note) when it falls outside the model vocab — a
    big-vocab tokenizer on a small model must not break decoding that
    worked before EOS support. Negative values force-disable."""
    if explicit is not None and explicit >= vocab:
        raise SystemExit(f"{flag} {explicit} outside the model vocab "
                         f"[0, {vocab})")
    eos_id = explicit
    if eos_id is None and tokenizer is not None:
        from nezha_tpu.data.tokenizer import default_eos_id
        eos_id = default_eos_id(tokenizer)
        if eos_id is not None and eos_id >= vocab:
            print(f"note: tokenizer EOS id {eos_id} is outside this "
                  f"model's vocab [0, {vocab}); EOS stopping disabled",
                  file=sys.stderr)
            eos_id = None
    if eos_id is not None and eos_id < 0:
        eos_id = None
    return eos_id
