"""`nezha-reshard` — re-lay a training checkpoint for the serve mesh.

Training topologies (zero1/dp replicas, gspmd meshes, plain npz) lay
parameters out for throughput; the sharded serve engine
(``nezha-serve --mesh M``) needs them Megatron head/feature-sharded
over a 1xM ``tp`` mesh. This entry runs that redistribution standalone
(``nezha-serve --mesh M --ckpt-dir ...`` invokes the same path
implicitly at startup):

- loads the newest (or ``--step``) training checkpoint — dense npz
  (CRC32-verified per leaf against the PR 4 embedded manifest, streamed
  one leaf at a time so host memory stays bounded by the largest leaf)
  or the per-shard zero1/gspmd format (each serve-device slice
  assembled from exactly the stored shards overlapping it);
- commits every leaf to its serve-mesh ``NamedSharding``;
- with ``--out DIR``, writes the re-laid state as a serve-topology
  sharded checkpoint (readable by this tool or ``nezha-serve`` on any
  later mesh size), and with ``--verify`` reads it back and proves the
  round trip bitwise.

Corruption is a TYPED refusal (``ReshardError``, exit 1) — a CRC
mismatch or missing leaf must never become served garbage. RUNBOOK §9
documents the `serve.reshard` chaos drill.

    nezha-reshard --ckpt-dir runs/gpt2 --mesh 4 --model-preset tiny \
        --out /ckpts/gpt2.serve4 --verify
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nezha-reshard", description=__doc__)
    p.add_argument("--ckpt-dir", required=True,
                   help="training checkpoint dir (nezha-train npz or "
                        "sharded format)")
    p.add_argument("--mesh", type=int, required=True,
                   help="serve mesh size M (1xM tensor-parallel; "
                        "num_heads must divide by it)")
    p.add_argument("--model-preset", choices=["full", "tiny"],
                   default="full")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: newest)")
    p.add_argument("--out", default=None,
                   help="write the re-laid state as a serve-topology "
                        "sharded checkpoint here")
    p.add_argument("--verify", action="store_true",
                   help="with --out: read the written checkpoint back "
                        "and prove the round trip bitwise")
    p.add_argument("--json", action="store_true",
                   help="print the reshard report as JSON")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu)")
    return p


def run(args) -> int:
    from nezha_tpu.cli.common import setup_jax
    setup_jax(args)
    import jax

    from nezha_tpu.cli.common import gpt2_for_preset
    from nezha_tpu.parallel.mesh import make_mesh
    from nezha_tpu.serve.sharded import (ReshardError, reshard_checkpoint,
                                         save_serve_checkpoint,
                                         verify_roundtrip)

    if args.mesh < 1:
        raise SystemExit(f"--mesh must be >= 1, got {args.mesh}")
    ndev = len(jax.devices())
    if args.mesh > ndev:
        raise SystemExit(
            f"--mesh {args.mesh} but only {ndev} device(s) visible")
    # The serve model is always the unrolled decode layout;
    # reshard_checkpoint detects a scan-trunk checkpoint from its
    # leaves and unstacks it.
    model = gpt2_for_preset(args.model_preset)
    if model.cfg.num_heads % args.mesh:
        # Param placement alone would succeed (feature axes divide),
        # but no engine can serve the result — producing the artifact
        # would be a trap, so refuse up front as the help text says.
        raise SystemExit(
            f"--mesh {args.mesh}: num_heads={model.cfg.num_heads} not "
            f"divisible by the mesh — no engine can serve this "
            f"topology (K/V pools shard on the head axis)")
    mesh = make_mesh({"tp": args.mesh}, devices=jax.devices()[:args.mesh])
    try:
        variables, step = reshard_checkpoint(args.ckpt_dir, model, mesh,
                                             step=args.step)
    except ReshardError as e:
        print(f"nezha-reshard: REFUSED: {e}", file=sys.stderr)
        return 1
    report = {"ckpt_dir": args.ckpt_dir, "step": step,
              "mesh_devices": args.mesh}
    total = shard = 0
    dev0 = mesh.devices.flat[0]
    for leaf in jax.tree_util.tree_leaves(variables):
        if isinstance(leaf, jax.Array):
            total += leaf.nbytes
            shard += sum(s.data.nbytes for s in leaf.addressable_shards
                         if s.device == dev0)
    report["params_bytes"] = total
    report["params_bytes_per_device"] = shard
    if args.out:
        path = save_serve_checkpoint(args.out, variables, step)
        report["out"] = path
        if args.verify:
            bad = verify_roundtrip(args.out, variables, step)
            report["roundtrip_ok"] = not bad
            if bad:
                print(f"nezha-reshard: round-trip mismatch on "
                      f"{len(bad)} leaf/leaves: {bad[:5]}",
                      file=sys.stderr)
                return 1
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        rt = (" round-trip OK" if report.get("roundtrip_ok")
              else "")
        print(f"resharded step {step} onto a 1x{args.mesh} mesh: "
              f"{total / 2**20:.2f} MiB total, "
              f"{shard / 2**20:.2f} MiB/device"
              + (f" -> {report['out']}" if args.out else "") + rt)
    return 0


def main(argv=None) -> int:
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
