"""`nezha-generate` — KV-cache text generation from a trained checkpoint.

The inference-side counterpart of `nezha-train` (SURVEY.md §1 CLI row):
restore a GPT-2 checkpoint the trainer wrote (or Hugging Face weights via
models/convert.py) and decode with the cached single-position path
(models/generate.py: jit-compiled prefill + lax.scan decode — no Python
loop over positions, TPU-friendly static shapes).

Prompts: token id lists (`--prompt-tokens 15496,995`), a binary token file
(`--prompt-file`, uint16/int32), or raw text (`--prompt`). Text prompts
encode through `--tokenizer DIR` (real GPT-2 BPE / BERT WordPiece vocab
files, network-free — data/tokenizer.py; defaults to --hf-dir's shipped
tokenizer when present) or fall back to byte-level (the vocab-256
encoding `data/pack.py` trains with). Output decodes back to text the
same way.

    nezha-generate --ckpt-dir runs/gpt2 --prompt-tokens 1,2,3 \
        --max-new-tokens 32 --temperature 0.8 --top-k 40
    nezha-generate --hf-dir /ckpts/gpt2 --prompt "The meaning of life" \
        --temperature 0.8 --top-p 0.95   # real BPE text in and out
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nezha-generate", description=__doc__)
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--ckpt-dir",
                     help="checkpoint dir written by nezha-train "
                          "(--config gpt2_124m)")
    src.add_argument("--hf-dir",
                     help="Hugging Face GPT2LMHeadModel directory "
                          "(offline; needs the `transformers` package)")
    src.add_argument("--random-init", action="store_true",
                     help="fresh random weights (smoke/benchmark runs)")
    p.add_argument("--model-preset", choices=["full", "tiny"], default="full",
                   help="must match the preset the checkpoint was trained "
                        "with (mirrors nezha-train)")
    p.add_argument("--prompt-tokens", default=None,
                   help="comma-separated token ids, e.g. 15496,995")
    p.add_argument("--prompt", default=None,
                   help="raw text; encoded with --tokenizer when given, "
                        "else byte-level (vocab 256 — the encoding "
                        "data/pack.py trains with); output decodes back "
                        "to text")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer directory (vocab.json+merges.txt -> "
                        "GPT-2 BPE, vocab.txt -> WordPiece; see "
                        "data/tokenizer.py). Defaults to --hf-dir when "
                        "that directory ships tokenizer files, so HF "
                        "checkpoints generate real text out of the box")
    p.add_argument("--prompt-file", default=None,
                   help="binary token file (uint16 unless --prompt-i32)")
    p.add_argument("--prompt-i32", action="store_true")
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=1.0,
                   help="0 = greedy argmax")
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None,
                   help="nucleus sampling: keep the smallest prefix of "
                        "descending-prob tokens with mass >= p")
    p.add_argument("--eos-id", type=int, default=None,
                   help="stop rows that emit this token (later positions "
                        "pad with it); defaults to the tokenizer's EOS "
                        "(<|endoftext|> / [SEP]) when one is loaded, "
                        "-1 disables even then")
    p.add_argument("--num-samples", type=int, default=1,
                   help="decode N sampled continuations of ONE prompt in "
                        "a single batch (temperature > 0; output gains a "
                        "\"samples\" list)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu)")
    return p


def _prompt_ids(args, tokenizer=None) -> np.ndarray:
    given = [x is not None
             for x in (args.prompt_tokens, args.prompt, args.prompt_file)]
    if sum(given) != 1:
        raise SystemExit("pass exactly one of "
                         "--prompt-tokens/--prompt/--prompt-file")
    if args.prompt is not None:
        if not args.prompt:
            raise SystemExit("--prompt is empty")
        if tokenizer is not None:
            from nezha_tpu.data.tokenizer import encode_plain
            ids = np.asarray(encode_plain(tokenizer, args.prompt), np.int32)
            if ids.size == 0:
                raise SystemExit("--prompt encoded to zero tokens")
            return ids[None, :]
        ids = np.frombuffer(args.prompt.encode("utf-8"), np.uint8)
        return ids.astype(np.int32)[None, :]
    if args.prompt_tokens is not None:
        try:
            ids = [int(t) for t in args.prompt_tokens.split(",") if t.strip()]
        except ValueError:
            raise SystemExit(f"--prompt-tokens must be comma-separated ids, "
                             f"got {args.prompt_tokens!r}")
        if not ids:
            raise SystemExit("--prompt-tokens is empty")
        return np.asarray([ids], np.int32)
    dtype = np.int32 if args.prompt_i32 else np.uint16
    ids = np.fromfile(args.prompt_file, dtype=dtype).astype(np.int32)
    if ids.size == 0:
        raise SystemExit(f"{args.prompt_file} holds no tokens")
    return ids[None, :]


def _load_tokenizer(args):
    import os

    from nezha_tpu.data.tokenizer import load_tokenizer
    if args.tokenizer:
        try:
            return load_tokenizer(args.tokenizer)
        except FileNotFoundError as e:
            raise SystemExit(str(e))
    if args.hf_dir:
        # Same completeness rule as load_tokenizer itself (BPE needs BOTH
        # files): a partial vocab copy falls back to byte-level instead
        # of aborting a generation that used to work.
        bpe = all(os.path.isfile(os.path.join(args.hf_dir, f))
                  for f in ("vocab.json", "merges.txt"))
        wp = os.path.isfile(os.path.join(args.hf_dir, "vocab.txt"))
        if bpe or wp:
            return load_tokenizer(args.hf_dir)
    return None


def run(args) -> dict:
    import jax

    from nezha_tpu.cli.common import load_gpt2_for_inference, setup_jax
    setup_jax(args)

    from nezha_tpu.models.generate import generate

    model, variables = load_gpt2_for_inference(args)

    tokenizer = _load_tokenizer(args)
    prompt = _prompt_ids(args, tokenizer)
    vocab = model.cfg.vocab_size
    if tokenizer is not None and tokenizer.vocab_size > vocab:
        raise SystemExit(
            f"tokenizer vocab {tokenizer.vocab_size} exceeds model vocab "
            f"{vocab}; wrong --tokenizer for this checkpoint?")
    if prompt.max() >= vocab or prompt.min() < 0:
        raise SystemExit(f"prompt ids must be in [0, {vocab}); "
                         f"got max {int(prompt.max())}")
    limit = model.cfg.max_positions - prompt.shape[1]
    if args.max_new_tokens > limit:
        raise SystemExit(f"prompt ({prompt.shape[1]} tokens) + "
                         f"--max-new-tokens {args.max_new_tokens} exceeds "
                         f"max_positions {model.cfg.max_positions}")
    if args.top_k is not None and not 1 <= args.top_k <= vocab:
        raise SystemExit(f"--top-k must be in [1, {vocab}] for this "
                         f"model's vocab, got {args.top_k}")
    if args.num_samples < 1:
        raise SystemExit(f"--num-samples must be >= 1, got "
                         f"{args.num_samples}")
    if args.num_samples > 1 and args.temperature == 0.0:
        raise SystemExit("--num-samples > 1 needs sampling (greedy "
                         "decoding is deterministic — every sample would "
                         "be identical); pass --temperature > 0")

    # EOS: explicit flag wins (validated hard); else the tokenizer's
    # natural EOS, auto-disabled when outside the model vocab; -1
    # force-disables. Shared policy with nezha-serve.
    from nezha_tpu.cli.common import resolve_eos_id
    eos_id = resolve_eos_id(args.eos_id, tokenizer, vocab)

    if args.num_samples > 1:
        # N sampled continuations of ONE prompt as a single batched
        # decode — the same batched single-token program serving uses.
        prompt = np.repeat(prompt, args.num_samples, axis=0)
    out = generate(model, variables, prompt,
                   max_new_tokens=args.max_new_tokens,
                   temperature=args.temperature, top_k=args.top_k,
                   top_p=args.top_p,
                   rng=jax.random.PRNGKey(args.seed),
                   eos_id=eos_id)
    rows = np.asarray(out)[:, prompt.shape[1]:]

    def row_result(new_tokens: list) -> dict:
        result = {"tokens": new_tokens}
        if tokenizer is not None:
            # Real-vocabulary decode: HF GPT-2 weights + their shipped BPE
            # files emit actual text (VERDICT r4 missing item 2). decode()
            # skips unknown ids, so count them loudly (mirror of the
            # byte-level path's non_byte_tokens warning).
            known = (tokenizer.decoder if hasattr(tokenizer, "decoder")
                     else tokenizer.ids_to_tokens)
            dropped = sum(t not in known for t in new_tokens)
            result["text"] = tokenizer.decode(new_tokens)
            if dropped:
                result["unknown_tokens"] = dropped
                print(f"warning: {dropped}/{len(new_tokens)} generated ids "
                      f"are outside this tokenizer's vocab "
                      f"({tokenizer.vocab_size}) — wrong --tokenizer for "
                      f"this checkpoint? \"text\" is partial",
                      file=sys.stderr)
        elif args.prompt is not None:
            # Byte-level round trip (the encoding pack_text_files trains
            # with). A non-byte-trained checkpoint (e.g. BPE HF weights)
            # emits ids >= 256 — count them loudly rather than silently
            # shrinking "text".
            dropped = sum(t >= 256 for t in new_tokens)
            result["text"] = bytes(t for t in new_tokens if t < 256).decode(
                "utf-8", errors="replace")
            if dropped:
                result["non_byte_tokens"] = dropped
                print(f"warning: {dropped}/{len(new_tokens)} generated ids "
                      f"are >= 256 — this checkpoint is not byte-level-"
                      f"trained; \"text\" is partial (pass --tokenizer DIR "
                      f"with the model's vocab files for real text)",
                      file=sys.stderr)
        return result

    samples = [row_result(r.tolist()) for r in rows]
    result = {"prompt_len": int(prompt.shape[1]), **samples[0]}
    if eos_id is not None:
        result["eos_id"] = eos_id
    if args.num_samples > 1:
        result["num_samples"] = args.num_samples
        result["samples"] = samples
    print(json.dumps(result))
    return result


def main(argv=None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
