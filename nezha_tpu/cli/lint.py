"""nezha-lint — run the repo's static invariant rules.

Usage::

    nezha-lint [--root DIR] [--rule NAME ...] [--json] [--list-rules]
               [--baseline PATH | --no-baseline] [--update-baseline]

Exit codes: 0 clean (all findings suppressed by the baseline), 1 when
unsuppressed findings / stale baseline entries / parse failures exist,
2 on usage errors. ``--json`` emits one machine-readable object on
stdout (findings, suppressed count, stale keys) for CI annotation.

``--update-baseline`` rewrites the baseline to accept exactly the
CURRENT findings, preserving existing justifications and stamping new
entries with a placeholder the next load will REJECT until a human
writes the real one-line reason — regenerating the file can never
silently launder new violations into accepted ones.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from nezha_tpu.analysis import (BaselineError, RULES, SourceIndex,
                                apply_baseline, load_baseline, load_rules,
                                run_rules, write_baseline)
from nezha_tpu.analysis.baseline import DEFAULT_BASELINE


def _find_root(start: str) -> str:
    """Walk up from ``start`` to the repo root (the dir holding
    pyproject.toml with a nezha_tpu/ package); fall back to start."""
    cur = os.path.abspath(start)
    while True:
        if os.path.isfile(os.path.join(cur, "pyproject.toml")) \
                and os.path.isdir(os.path.join(cur, "nezha_tpu")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nezha-lint",
        description="AST-based invariant checker for the nezha-tpu "
                    "tree (tracing, donation, host-sync, lock, and "
                    "registry contracts).")
    p.add_argument("--root", default=None,
                   help="repo root (default: walk up from cwd to the "
                        "dir holding pyproject.toml + nezha_tpu/)")
    p.add_argument("--rule", action="append", default=None,
                   metavar="NAME",
                   help="run only this rule (repeatable; default all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of text lines")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help=f"suppression baseline (default "
                        f"<root>/{DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to accept the current "
                        "findings (new entries get a placeholder "
                        "justification you must edit before it loads)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    load_rules()
    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name:24s} {RULES[name].contract}")
        return 0
    root = args.root or _find_root(os.getcwd())
    if args.update_baseline and args.rule:
        # A partial regeneration would rewrite the file to ONLY the
        # selected rules' findings, deleting every other rule's
        # suppressions (and their justifications) — refuse.
        print("nezha-lint: --update-baseline cannot be combined with "
              "--rule (it would drop every other rule's suppressions)",
              file=sys.stderr)
        return 2
    t0 = time.monotonic()
    index = SourceIndex(root)
    try:
        findings = run_rules(index, args.rule)
    except KeyError as e:
        print(f"nezha-lint: {e.args[0]}", file=sys.stderr)
        return 2
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    if args.update_baseline:
        # Lenient read: regeneration must PRESERVE the human-written
        # justifications even when the file currently holds placeholder
        # entries a strict load rejects. Structural damage aborts —
        # never rewrite what could not be read.
        try:
            existing = load_baseline(baseline_path, strict=False)
        except BaselineError as e:
            print(f"nezha-lint: refusing to rewrite a baseline that "
                  f"cannot be read: {e}", file=sys.stderr)
            return 2
        write_baseline(findings, baseline_path, justifications=existing)
        print(f"nezha-lint: wrote {len(findings)} suppression(s) to "
              f"{baseline_path}", file=sys.stderr)
        return 0
    baseline = {}
    baseline_error = None
    if not args.no_baseline:
        try:
            baseline = load_baseline(baseline_path)
        except BaselineError as e:
            baseline_error = str(e)
        if args.rule:
            # Single-rule runs only PRODUCE the selected rules'
            # findings (plus syntax), so only those rules' suppressions
            # can be judged stale — an unselected rule's valid entry
            # must not fail the run.
            selected = set(args.rule) | {"syntax"}
            baseline = {k: v for k, v in baseline.items()
                        if k.split(":", 1)[0] in selected}
    kept, stale = apply_baseline(findings, baseline)
    dt = time.monotonic() - t0
    rc = 1 if (kept or stale or baseline_error) else 0
    if args.json:
        print(json.dumps({
            "version": 1, "root": root,
            "rules": sorted(args.rule) if args.rule else sorted(RULES),
            "files_indexed": len(index.modules),
            "elapsed_s": round(dt, 3),
            "findings": [f.to_json() for f in kept],
            "suppressed": len(findings) - len(kept),
            "stale_baseline_keys": stale,
            "baseline_error": baseline_error,
            "exit_code": rc,
        }, indent=2))
        return rc
    if baseline_error:
        print(f"nezha-lint: BASELINE ERROR: {baseline_error}",
              file=sys.stderr)
    for f in kept:
        print(f.render())
    for k in stale:
        print(f"nezha-lint: stale baseline entry {k!r} matches no "
              f"current finding — remove it (the violation it excused "
              f"is gone)", file=sys.stderr)
    n_rules = len(args.rule) if args.rule else len(RULES)
    if rc:
        print(f"nezha-lint: FAIL — {len(kept)} finding(s), "
              f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} "
              f"({n_rules} rules, {len(index.modules)} files, "
              f"{dt:.2f}s)", file=sys.stderr)
    else:
        print(f"nezha-lint: OK — {n_rules} rules over "
              f"{len(index.modules)} files in {dt:.2f}s "
              f"({len(findings) - len(kept)} baseline-suppressed)",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
