"""Training CLI — the counterpart of the reference's `cmd/nezha-train`
(SURVEY.md §1: flag parsing, config -> model/backend/world-size, launches
the training loop)."""
