"""``nezha-top``: a live terminal fleet view over a ``/metrics``
endpoint.

    nezha-serve --replicas 2 --front-end-port 8700 ... &
    nezha-top http://127.0.0.1:8700

Polls the router's (or a single replica's) Prometheus-text ``/metrics``
every ``--interval`` seconds, parses the window-labeled samples, and
renders a one-screen fleet dashboard: live replicas, queue depth,
admission/token rates, TTFT/TPOT quantiles, and error counters — all
over the rolling window picked with ``--window`` (the same 10s/60s/300s
views ``Registry.windows`` serves). ``--iterations`` bounds the loop for
scripting and tests; the default polls until interrupted.

The fleet numbers are the router's merged-sketch roll-up (see
``obs.merge_window_payloads``), so quantiles are fleet-exact, not
averages of replica quantiles. docs/RUNBOOK.md "Monitoring & SLOs".
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nezha-top",
        description="Live terminal fleet view over a nezha /metrics "
                    "endpoint (router front-end or single replica).")
    p.add_argument("url", help="base URL serving /metrics, e.g. "
                               "http://127.0.0.1:8700")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between polls (default 2)")
    p.add_argument("--iterations", type=int, default=0,
                   help="stop after N polls (default 0 = run until "
                        "interrupted)")
    p.add_argument("--window", default="60s",
                   choices=("10s", "60s", "300s"),
                   help="rolling window the rates/quantiles are read "
                        "from (default 60s)")
    p.add_argument("--no-clear", action="store_true",
                   help="append frames instead of redrawing in place "
                        "(for logs / non-TTY output)")
    return p


def fetch_metrics_text(url: str, timeout: float = 5.0) -> str:
    """GET ``<url>/metrics`` and return the exposition text."""
    from urllib.request import urlopen
    target = url.rstrip("/") + "/metrics"
    with urlopen(target, timeout=timeout) as resp:
        return resp.read().decode("utf-8", errors="replace")


# Display rows: (label, exposition name, kind). Kinds: "rate" reads the
# windowed counter rate, "last" the windowed gauge last-value, "hist"
# the windowed p50/p99 pair, "total" the cumulative unlabeled sample.
_ROWS = (
    ("replicas live", "nezha_router_replicas_live", "total"),
    ("queue depth", "nezha_serve_queue_depth_last", "last"),
    ("batch occupancy", "nezha_serve_batch_occupancy_last", "last"),
    ("admitted/s", "nezha_serve_admitted_total_rate", "rate"),
    ("tokens/s", "nezha_serve_tokens_total_rate", "rate"),
    ("rejected/s", "nezha_serve_rejected_total_rate", "rate"),
    ("errors/s", "nezha_serve_errors_total_rate", "rate"),
    ("ttft (s)", "nezha_serve_ttft_s", "hist"),
    ("tpot (s)", "nezha_serve_tpot_s", "hist"),
    ("route (s)", "nezha_router_route_s", "hist"),
    ("replica restarts", "nezha_router_replica_restarts_total", "total"),
    ("max burn rate", "nezha_slo_burn_rate_max", "total"),
    ("watchdog events", "nezha_watchdog_events_total", "total"),
)


def render_top(samples, window: str, url: str = "") -> str:
    """One dashboard frame from parsed ``/metrics`` samples — pure, so
    tests can feed it ``parse_prometheus(render_prometheus(...))``."""
    from nezha_tpu.obs.timeseries import metric_value
    lines = [f"nezha-top  {url}  window={window}".rstrip()]
    lines.append(f"  {'metric':<20}{'value':>12}{'p99':>12}")
    shown = 0
    for label, name, kind in _ROWS:
        if kind == "hist":
            p50 = metric_value(samples, name, window=window,
                               quantile="p50")
            p99 = metric_value(samples, name, window=window,
                               quantile="p99")
            if p50 is None and p99 is None:
                continue
            lines.append(f"  {label:<20}{_num(p50):>12}{_num(p99):>12}")
        else:
            if kind == "total":
                v = metric_value(samples, name)
            else:
                v = metric_value(samples, name, window=window)
            if v is None:
                continue
            lines.append(f"  {label:<20}{_num(v):>12}")
        shown += 1
    if not shown:
        lines.append("  (no recognized samples — is this a nezha "
                     "/metrics endpoint with windows installed?)")
    return "\n".join(lines)


def _num(v) -> str:
    if v is None:
        return "-"
    if float(v) == int(v) and abs(v) < 1e9:
        return str(int(v))
    return f"{v:.4f}"


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # Deferred so `--help` stays instant (repo convention for CLI
    # entries).
    from nezha_tpu.obs.timeseries import parse_prometheus

    polls = 0
    errors = 0
    while True:
        frame = None
        try:
            text = fetch_metrics_text(args.url)
            frame = render_top(parse_prometheus(text), args.window,
                               url=args.url)
            errors = 0
        except KeyboardInterrupt:
            return 0
        except Exception as e:  # connection refused, timeout, bad body
            errors += 1
            print(f"nezha-top: fetch failed ({e})", file=sys.stderr)
            if errors >= 5:
                print("nezha-top: 5 consecutive failures, giving up",
                      file=sys.stderr)
                return 1
        if frame is not None:
            if not args.no_clear and sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print(frame, flush=True)
        polls += 1
        if args.iterations and polls >= args.iterations:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
