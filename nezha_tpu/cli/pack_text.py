"""``nezha-pack-text``: text files -> flat binary token files for
`nezha-train --data-dir` (SURVEY.md §2 data loaders; LM configs 3-4).

Byte-level by default (vocab 256, zero dependencies); with
``--tokenizer DIR`` the corpus is encoded with the real GPT-2 BPE or BERT
WordPiece vocabulary in that directory (``vocab.json``+``merges.txt`` or
``vocab.txt`` — the files a Hugging Face checkpoint ships; network-free,
see data/tokenizer.py). Usage::

    nezha-pack-text docs/ --out /data/corpus/train.tokens.u16
    nezha-pack-text book.txt --tokenizer /ckpts/gpt2 \
        --out /data/corpus/train.tokens.u16
    nezha-train --config gpt2_124m --data-dir /data/corpus

The output dtype follows the vocab (uint16 when every id fits, else
int32) and the filename must match what nezha-train probes for
(train.tokens.u16 / train.tokens.i32).
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nezha-pack-text",
        description="Pack text files/trees into a flat binary token file "
                    "for nezha-train --data-dir.")
    p.add_argument("src", nargs="+",
                   help="text files and/or directories (directories are "
                        "walked for --suffix files)")
    p.add_argument("--out", required=True,
                   help="output token file, e.g. corpus/train.tokens.u16")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer directory (vocab.json+merges.txt for "
                        "GPT-2 BPE, vocab.txt for BERT WordPiece); "
                        "default: byte-level vocab 256")
    p.add_argument("--learn-bpe", type=int, default=None, metavar="MERGES",
                   help="learn a byte-level BPE tokenizer from the input "
                        "corpus itself (vocab 256+MERGES; airgapped "
                        "alternative to a downloaded vocabulary), save it "
                        "to --save-tokenizer, and pack with it")
    p.add_argument("--learn-wordpiece", type=int, default=None,
                   metavar="VOCAB",
                   help="learn a BERT-style WordPiece vocab.txt of this "
                        "size from the input corpus (likelihood-scored "
                        "merges, ## continuations; airgapped BERT data "
                        "prep), save to --save-tokenizer, pack with it")
    p.add_argument("--save-tokenizer", default=None,
                   help="output directory for the learned tokenizer files "
                        "(required with --learn-bpe/--learn-wordpiece)")
    p.add_argument("--suffix", nargs="+", default=[".txt", ".md", ".py"],
                   help="file suffixes picked up under directory sources")
    return p


def run(args) -> dict:
    import numpy as np

    from nezha_tpu.data import pack

    paths = []
    for s in args.src:
        if os.path.isdir(s):
            paths.extend(pack.collect_paths(s, args.suffix))
        elif os.path.isfile(s):
            paths.append(s)
        else:
            raise SystemExit(f"no such file or directory: {s}")
    if not paths:
        raise SystemExit("no input files matched")

    out_dir = os.path.dirname(os.path.abspath(args.out))
    os.makedirs(out_dir, exist_ok=True)
    learning = [x for x in (args.learn_bpe, args.learn_wordpiece)
                if x is not None]
    if learning:
        if args.tokenizer or len(learning) > 1:
            raise SystemExit("pass ONE of --tokenizer / --learn-bpe / "
                             "--learn-wordpiece")
        if not args.save_tokenizer:
            raise SystemExit("--learn-bpe/--learn-wordpiece need "
                             "--save-tokenizer DIR (training and "
                             "generation must reuse the learned "
                             "vocabulary)")
        if learning[0] < 1:
            raise SystemExit(f"learned vocab/merge count must be >= 1, "
                             f"got {learning[0]}")
        from pathlib import Path

        texts = (Path(p).read_text(encoding="utf-8")
                 for p in sorted(paths))
        if args.learn_bpe is not None:
            from nezha_tpu.data.bpe_train import learn_bpe, save_bpe_files
            vocab, merges = learn_bpe(texts, args.learn_bpe)
            save_bpe_files(args.save_tokenizer, vocab, merges)
            print(f"learned BPE: {len(merges)} merges, vocab "
                  f"{len(vocab)} -> {args.save_tokenizer}",
                  file=sys.stderr)
        else:
            from nezha_tpu.data.bpe_train import (learn_wordpiece,
                                                  save_wordpiece_vocab)
            try:
                wvocab = learn_wordpiece(texts, args.learn_wordpiece)
            except ValueError as e:
                raise SystemExit(str(e))
            save_wordpiece_vocab(args.save_tokenizer, wvocab)
            print(f"learned WordPiece: vocab {len(wvocab)} -> "
                  f"{args.save_tokenizer}", file=sys.stderr)
        args.tokenizer = args.save_tokenizer
    if args.tokenizer:
        from nezha_tpu.data.tokenizer import load_tokenizer
        tok = load_tokenizer(args.tokenizer)
        dtype = pack.token_dtype(tok.vocab_size)
        want = ".u16" if dtype == np.uint16 else ".i32"
        if not args.out.endswith(want):
            # nezha-train probes train.tokens.u16/.i32 by name; a mismatch
            # here would silently misread every id at training time.
            raise SystemExit(
                f"--out must end in {want} for a vocab of "
                f"{tok.vocab_size} (nezha-train infers dtype from the "
                f"filename)")
        n = pack.pack_text_files_tokenized(paths, args.out, tok,
                                           dtype=dtype)
        kind = type(tok).__name__
        # Meta sidecar (ADVICE r5): record which tokenizer packed this
        # corpus so nezha-train can resolve the TRUE [MASK] id (a learned
        # WordPiece vocab puts it at id 4, not the BERT convention's 103)
        # without the user re-supplying the tokenizer path.
        import json
        mask_id = getattr(tok, "vocab", {}).get(
            getattr(tok, "mask_token", "[MASK]")) \
            if hasattr(tok, "vocab") else None
        with open(args.out + ".meta.json", "w", encoding="utf-8") as f:
            json.dump({"tokenizer_kind": kind,
                       "tokenizer_dir": os.path.abspath(args.tokenizer),
                       "vocab_size": tok.vocab_size,
                       "mask_token_id": mask_id}, f)
    else:
        if not args.out.endswith(".u16"):
            raise SystemExit("--out must end in .u16 for byte-level "
                             "packing (nezha-train infers dtype from the "
                             "filename)")
        n = pack.pack_text_files(paths, args.out)
        kind = "byte-level"
    print(f"packed {len(paths)} files -> {args.out}: {n} tokens ({kind})",
          file=sys.stderr)
    return {"files": len(paths), "tokens": int(n), "tokenizer": kind}


def main(argv=None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
