"""`nezha-bench`: the serving sweep + decode-attention microbench as ONE
reproducible command with per-platform regression gates.

ROADMAP item 5 ("repair and harden the perf trajectory"): every PR's
speed claim should land in a committed record automatically, and a CPU
fallback run must never regress (or overwrite) a TPU baseline. This
entry point

1. resolves the backend SELF-HEALINGLY (a dead TPU tunnel falls back to
   CPU instead of crashing — the bench.py fix, shared here),
2. runs the closed-loop serving sweep (``benchmarks/serving.py``: the
   decode-horizon sweep, the paged-KV shared-prefix record, the
   paged-vs-dense and paged-int8-vs-paged-bf16 equal-memory occupancy
   records) and the decode-attention microbench
   (``benchmarks/decode_attention.py``),
3. compares the headline numbers against the committed baselines
   (``BENCH_serving.json`` / ``BENCH_decode_attention.json``), keyed by
   platform family — a run on a platform with no baseline SEEDS one
   (with ``--update``) and gates nothing,
4. exits nonzero when a gated metric regressed past ``--threshold``.

Gated metrics: serving ``tokens_per_sec`` per decode horizon (higher is
better), the speculative-decode suite's ``tokens_per_verify`` and
spec-vs-classic throughput ratio (higher is better), the opt-in
scrape_overhead suite's scraped-vs-capture-only throughput ratio (hard
0.95 floor — windows + a 1s /metrics scraper must cost under 5%), the
opt-in fleet_kv suite's fleet-hit revisit TTFT (hard 0.7x-of-cold
ceiling, plus nonzero affinity wins / peer pulls), the opt-in
long_context suite's sequence-sharded prefill (hard bit-identical
greedy parity at mesh 2 in bf16 AND int8; the 1.5x prefill tokens/s
floor gates on TPU only), and
the decode-attention kernel's median ``kernel_ms`` across
configs (lower is better). Latency-shaped CPU numbers are noisy, so the
default threshold is deliberately loose (30%) — the gate catches
step-function regressions (a lost kernel, a recompile-per-token bug),
not single-digit drift.

Usage::

    nezha-bench                       # run + gate against baselines
    nezha-bench --update              # run + rewrite the baselines
    nezha-bench --quick               # tiny shapes (tier-1 smoke)
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--suites", default="serving,decode_attention",
                   help="comma-separated subset of "
                        "{serving, decode_attention, sharded_serve, "
                        "kv_churn, fleet_kv, flash_prefill, "
                        "scrape_overhead, overload_storm}. "
                        "overload_storm (bursty Poisson mixed-priority "
                        "arrivals at overcapacity: WFQ + preemption ON "
                        "vs the exact pre-WFQ FIFO control; hard-gates "
                        "interactive TTFT p99 at <= the control's, "
                        "preemptions nonzero, zero errors, and batch/"
                        "background completing — not starved) is "
                        "opt-in: two full open-loop serving runs. "
                        "flash_prefill (the paged flash-prefill "
                        "kernel vs the composed masked path at a "
                        "long-prompt int8 load; hard-gates the frozen "
                        "program contract on both impls — off-TPU the "
                        "kernel interprets, so the committed record "
                        "is a correctness record, not a perf claim) "
                        "is opt-in: two full serving runs. "
                        "scrape_overhead "
                        "(the telemetry-plane tax: the same closed "
                        "loop capture-only vs capture + rolling "
                        "windows + a 1s /metrics scraper; hard gate "
                        "scraped >= 0.95x baseline tokens/sec) is "
                        "opt-in: a latency ratio of two full serving "
                        "runs wants a quiet machine. "
                        "sharded_serve (mesh 1 vs 2 vs 4 at "
                        "equal total memory + the bit-identical greedy-"
                        "parity gate) is opt-in: it needs forced host "
                        "devices off-TPU and its runtime is a "
                        "multiple of the serving sweep's. kv_churn "
                        "(many users revisiting after their KV blocks "
                        "cycled — the tiered-KV host-spill record) is "
                        "opt-in: its hard gate pins promote-hit TTFT "
                        "at <= 0.5x the cold prefill, a latency ratio "
                        "that wants a quiet machine. fleet_kv (users "
                        "revisiting a 3-replica routed fleet whose "
                        "per-replica pools are each too small — the "
                        "fleet-wide KV reuse record, affinity routing "
                        "vs a least-loaded control) is opt-in for the "
                        "same reason: its hard gates pin fleet-hit "
                        "revisit TTFT at <= 0.7x the cold prefill and "
                        "require nonzero affinity wins + committed "
                        "peer pulls")
    p.add_argument("--serving-baseline", default="BENCH_serving.json",
                   help="committed serving record to gate against")
    p.add_argument("--decode-baseline",
                   default="BENCH_decode_attention.json",
                   help="committed decode-attention record to gate "
                        "against")
    p.add_argument("--threshold", type=float, default=0.30,
                   help="allowed fractional regression per gated "
                        "metric before the run fails")
    p.add_argument("--update", action="store_true",
                   help="rewrite the baseline files with this run's "
                        "numbers (per-platform: other platforms' "
                        "slots are preserved)")
    p.add_argument("--quick", action="store_true",
                   help="tiny shapes / few requests — the tier-1 "
                        "smoke configuration, NOT a perf claim")
    p.add_argument("--requests", type=int, default=None,
                   help="serving sweep request count override")
    p.add_argument("--horizons", default=None,
                   help="serving sweep decode horizons override "
                        "(comma-separated; default 1,4,8)")
    p.add_argument("--out", default=None,
                   help="write the combined record here (JSON)")
    p.add_argument("--json", action="store_true",
                   help="print the combined record as JSON")
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (default: auto with CPU "
                        "fallback when backend init fails)")
    return p


def _resolve_platform(requested: Optional[str]) -> str:
    """Initialize JAX, falling back to CPU when the requested/ambient
    backend cannot start (the self-healing move ROADMAP item 5 asks
    for) — the record is always labeled with what actually ran."""
    if requested:
        os.environ["JAX_PLATFORMS"] = requested
    import jax
    try:
        return jax.default_backend()
    except RuntimeError as e:
        print(f"nezha-bench: backend init failed ({e}); retrying on "
              f"cpu", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.extend.backend.clear_backends()
        return jax.default_backend()


def _bench_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "benchmarks")


def _run_serving(args, platform: str) -> dict:
    import tempfile

    sys.path.insert(0, _bench_dir())
    import serving as serving_bench

    horizons = args.horizons or ("1,4" if args.quick else "1,4,8")
    requests = args.requests or (8 if args.quick else 48)
    argv = ["--requests", str(requests), "--concurrency",
            "2" if args.quick else "6",
            "--max-batch-size", "2" if args.quick else "6",
            "--max-len", "48" if args.quick else "64",
            "--max-prefill-len", "8" if args.quick else "16",
            "--max-new-tokens", "4" if args.quick else "32",
            "--decode-horizon", horizons,
            "--platform", platform]
    # Two passes over the same shapes. The gated THROUGHPUT sweep runs
    # capture-free: a telemetry capture at trace-sample 1.0 costs ~8%
    # tokens/sec on the CPU tiny-model bench, which would silently eat
    # the gate's headroom against the pre-telemetry baseline — and, on
    # --update, bake tracing overhead into the committed throughput
    # record. A separate CAPTURED pass contributes ONLY its stitched
    # ``trace`` block (the per-segment TTFT decomposition the gate
    # below holds against the baseline); its throughput numbers are
    # discarded.
    sweep = serving_bench.run(
        serving_bench.build_parser().parse_args(list(argv)))
    with tempfile.TemporaryDirectory(prefix="nezha-bench-trace-") as td:
        traced = serving_bench.run(
            serving_bench.build_parser().parse_args(
                argv + ["--run-dir", td]))
    if "by_horizon" in sweep:
        for h, rec in sweep["by_horizon"].items():
            rec["trace"] = (traced["by_horizon"].get(h) or {}).get(
                "trace")
    else:
        sweep["trace"] = traced.get("trace")
    sweep["trace_source"] = ("separate captured pass — tokens_per_sec "
                             "measured capture-free")
    # The paged-KV shared-prefix record rides in the same suite: 80%
    # templated traffic, hit TTFT vs miss TTFT (ISSUE 8 acceptance).
    # Shared-prefix run at concurrency BELOW the slot count: TTFT is
    # then prefill-dominated (no queue wait), so the record isolates
    # the reuse win itself.
    shared_argv = ["--requests", str(requests),
                   "--concurrency", "2" if args.quick else "3",
                   "--max-batch-size", "2" if args.quick else "6",
                   "--max-len", "64" if args.quick else "96",
                   "--max-prefill-len", "8" if args.quick else "16",
                   "--max-new-tokens", "4" if args.quick else "16",
                   "--kv-block-size", "4" if args.quick else "16",
                   "--shared-prefix-frac", "0.8",
                   "--shared-prefix-len", "16" if args.quick else "64",
                   "--platform", platform]
    shared = serving_bench.run(serving_bench.build_parser().parse_args(
        shared_argv))
    # Equal-memory occupancy: dense and paged runs whose device KV
    # budgets hold the SAME number of token-positions — dense peaks at
    # its slot count, paged at what the block budget admits (strictly
    # more on under-max_len traffic; the ISSUE 8 acceptance record).
    if args.quick:
        budget_note = "64 token-positions each"
        dense_argv = ["--kv-layout", "dense", "--max-batch-size", "2",
                      "--max-len", "32"]
        paged_argv = ["--max-batch-size", "4", "--max-len", "32",
                      "--kv-block-size", "4", "--kv-num-blocks", "17"]
        load = ["--requests", str(requests), "--concurrency", "8",
                "--prompt-len", "4", "--max-new-tokens", "4",
                "--max-prefill-len", "8", "--platform", platform]
    else:
        budget_note = "256 token-positions each"
        dense_argv = ["--kv-layout", "dense", "--max-batch-size", "4",
                      "--max-len", "64"]
        paged_argv = ["--max-batch-size", "8", "--max-len", "64",
                      "--kv-block-size", "16", "--kv-num-blocks", "17"]
        load = ["--requests", str(requests), "--concurrency", "8",
                "--prompt-len", "8", "--max-new-tokens", "16",
                "--max-prefill-len", "16", "--platform", platform]
    dense = serving_bench.run(serving_bench.build_parser().parse_args(
        dense_argv + load))
    paged = serving_bench.run(serving_bench.build_parser().parse_args(
        paged_argv + load))
    # Equal-memory int8 vs bf16 (ISSUE 9 acceptance): paged pools whose
    # device KV budgets hold the same BYTES — an int8 block costs ~half
    # a bf16 block (+ one fp32 scale per head: 4/(block_size*D) per
    # element), so the same budget holds ~2x the blocks and resident-
    # request capacity ~doubles while each request's footprint (1 block
    # here) is unchanged. Block counts below keep the int8 budget AT OR
    # UNDER the bf16 byte budget, so the capacity claim is never
    # flattered by rounding.
    if args.quick:
        int8_budget = ("4 usable bf16 blocks vs 7 int8 "
                       "(int8 bytes 11% UNDER the bf16 budget)")
        bf16_argv = ["--max-batch-size", "16", "--max-len", "32",
                     "--kv-num-blocks", "5"]
        int8_argv = ["--max-batch-size", "16", "--max-len", "32",
                     "--kv-num-blocks", "8", "--kv-dtype", "int8"]
        iload = ["--requests", str(requests), "--concurrency", "8",
                 "--prompt-len", "4", "--max-new-tokens", "4",
                 "--max-prefill-len", "8", "--platform", platform]
    else:
        int8_budget = ("8 usable bf16 blocks vs 15 int8 "
                       "(int8 bytes 4.8% UNDER the bf16 budget)")
        bf16_argv = ["--max-batch-size", "16", "--max-len", "32",
                     "--kv-num-blocks", "9"]
        int8_argv = ["--max-batch-size", "16", "--max-len", "32",
                     "--kv-num-blocks", "16", "--kv-dtype", "int8"]
        iload = ["--requests", str(max(requests, 32)),
                 "--concurrency", "16",
                 "--prompt-len", "4", "--max-new-tokens", "8",
                 "--max-prefill-len", "8", "--platform", platform]
    kv_bf16 = serving_bench.run(serving_bench.build_parser().parse_args(
        bf16_argv + iload))
    kv_int8 = serving_bench.run(serving_bench.build_parser().parse_args(
        int8_argv + iload))
    # Disaggregated prefill/decode tiers vs co-located (ISSUE 11
    # acceptance): a LONG-PROMPT mix (the traffic shape whose bursty
    # prefill stalls co-located TPOT) at EQUAL TOTAL HARDWARE — a
    # 1-prefill + 2-decode router vs a 3-replica co-located one, same
    # closed-loop load. TPOT is the worker-local decode cadence
    # (benchmarks/serving.py), so the ratio isolates what the decode
    # tier gains by never interleaving prefill. The record carries
    # migration GB/s and the prefill-wait/decode-wait queueing split
    # (recorded, not gated — CPU latency numbers are noisy; the gate
    # stays on the horizon-sweep tokens/sec).
    if args.quick:
        dis_load = ["--requests", str(requests), "--concurrency", "4",
                    "--prompt-len-mix", "6,20", "--max-new-tokens", "6",
                    "--max-batch-size", "2", "--max-len", "48",
                    "--max-prefill-len", "8", "--kv-block-size", "4",
                    "--platform", platform]
    else:
        dis_load = ["--requests", str(requests), "--concurrency", "6",
                    "--prompt-len-mix", "8,56,56",
                    "--max-new-tokens", "16",
                    "--max-batch-size", "4", "--max-len", "96",
                    "--max-prefill-len", "16", "--kv-block-size", "16",
                    "--platform", platform]
    tiers = ["--prefill-replicas", "1", "--decode-replicas",
             "1" if args.quick else "2"]
    disagg = serving_bench.run(serving_bench.build_parser().parse_args(
        ["--disaggregate"] + tiers + dis_load))
    coloc = serving_bench.run(serving_bench.build_parser().parse_args(
        ["--replicas", "2" if args.quick else "3"] + dis_load))
    # Speculative decode vs classic at EQUAL HARDWARE (ISSUE 13
    # acceptance): same model, same batch, same closed-loop load, both
    # runs in this one process so the ratio sees the same machine
    # state. The load is GREEDY (the bit-identical-parity mode) with
    # decodes long enough to amortize the draft's prefill tax — the
    # regime speculation targets (decode-dominated small-batch
    # traffic); h=1 so every accepted draft token is a dispatch the
    # classic engine would have paid for. The draft is a 1-layer
    # early-exit self-draft (no second checkpoint). A draft_k sweep
    # rides along so the accept-rate-vs-window-size tradeoff is in the
    # committed record.
    if args.quick:
        spec_load = ["--requests", str(requests), "--concurrency", "2",
                     "--max-batch-size", "2", "--max-len", "48",
                     "--max-prefill-len", "8", "--prompt-len", "4",
                     "--max-new-tokens", "8", "--sample-fraction", "0",
                     "--decode-horizon", "1", "--platform", platform]
        spec_ks = [3]
    else:
        spec_load = ["--requests", str(requests), "--concurrency", "4",
                     "--max-batch-size", "4", "--max-len", "88",
                     "--max-prefill-len", "16", "--prompt-len", "8",
                     "--max-new-tokens", "72", "--sample-fraction", "0",
                     "--decode-horizon", "1", "--platform", platform]
        spec_ks = [2, 4, 7]
    spec_classic = serving_bench.run(
        serving_bench.build_parser().parse_args(spec_load))
    spec_sweep = {}
    for kk in spec_ks:
        spec_sweep[str(kk)] = serving_bench.run(
            serving_bench.build_parser().parse_args(
                spec_load + ["--speculative", "--draft-k", str(kk),
                             "--draft-layers", "1"]))
    spec_best = spec_sweep[str(spec_ks[-1])]
    return {"closed_loop_horizon_sweep": sweep,
            "speculative_decode": {
                "load": "greedy closed loop, long decode, h=1, "
                        "1-layer self-draft",
                "classic": spec_classic,
                "draft_k_sweep": spec_sweep,
                "headline_draft_k": spec_ks[-1],
                "tokens_per_verify":
                    spec_best["spec"]["tokens_per_verify"],
                "accept_rate": spec_best["spec"]["accept_rate"],
                "tokens_per_sec_ratio_spec_vs_classic": (
                    spec_best["tokens_per_sec"]
                    / max(spec_classic["tokens_per_sec"], 1e-9)),
            },
            "disaggregated_prefill_decode": {
                "load": "long-prompt mix "
                        + dis_load[dis_load.index("--prompt-len-mix") + 1],
                "disaggregated": disagg, "colocated": coloc,
                "migration_gb_per_s":
                    (disagg.get("migration") or {}).get("gb_per_s"),
                "prefill_wait_p50_s": disagg["prefill_wait_s"]["p50"],
                "decode_wait_p50_s": disagg["decode_wait_s"]["p50"],
                "tpot_p50_ratio_disagg_vs_colocated": (
                    disagg["tpot_s"]["p50"]
                    / max(coloc["tpot_s"]["p50"], 1e-9)),
            },
            "shared_prefix_0.8": shared,
            "paged_vs_dense_equal_memory": {
                "kv_budget": budget_note,
                "dense": dense, "paged": paged,
                "dense_peak_resident":
                    dense["kv"]["peak_resident_requests"],
                "paged_peak_resident":
                    paged["kv"]["peak_resident_requests"],
            },
            "paged_int8_vs_bf16_equal_memory": {
                "kv_budget": int8_budget,
                "bf16": kv_bf16, "int8": kv_int8,
                "bf16_peak_resident":
                    kv_bf16["kv"]["peak_resident_requests"],
                "int8_peak_resident":
                    kv_int8["kv"]["peak_resident_requests"],
                "bf16_peak_bytes":
                    kv_bf16["kv"]["peak_bytes_resident"],
                "int8_peak_bytes":
                    kv_int8["kv"]["peak_bytes_resident"],
                # TTFT/TPOT ride along so the capacity claim is
                # checkable against its latency cost in one place
                # (CPU records are noisy — the gate stays on the
                # horizon-sweep tokens/sec, not on these).
                "ttft_p50_ratio_int8_vs_bf16": (
                    kv_int8["ttft_s"]["p50"]
                    / max(kv_bf16["ttft_s"]["p50"], 1e-9)),
                "tpot_p50_ratio_int8_vs_bf16": (
                    kv_int8["tpot_s"]["p50"]
                    / max(kv_bf16["tpot_s"]["p50"], 1e-9)),
            }}


def _run_sharded_serve(args, platform: str) -> dict:
    """The tensor-sharded serving suite (ISSUE 14): the SAME closed
    loop at mesh 1 vs 2 vs 4 under EQUAL TOTAL MEMORY (one fixed
    kv_num_blocks budget — a mesh-M run holds the same logical blocks,
    each device 1/M of the bytes), plus the hard correctness gate:
    greedy outputs across mesh sizes must be BIT-IDENTICAL to the
    single-device engine. Meshes the visible device count cannot host
    are recorded as dropped, never silently skipped (the tier-1 rig
    forces 8 host devices; a bare laptop records mesh 1 only)."""
    import jax

    sys.path.insert(0, _bench_dir())
    import serving as serving_bench

    ndev = len(jax.devices())
    want = [1, 2, 4]
    meshes = [m for m in want if m <= ndev]
    dropped = [m for m in want if m > ndev]
    if dropped:
        print(f"nezha-bench: sharded_serve dropping meshes {dropped} "
              f"({ndev} device(s) visible)", file=sys.stderr)
    requests = args.requests or (8 if args.quick else 24)
    # Equal total memory: ONE block budget across every mesh size.
    load = ["--requests", str(requests), "--concurrency", "4",
            "--max-batch-size", "4",
            "--max-len", "32", "--max-prefill-len", "8",
            "--prompt-len", "4",
            "--max-new-tokens", "4" if args.quick else "8",
            "--kv-block-size", "4", "--kv-num-blocks", "33",
            "--sample-fraction", "0", "--platform", platform]
    by_mesh = {}
    for m in meshes:
        by_mesh[str(m)] = serving_bench.run(
            serving_bench.build_parser().parse_args(
                load + ["--mesh", str(m)]))
    single = by_mesh.get("1") or {}
    ratios_ttft, ratios_tpot = {}, {}
    for m, rec in by_mesh.items():
        if m == "1" or not single:
            continue
        ratios_ttft[m] = (rec["ttft_s"]["p50"]
                          / max(single["ttft_s"]["p50"], 1e-9))
        ratios_tpot[m] = (rec["tpot_s"]["p50"]
                          / max(single["tpot_s"]["p50"], 1e-9))
    return {
        "kv_budget": "33 blocks x 4 tokens shared across meshes "
                     "(equal TOTAL memory; each mesh-M device holds "
                     "1/M of the bytes)",
        "devices_visible": ndev,
        "meshes": meshes, "dropped_meshes": dropped,
        "by_mesh": by_mesh,
        "greedy_parity": _sharded_greedy_parity(meshes),
        "ttft_p50_ratio_vs_single": ratios_ttft,
        "tpot_p50_ratio_vs_single": ratios_tpot,
    }


def _sharded_greedy_parity(meshes) -> bool:
    """Bit-identical greedy parity across mesh sizes: one tiny model,
    one prompt set, engines at every runnable mesh — token streams
    must match the single-device engine exactly. The hard gate of the
    sharded_serve suite (a False here fails the bench regardless of
    baselines)."""
    import jax
    import jax.numpy as jnp

    from nezha_tpu.cli.train import TINY_GPT2_KW
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config
    from nezha_tpu.serve import Engine, Request, Scheduler, ServeConfig
    from nezha_tpu.serve.sharded import ShardedEngine

    model = GPT2(GPT2Config(**TINY_GPT2_KW))
    variables = model.init(jax.random.PRNGKey(0))
    cfg = ServeConfig(max_batch_size=2, max_len=32, max_prefill_len=8,
                      cache_dtype=jnp.float32)
    prompts = [[5, 17, 3], [9, 8, 7, 6, 5], [1, 2]]

    def decode(engine):
        sched = Scheduler(engine)
        for i, p in enumerate(prompts):
            sched.submit(Request(prompt=p, max_new_tokens=6,
                                 request_id=f"p{i}"))
        sched.run_until_idle(max_iters=300)
        return {k: v.tokens for k, v in sched.results.items()}

    ref = decode(Engine(model, variables, cfg))
    for m in meshes:
        if m == 1:
            continue
        if decode(ShardedEngine(model, variables, cfg,
                                mesh_devices=m)) != ref:
            return False
    return True


def _run_kv_churn(args, platform: str) -> dict:
    """The tiered-KV churn suite (ISSUE 15): U users with distinct
    block-aligned prompt prefixes revisit round-robin, against a
    device pool deliberately sized to hold only ~2 users' cached
    prefixes — between a user's visits their trie blocks are LRU-
    evicted, so a revisit is a cold re-prefill UNLESS the host tier
    caught the demotion and promotes it back. Two runs at identical
    shapes: host tier ON (the promote path) and OFF (the cold-
    re-prefill control). The acceptance gate is within the HOST run:
    revisit (promote-hit) TTFT p50 <= 0.5x first-visit (cold) TTFT
    p50, with promotions > 0 proving the tier — not lucky device
    residency — served the revisits."""
    sys.path.insert(0, _bench_dir())
    import serving as serving_bench

    # One proven shape for quick and full (full just churns longer):
    # 64-token prefixes over 16-token int8 blocks against a 13-usable-
    # block device pool — ~2 users' cached prefixes fit, so a user's
    # blocks are always evicted (demoted) before their next visit. The
    # cold prefill is 9 chunks of 8; the promote hit is a 4-block
    # host->device copy + ONE tail chunk.
    users, rounds = (4, 3) if args.quick else (6, 4)
    common = ["--requests", str(users * rounds), "--concurrency", "1",
              "--churn-users", str(users),
              "--churn-prefix-len", "64",
              "--kv-block-size", "16", "--kv-dtype", "int8",
              "--kv-num-blocks", "14",
              "--max-batch-size", "2", "--max-prefill-len", "8",
              "--max-len", "80", "--max-new-tokens", "4",
              "--sample-fraction", "0",
              "--platform", platform]
    host_budget = 32
    host = serving_bench.run(serving_bench.build_parser().parse_args(
        common + ["--kv-host-blocks", str(host_budget)]))
    ctrl = serving_bench.run(serving_bench.build_parser().parse_args(
        common + ["--kv-host-blocks", "0"]))
    hc, cc = host["kv_churn"], ctrl["kv_churn"]
    return {
        "load": f"{users} users x {rounds} visits, 64-token prefixes "
                f"over 16-token int8 blocks, 13-usable-block device "
                f"pool, host budget {host_budget}",
        "host_tier": host,
        "control_no_host_tier": ctrl,
        "demotions": hc["demotions"],
        "promotions": hc["promotions"],
        "promote_failures": hc["promote_failures"],
        # The gated headline: promote-hit TTFT vs the SAME run's cold
        # first visits (identical prompt shapes, same machine state).
        "promote_vs_cold_ttft_p50": hc["revisit_vs_first_ttft_p50"],
        # The control's revisits re-prefill cold (any device-trie
        # survivors only flatter it), so this ratio shows what the
        # tier is worth end to end. Recorded, not gated — two separate
        # runs' latencies divide noisily on CPU.
        "control_revisit_vs_first_ttft_p50":
            cc["revisit_vs_first_ttft_p50"],
        "revisit_ttft_p50_host_vs_control": (
            hc["ttft_revisit_s"]["p50"]
            / max(cc["ttft_revisit_s"]["p50"], 1e-9)),
    }


def _run_fleet_kv(args, platform: str) -> dict:
    """The fleet-wide KV reuse suite (ISSUE 17): the multi-replica
    churn scenario — U users with distinct block-aligned prefixes
    revisit a 3-replica ROUTED fleet whose per-replica pools are each
    too small to hold every user, while the fleet aggregate holds them
    all. Two runs at identical shapes: ``--affinity-routing on``
    (digest-affinity revisits + the peer-pull drill against a
    queue-clamped owner) and ``off`` (least-loaded control — traffic
    piles onto one replica, whose pool cycles, so revisits re-prefill
    cold). The hard gates are within the AFFINITY run: revisit
    (fleet-hit) TTFT p50 <= 0.7x first-visit (cold) TTFT p50, with
    affinity wins / committed pulls / peer-installed blocks all
    nonzero proving the fleet machinery — not single-pool luck —
    served them. The seeds are pinned per shape so the consistent-hash
    cold placement provably spreads 6 users across 3 replicas (worst
    replica holds 2)."""
    sys.path.insert(0, _bench_dir())
    import serving as serving_bench

    # Quick: 32-token prefixes (2 blocks), 9-usable-block pools — one
    # replica holds at most ~3 users' prefixes, the fleet holds all 6.
    # Full: 64-token prefixes (4 blocks), 17-usable-block pools, one
    # more revisit round. Seeds pinned to a 2/2/2 cold spread.
    users = 6
    visits, plen, nblocks, mlen, seed = \
        (2, 32, 10, 64, 7) if args.quick else (3, 64, 18, 96, 0)
    common = ["--replicas", "3", "--requests", str(users * visits),
              "--concurrency", "1",
              "--churn-users", str(users),
              "--churn-prefix-len", str(plen),
              "--kv-block-size", "16", "--kv-dtype", "int8",
              "--kv-num-blocks", str(nblocks),
              "--max-batch-size", "2", "--max-prefill-len", "8",
              "--max-len", str(mlen), "--max-new-tokens", "4",
              "--sample-fraction", "0", "--queue-capacity", "8",
              "--digest-interval", "0.2", "--seed", str(seed),
              "--platform", platform]
    aff = serving_bench.run(serving_bench.build_parser().parse_args(
        common + ["--affinity-routing", "on"]))["fleet"]
    ctrl = serving_bench.run(serving_bench.build_parser().parse_args(
        common + ["--affinity-routing", "off"]))["fleet"]
    peer = aff.get("peer_pull") or {}
    first_p50 = aff["ttft_first_visit_s"]["p50"]
    return {
        "load": f"{users} users x {visits} visits, {plen}-token "
                f"prefixes over 16-token int8 blocks, 3 replicas x "
                f"{nblocks - 1}-usable-block pools, seed {seed}",
        "affinity": aff,
        "control_least_loaded": ctrl,
        "affinity_wins": aff["affinity_wins"],
        "kv_pulls": aff["kv_pulls"],
        "kv_pull_bytes": aff["kv_pull_bytes"],
        "fleet_hits": aff["fleet_hits"],
        "peer_installed": peer.get("installed", 0),
        "peer_pull_seconds": peer.get("pull_s"),
        # The gated headline: fleet-hit revisit TTFT vs the SAME run's
        # cold first visits (identical prompt shapes, same process).
        "revisit_vs_first_ttft_p50": aff["revisit_vs_first_ttft_p50"],
        # The control's revisits re-prefill cold, so these show what
        # fleet-wide reuse is worth end to end. Recorded, not gated —
        # two separate runs' latencies divide noisily on CPU, and the
        # peer hit's TTFT at tiny shapes sits inside timer jitter.
        "control_revisit_vs_first_ttft_p50":
            ctrl["revisit_vs_first_ttft_p50"],
        "revisit_ttft_p50_affinity_vs_control": (
            aff["ttft_revisit_s"]["p50"]
            / max(ctrl["ttft_revisit_s"]["p50"], 1e-9)),
        "peer_hit_vs_first_ttft_p50": (
            peer["ttft_s"] / max(first_p50, 1e-9)
            if peer.get("ttft_s") is not None else None),
    }


def _run_flash_prefill(args, platform: str) -> dict:
    """The flash-prefill record (ISSUE 18 acceptance): the SAME
    long-prompt closed-loop load twice in one process on an int8 pool
    — ``--prefill-impl kernel`` (the Pallas paged-prefill kernel with
    the block write fused into its epilogue) vs ``xla`` (the composed
    masked path + ``_quant_prefill_write`` round-trip). The hard gate
    is the frozen program contract: BOTH impls compile exactly
    ``1 + len(prefill_buckets)`` programs — the kernel replaces the
    chunk attention and the write INSIDE the per-bucket program, it
    must never add one (the strictly-fewer-scatters pin lives in
    tests/test_prefill_attention.py at the HLO level). The TTFT ratio
    is the perf headline on TPU; off-TPU the kernel runs in interpret
    mode, so the record is labeled a CORRECTNESS record and the ratio
    is recorded, not gated. Long prompts are capped at 8192 tokens by
    construction (the mix is clamped to the model's positions; CPU
    shapes scale the same mix down)."""
    sys.path.insert(0, _bench_dir())
    import serving as serving_bench

    requests = args.requests or (6 if args.quick else 24)
    if args.quick:
        load = ["--requests", str(requests), "--concurrency", "4",
                "--prompt-len-mix", "6,20", "--max-new-tokens", "4",
                "--max-batch-size", "2", "--max-len", "48",
                "--max-prefill-len", "8", "--kv-block-size", "4",
                "--kv-dtype", "int8", "--sample-fraction", "0",
                "--platform", platform]
    else:
        load = ["--requests", str(requests), "--concurrency", "6",
                "--prompt-len-mix", "8,56,56", "--max-new-tokens", "8",
                "--max-batch-size", "4", "--max-len", "96",
                "--max-prefill-len", "16", "--kv-block-size", "16",
                "--kv-dtype", "int8", "--sample-fraction", "0",
                "--platform", platform]
    kernel = serving_bench.run(serving_bench.build_parser().parse_args(
        load + ["--prefill-impl", "kernel"]))
    masked = serving_bench.run(serving_bench.build_parser().parse_args(
        load + ["--prefill-impl", "xla"]))
    expected = 1 + len(kernel["prefill_buckets"])
    return {
        "load": "long-prompt mix "
                + load[load.index("--prompt-len-mix") + 1]
                + ", int8 pool, greedy closed loop",
        # Off-TPU the kernel interprets — the numbers prove parity and
        # the frozen contract, NOT kernel speed.
        "mode": ("perf" if platform == "tpu"
                 else "correctness (interpret-mode kernel off-TPU)"),
        "kernel": kernel,
        "masked": masked,
        "programs_expected": expected,
        "programs_kernel": kernel["compile_cache"]["entries"],
        "programs_masked": masked["compile_cache"]["entries"],
        "ttft_p50_ratio_kernel_vs_masked": (
            kernel["ttft_s"]["p50"]
            / max(masked["ttft_s"]["p50"], 1e-9)),
        "tokens_per_sec_ratio_kernel_vs_masked": (
            kernel["tokens_per_sec"]
            / max(masked["tokens_per_sec"], 1e-9)),
    }


def _run_long_context(args, platform: str) -> dict:
    """The long-context prefill record (ISSUE 20 acceptance): the SAME
    long-prompt greedy load at mesh 1 (classic replicated engine) vs
    mesh 2 with ``prefill_mode=sequence`` — every chunk sharded over
    the mesh's sequence axis, wide ``long_prefill_buckets`` so an
    8k/32k prompt prefills in a few chunks instead of hundreds of
    ``max_prefill_len`` strides. The hard gate is bit-identical greedy
    parity (bf16 KV and an int8-pool second pass) — sequence sharding
    must be a pure execution-strategy change. On TPU the mesh-2 run
    must additionally clear 1.5x the single-device prefill tokens/s;
    off-TPU the attention runs composed/interpret-mode on scaled-down
    prompt shapes, so the record is labeled CORRECTNESS and the ratio
    is recorded, not gated."""
    import time

    import jax
    import jax.numpy as jnp

    from nezha_tpu.models.gpt2 import GPT2, GPT2Config
    from nezha_tpu.serve import Engine, Request, Scheduler, ServeConfig
    from nezha_tpu.serve.sharded import ShardedEngine

    ndev = len(jax.devices())
    if platform == "tpu":
        # The real acceptance shapes: 8k and 32k prompts over wide
        # buckets on a model sized to make sequence sharding pay.
        prompt_lens = [8192, 8192, 32768]
        p_max, buckets, lbuckets = 512, (256, 512), (8192, 32768)
        max_len = 33024
        model_kw = dict(vocab_size=512, max_positions=33536,
                        num_layers=4, num_heads=8, hidden_size=128)
        max_new = 2
    elif args.quick:
        prompt_lens = [64, 64, 128]
        p_max, buckets, lbuckets = 16, (8, 16), (64, 128)
        max_len = 160
        model_kw = dict(vocab_size=64, max_positions=192,
                        num_layers=2, num_heads=4, hidden_size=32)
        max_new = 2
    else:
        # The committed CPU correctness record: the same mix scaled
        # down 64x (the composed path attends the full prompt, so
        # CPU wall time stays in seconds).
        prompt_lens = [128, 128, 512]
        p_max, buckets, lbuckets = 16, (8, 16), (128, 512)
        max_len = 544
        model_kw = dict(vocab_size=64, max_positions=576,
                        num_layers=2, num_heads=4, hidden_size=32)
        max_new = 2
    dropped = [] if ndev >= 2 else ["mesh2"]
    if dropped:
        print(f"nezha-bench: long_context dropping mesh 2 "
              f"({ndev} device(s) visible)", file=sys.stderr)

    model = GPT2(GPT2Config(**model_kw))
    variables = model.init(jax.random.PRNGKey(0))
    rng = random.Random(0)
    vocab = model_kw["vocab_size"]
    prompts = [[rng.randrange(vocab) for _ in range(n)]
               for n in prompt_lens]

    def mk_cfg(**kw):
        return ServeConfig(
            max_batch_size=2, max_len=max_len, max_prefill_len=p_max,
            prefill_buckets=buckets, long_prefill_buckets=lbuckets,
            queue_capacity=len(prompts) + 1,
            cache_dtype=jnp.bfloat16, **kw)

    def bench(engine):
        def one_pass():
            sched = Scheduler(engine)
            for i, p in enumerate(prompts):
                sched.submit(Request(prompt=p, max_new_tokens=max_new,
                                     request_id=f"r{i}"))
            t0 = time.perf_counter()
            sched.run_until_idle(max_iters=20000)
            wall = time.perf_counter() - t0
            assert not sched.has_work()
            return wall, {k: v.tokens for k, v in sched.results.items()}
        one_pass()                      # warm every bucket + the step
        wall, toks = one_pass()         # measured: compile-free pass
        ptoks = sum(prompt_lens)
        return {"wall_s": wall,
                "prefill_tokens": ptoks,
                "prefill_tokens_per_sec": ptoks / max(wall, 1e-9),
                }, toks

    by_mesh = {}
    rec1, ref = bench(Engine(model, variables, mk_cfg()))
    by_mesh["1"] = rec1
    parity = parity_int8 = ratio = None
    if not dropped:
        seq_cfg = mk_cfg(prefill_mode="sequence")
        rec2, got = bench(ShardedEngine(model, variables, seq_cfg,
                                        mesh_devices=2))
        by_mesh["2"] = rec2
        parity = got == ref
        ratio = (rec2["prefill_tokens_per_sec"]
                 / max(rec1["prefill_tokens_per_sec"], 1e-9))
        # The int8 second pass: quantized pools + per-block scales
        # must survive sequence sharding bit-for-bit too (the fused
        # epilogue write runs per shard on its own heads).
        _, ref8 = bench(Engine(model, variables,
                               mk_cfg(kv_dtype="int8")))
        _, got8 = bench(ShardedEngine(
            model, variables, mk_cfg(kv_dtype="int8",
                                     prefill_mode="sequence"),
            mesh_devices=2))
        parity_int8 = got8 == ref8
    return {
        # Off-TPU the prompts are scaled down and attention runs the
        # composed path — the numbers prove parity, NOT seq speedup.
        "mode": ("perf" if platform == "tpu"
                 else "correctness (composed attention off-TPU, "
                      "scaled-down prompts)"),
        "load": f"prompt lens {prompt_lens}, long buckets "
                f"{list(lbuckets)}, greedy, bf16 KV + int8 parity "
                f"pass",
        "devices_visible": ndev,
        "dropped": dropped,
        "prompt_lens": prompt_lens,
        "long_prefill_buckets": list(lbuckets),
        "by_mesh": by_mesh,
        "greedy_parity": parity,
        "greedy_parity_int8": parity_int8,
        "prefill_tps_ratio_mesh2_vs_mesh1": ratio,
    }


def _run_scrape_overhead(args, platform: str) -> dict:
    """The telemetry-plane overhead record (ISSUE 16 acceptance): the
    SAME closed-loop load twice in one process — a capture-only run
    (run-dir sink, rolling windows OFF, no scraper) vs capture +
    rolling windows + an in-process thread rendering the full windowed
    ``/metrics`` exposition every second. The hard gate pins the
    scraped pass's tokens/sec at >= 0.95x the baseline's: the window
    tap is O(1) bucket math per instrument write and a scrape renders
    from window deltas without touching the serving loop's locks, so
    always-on telemetry must cost under 5%."""
    import tempfile

    sys.path.insert(0, _bench_dir())
    import serving as serving_bench

    # The horizon-sweep shape at h=4 (the dispatch-amortized serving
    # regime): a telemetry tax that hides at h=1's dispatch overhead
    # would still show here. The load runs ~2s on the CPU tiny model —
    # long enough that the 1s scraper fires at least twice inside the
    # measured window AND that run-to-run noise (±3% on short loads)
    # stays under the 5% bound being gated. Quick mode shrinks the
    # load and tightens the interval so the scraper still fires during
    # tier-1 smoke runs.
    requests = args.requests or (8 if args.quick else 256)
    load = ["--requests", str(requests),
            "--concurrency", "2" if args.quick else "6",
            "--max-batch-size", "2" if args.quick else "6",
            "--max-len", "48" if args.quick else "64",
            "--max-prefill-len", "8" if args.quick else "16",
            "--max-new-tokens", "4" if args.quick else "32",
            "--decode-horizon", "4", "--platform", platform]
    interval = 0.02 if args.quick else 1.0
    with tempfile.TemporaryDirectory(prefix="nezha-bench-scrape-") as td:
        base = serving_bench.run(
            serving_bench.build_parser().parse_args(
                load + ["--run-dir", os.path.join(td, "base"),
                        "--obs-windows", "off"]))
        scraped = serving_bench.run(
            serving_bench.build_parser().parse_args(
                load + ["--run-dir", os.path.join(td, "scraped"),
                        "--obs-windows", "on",
                        "--scrape-interval", str(interval)]))
    return {
        "load": f"closed loop h=4, {requests} requests, scrape every "
                f"{interval}s",
        "scrape_interval_s": interval,
        "baseline_capture_only": base,
        "windows_scraped": scraped,
        "scrapes": (scraped.get("telemetry") or {}).get("scrapes", 0),
        "tokens_per_sec_ratio_scraped_vs_baseline": (
            scraped["tokens_per_sec"]
            / max(base["tokens_per_sec"], 1e-9)),
    }


def _run_overload_storm(args, platform: str) -> dict:
    """The SLO-aware multi-tenant scheduling record (ISSUE 19
    acceptance): the SAME seeded open-loop Poisson mixed-priority
    arrival process twice in one process — WFQ + preemption ON (the
    storm pass) vs the exact pre-WFQ bounded FIFO as control
    (``--priority-scheduling off`` records each request's drawn class
    but submits every one into the single default lane;
    ``--preemption off``). Arrivals run well past service capacity,
    so the control's interactive requests queue behind batch and
    background work while the storm pass grants them first and
    preempts running background decodes to the KV trie / host tier.
    Hard gates: interactive TTFT p99 at <= 1.0x the FIFO control's,
    preemptions nonzero (the win must be earned by actual churn, not
    arrival luck), zero errors in either pass, and the batch +
    background classes all finishing — priority must never become
    starvation. Baseline drift of the p99 ratio is additionally held
    to --threshold when a committed record exists."""
    sys.path.insert(0, _bench_dir())
    import serving as serving_bench

    requests = args.requests or (36 if args.quick else 96)
    # Offered rate is far above the tiny model's service rate, so the
    # whole run arrives as one burst and the queue builds a deep
    # backlog in both passes; queue capacity covers the full run so
    # the completion gates never race arrival luck against drops.
    # Interactive traffic is deliberately the RARE class (~15%): the
    # scheduling win being recorded is an interactive request jumping
    # a queue of batch/background work, not interactive requests
    # contending with each other — and a sparse interactive stream
    # keeps preemption churn (each preempt+resume costs a re-prefill)
    # from eating the win on the prefill-heavy tiny model.
    rate = 250.0 if args.quick else 300.0
    mix = "interactive=0.15,batch=0.35,background=0.5"
    load = ["--requests", str(requests), "--mode", "open",
            "--rate", str(rate), "--seed", "19",
            "--priority-mix", mix,
            "--prompt-len-mix", "3,6", "--max-new-tokens", "16",
            "--max-batch-size", "2", "--max-len", "48",
            "--max-prefill-len", "8", "--kv-block-size", "4",
            "--queue-capacity", str(requests),
            "--sample-fraction", "0", "--platform", platform]
    storm = serving_bench.run(serving_bench.build_parser().parse_args(
        load + ["--preemption", "on"]))
    control = serving_bench.run(serving_bench.build_parser().parse_args(
        load + ["--priority-scheduling", "off"]))
    sp = storm["priorities"]
    cp = control["priorities"]
    s_ttft = sp["by_class"]["interactive"]["ttft_s"]["p99"]
    c_ttft = cp["by_class"]["interactive"]["ttft_s"]["p99"]
    return {
        "load": f"open loop, {requests} requests at {rate}/s offered, "
                f"mix {mix}, greedy, 2 slots",
        "storm": storm,
        "control_fifo": control,
        "preemptions": sp["preemptions"],
        "resumes": sp["resumes"],
        "errors": (storm["faults"]["errored"]
                   + control["faults"]["errored"]),
        "dropped": (storm["dropped_queue_full"]
                    + control["dropped_queue_full"]),
        "interactive_ttft_p99_s": s_ttft,
        "control_interactive_ttft_p99_s": c_ttft,
        "interactive_ttft_p99_vs_fifo": s_ttft / max(c_ttft, 1e-9),
        "by_class_finished": {
            cls: {"storm": sp["by_class"][cls]["finished"],
                  "control": cp["by_class"][cls]["finished"],
                  "drawn_storm": sp["by_class"][cls]["drawn"],
                  "drawn_control": cp["by_class"][cls]["drawn"]}
            for cls in ("interactive", "batch", "background")},
    }


def _run_decode_attention(args, platform: str) -> dict:
    sys.path.insert(0, _bench_dir())
    import decode_attention as da_bench

    argv = (["--batch-sizes", "2", "--max-lens", "64", "--iters", "3",
             "--warmup", "1", "--skews", "full,short"]
            if args.quick else
            ["--batch-sizes", "4", "--max-lens", "128",
             "--skews", "full,half,short,mixed"])
    return da_bench.run(da_bench.build_parser().parse_args(
        argv + ["--platform", platform]))


def _platform_slot(baseline: dict, platform: str) -> Optional[dict]:
    """A committed record's per-platform slot. Legacy flat records (no
    ``by_platform``) count as their labeled platform family (default
    cpu for the CPU-captured serving/decode records)."""
    if not isinstance(baseline, dict):
        return None
    by = baseline.get("by_platform")
    if isinstance(by, dict):
        return by.get(platform)
    label = str(baseline.get("platform")
                or baseline.get("backend") or "cpu")
    return baseline if label.startswith(platform) else None


def _serving_tps(record: dict) -> dict:
    sweep = record.get("closed_loop_horizon_sweep", record)
    by_h = sweep.get("by_horizon")
    if by_h is None:
        return {sweep.get("decode_horizon", 1):
                sweep.get("tokens_per_sec", 0.0)}
    return {h: r.get("tokens_per_sec", 0.0) for h, r in by_h.items()}


def _serving_trace_p50s(record: dict) -> dict:
    """The gateable TTFT-decomposition metrics of a serving sweep:
    ``{"trace.<segment>_p50@h<H>": seconds}`` for every timeline
    segment the record's stitched ``trace`` block carries (absent for
    pre-tracing baselines — those gate nothing here)."""
    sweep = record.get("closed_loop_horizon_sweep", record)
    by_h = sweep.get("by_horizon")
    if by_h is None:
        by_h = {str(sweep.get("decode_horizon", 1)): sweep}
    out = {}
    for h, rec in by_h.items():
        segs = ((rec.get("trace") or {}).get("segments")) or {}
        for seg, pct in segs.items():
            if isinstance(pct, dict) and pct.get("p50") is not None:
                out[f"trace.{seg}_p50@h{h}"] = float(pct["p50"])
    return out


def _decode_kernel_ms(record: dict) -> Optional[float]:
    cfgs = record.get("configs") or []
    vals = sorted(c["kernel_ms"] for c in cfgs if "kernel_ms" in c)
    return vals[len(vals) // 2] if vals else None


def _gate(results: dict, baselines: dict, platform: str,
          threshold: float) -> dict:
    """-> {suite: {metric: {current, baseline, ratio, ok}}} for every
    gated metric that has a same-platform baseline."""
    vs = {}
    srv_base = _platform_slot(baselines.get("serving") or {}, platform)
    if "serving" in results and srv_base:
        base_tps = _serving_tps(srv_base)
        cur_tps = _serving_tps(results["serving"])
        rows = {}
        for h, base in base_tps.items():
            cur = cur_tps.get(h)
            if cur is None or not base:
                continue
            ratio = cur / base
            rows[f"tokens_per_sec@h{h}"] = {
                "current": cur, "baseline": base, "ratio": ratio,
                "ok": ratio >= 1.0 - threshold}
        # TTFT-decomposition gates (ISSUE 12): each stitched timeline
        # segment's p50 is held to the baseline's, lower-is-better —
        # a regression names WHICH hop slowed down (prefill compute vs
        # queue wait vs migration transfer), not just that TTFT moved.
        # Segments the baseline lacks (pre-tracing records) or whose
        # baseline p50 is sub-millisecond (router_queue on an
        # in-process bench, microsecond-scale waits on the CPU
        # tiny-model run — scheduler jitter alone moves those past any
        # sane threshold) gate nothing. Latency segments are noisier
        # than throughput, so they share the deliberately loose
        # --threshold.
        base_tr = _serving_trace_p50s(srv_base)
        cur_tr = _serving_trace_p50s(results["serving"])
        for metric, base in base_tr.items():
            cur = cur_tr.get(metric)
            if cur is None or base <= 1e-3:
                continue
            ratio = cur / base
            rows[metric] = {
                "current": cur, "baseline": base, "ratio": ratio,
                "ok": ratio <= 1.0 + threshold}
        # Speculative-decode gates (ISSUE 13): tokens emitted per
        # verify dispatch and the spec-vs-classic throughput ratio,
        # both higher-is-better against the committed record (absent
        # for pre-speculation baselines — those gate nothing). A
        # machinery regression (accept mask broken, draft cache
        # desyncs -> rejects everything) shows up as tokens_per_verify
        # collapsing toward 1; a perf regression in the fused program
        # shows up in the ratio.
        # Sharded-serving gates (ISSUE 14) live in the serving rows —
        # see below after the spec gates.
        base_spec = srv_base.get("speculative_decode") or {}
        cur_spec = (results["serving"].get("speculative_decode")
                    or {})
        for metric in ("tokens_per_verify",
                       "tokens_per_sec_ratio_spec_vs_classic"):
            base = base_spec.get(metric)
            cur = cur_spec.get(metric)
            if base and cur is not None:
                ratio = cur / base
                rows[f"spec.{metric}"] = {
                    "current": cur, "baseline": base, "ratio": ratio,
                    "ok": ratio >= 1.0 - threshold}
        vs["serving"] = rows
    # Sharded-serving gates (ISSUE 14): greedy parity is a HARD
    # correctness gate (no baseline needed — bit-identical or the run
    # fails), and the sharded-vs-single TTFT/TPOT p50 ratios are held
    # to the committed record within --threshold (lower is better; a
    # regression means the mesh's collective overhead grew).
    # Tiered-KV churn gates (ISSUE 15): promote-hit TTFT must be at
    # most half the cold-prefill TTFT (the acceptance pin — a hard
    # gate, no baseline needed), and promotions must be nonzero (a
    # ratio earned by device-trie luck instead of the host tier would
    # otherwise pass vacuously). Baseline drift of the ratio is
    # additionally held to --threshold when a committed record exists.
    cur_ch = results.get("kv_churn")
    if cur_ch:
        rows = vs.setdefault("serving", {})
        ratio = cur_ch.get("promote_vs_cold_ttft_p50")
        if ratio is not None:
            rows["kv_churn.promote_vs_cold_ttft_p50"] = {
                "current": ratio, "baseline": 0.5,
                "ratio": ratio / 0.5, "ok": ratio <= 0.5}
        promos = cur_ch.get("promotions", 0)
        rows["kv_churn.promotions"] = {
            "current": float(promos), "baseline": 1.0,
            "ratio": float(promos), "ok": promos > 0}
        base_ch = (srv_base or {}).get("kv_churn") or {}
        base_ratio = base_ch.get("promote_vs_cold_ttft_p50")
        if base_ratio and ratio is not None:
            rows["kv_churn.promote_vs_cold_ttft_p50_vs_baseline"] = {
                "current": ratio, "baseline": base_ratio,
                "ratio": ratio / base_ratio,
                "ok": ratio / base_ratio <= 1.0 + threshold}
    # Fleet KV reuse gates (ISSUE 17): a digest-affinity revisit must
    # cost at most 0.7x a cold first visit (the acceptance pin — a
    # hard gate, no baseline needed), with affinity wins, committed
    # peer pulls, and peer-installed blocks all nonzero so the ratio
    # can't pass on single-pool residency luck. Baseline drift of the
    # ratio is additionally held to --threshold when a committed
    # record exists.
    cur_fl = results.get("fleet_kv")
    if cur_fl:
        rows = vs.setdefault("serving", {})
        ratio = cur_fl.get("revisit_vs_first_ttft_p50")
        if ratio is not None:
            rows["fleet_kv.revisit_vs_first_ttft_p50"] = {
                "current": ratio, "baseline": 0.7,
                "ratio": ratio / 0.7, "ok": ratio <= 0.7}
        for metric in ("affinity_wins", "kv_pulls", "peer_installed"):
            n = cur_fl.get(metric, 0)
            rows[f"fleet_kv.{metric}"] = {
                "current": float(n), "baseline": 1.0,
                "ratio": float(n), "ok": n > 0}
        base_fl = (srv_base or {}).get("fleet_kv") or {}
        base_ratio = base_fl.get("revisit_vs_first_ttft_p50")
        if base_ratio and ratio is not None:
            rows["fleet_kv.revisit_vs_first_ttft_p50_vs_baseline"] = {
                "current": ratio, "baseline": base_ratio,
                "ratio": ratio / base_ratio,
                "ok": ratio / base_ratio <= 1.0 + threshold}
    # Flash-prefill gates (ISSUE 18): the frozen program contract is a
    # HARD correctness gate on BOTH impls — the kernel replaces the
    # chunk attention + int8 write inside the per-bucket program and
    # must never add a compiled entry (no baseline needed). The
    # kernel-vs-masked TTFT ratio gates only on TPU against the
    # committed record; off-TPU the kernel runs in interpret mode and
    # the ratio is a recorded correctness artifact, not a perf claim.
    cur_fp = results.get("flash_prefill")
    if cur_fp:
        rows = vs.setdefault("serving", {})
        expected = cur_fp.get("programs_expected")
        for impl in ("kernel", "masked"):
            n = cur_fp.get(f"programs_{impl}")
            if expected and n is not None:
                rows[f"flash_prefill.frozen_programs_{impl}"] = {
                    "current": float(n), "baseline": float(expected),
                    "ratio": n / expected, "ok": n == expected}
        if platform == "tpu":
            ratio = cur_fp.get("ttft_p50_ratio_kernel_vs_masked")
            base_fp = (srv_base or {}).get("flash_prefill") or {}
            base_ratio = base_fp.get("ttft_p50_ratio_kernel_vs_masked")
            if base_ratio and ratio is not None:
                rows["flash_prefill.ttft_p50_ratio_vs_baseline"] = {
                    "current": ratio, "baseline": base_ratio,
                    "ratio": ratio / base_ratio,
                    "ok": ratio / base_ratio <= 1.0 + threshold}
    # Long-context gates (ISSUE 20): bit-identical greedy parity
    # between the mesh-2 sequence-sharded engine and the single-device
    # replicated engine is a HARD correctness gate (bf16 and int8
    # passes, no baseline needed — sequence sharding is a pure
    # execution-strategy change). The mesh-2-vs-mesh-1 prefill
    # tokens/s ratio gates only on TPU against the 1.5x acceptance
    # floor; off-TPU the composed/interpret attention makes the ratio
    # a recorded correctness artifact, not a perf claim.
    cur_lc = results.get("long_context")
    if cur_lc:
        rows = vs.setdefault("serving", {})
        for key in ("greedy_parity", "greedy_parity_int8"):
            par = cur_lc.get(key)
            if par is not None:
                rows[f"long_context.{key}"] = {
                    "current": 1.0 if par else 0.0, "baseline": 1.0,
                    "ratio": 1.0 if par else 0.0, "ok": bool(par)}
        if platform == "tpu":
            ratio = cur_lc.get("prefill_tps_ratio_mesh2_vs_mesh1")
            if ratio is not None:
                rows["long_context.prefill_tps_ratio_mesh2_vs_mesh1"] \
                    = {"current": ratio, "baseline": 1.5,
                       "ratio": ratio / 1.5, "ok": ratio >= 1.5}
    # Scrape-overhead gate (ISSUE 16): rolling windows + a 1s /metrics
    # scraper must keep closed-loop tokens/sec within 5% of the
    # capture-only baseline measured in the SAME process — a hard
    # gate with a fixed 0.95 floor, no committed baseline needed (the
    # two passes ARE each other's baseline). --threshold deliberately
    # does not loosen it: the 5% bound is the acceptance pin itself.
    cur_sc = results.get("scrape_overhead")
    if cur_sc:
        rows = vs.setdefault("serving", {})
        ratio = cur_sc.get("tokens_per_sec_ratio_scraped_vs_baseline")
        if ratio is not None:
            rows["scrape_overhead.tokens_per_sec_ratio"] = {
                "current": ratio, "baseline": 0.95,
                "ratio": ratio / 0.95, "ok": ratio >= 0.95}
    # Overload-storm gates (ISSUE 19): under the same overcapacity
    # mixed-priority arrivals, WFQ + preemption must hold interactive
    # TTFT p99 at or below the FIFO control's (the acceptance pin — a
    # hard gate, no baseline needed), with preemptions nonzero so the
    # win is earned by actual churn, zero errors/drops in either pass,
    # and the batch + background classes finishing everything drawn —
    # priority must never become starvation. Baseline drift of the
    # p99 ratio is additionally held to --threshold when a committed
    # record exists.
    cur_os = results.get("overload_storm")
    if cur_os:
        rows = vs.setdefault("serving", {})
        ratio = cur_os.get("interactive_ttft_p99_vs_fifo")
        if ratio is not None:
            rows["overload_storm.interactive_ttft_p99_vs_fifo"] = {
                "current": ratio, "baseline": 1.0,
                "ratio": ratio, "ok": ratio <= 1.0}
        preempts = cur_os.get("preemptions", 0)
        rows["overload_storm.preemptions"] = {
            "current": float(preempts), "baseline": 1.0,
            "ratio": float(preempts), "ok": preempts > 0}
        for metric in ("errors", "dropped"):
            n = cur_os.get(metric, 0)
            rows[f"overload_storm.{metric}"] = {
                "current": float(n), "baseline": 0.0,
                "ratio": float(n), "ok": n == 0}
        for cls, counts in (cur_os.get("by_class_finished")
                            or {}).items():
            ok = (counts["storm"] == counts["drawn_storm"]
                  and counts["control"] == counts["drawn_control"])
            rows[f"overload_storm.{cls}_all_finished"] = {
                "current": float(counts["storm"]),
                "baseline": float(counts["drawn_storm"]),
                "ratio": (counts["storm"]
                          / max(counts["drawn_storm"], 1)),
                "ok": ok}
        base_os = (srv_base or {}).get("overload_storm") or {}
        base_ratio = base_os.get("interactive_ttft_p99_vs_fifo")
        if base_ratio and ratio is not None:
            rows["overload_storm.interactive_p99_vs_baseline"] = {
                "current": ratio, "baseline": base_ratio,
                "ratio": ratio / base_ratio,
                "ok": ratio / base_ratio <= 1.0 + threshold}
    cur_sh = results.get("sharded_serve")
    if cur_sh:
        rows = vs.setdefault("serving", {})
        par = cur_sh.get("greedy_parity")
        if par is not None:
            rows["sharded.greedy_parity"] = {
                "current": 1.0 if par else 0.0, "baseline": 1.0,
                "ratio": 1.0 if par else 0.0, "ok": bool(par)}
        base_sh = (srv_base or {}).get("sharded_serve") or {}
        for metric in ("ttft_p50_ratio_vs_single",
                       "tpot_p50_ratio_vs_single"):
            for m, cur in (cur_sh.get(metric) or {}).items():
                base = (base_sh.get(metric) or {}).get(m)
                if base and cur is not None:
                    ratio = cur / base
                    rows[f"sharded.{metric}@mesh{m}"] = {
                        "current": cur, "baseline": base,
                        "ratio": ratio,
                        "ok": ratio <= 1.0 + threshold}
    da_base = _platform_slot(baselines.get("decode_attention") or {},
                             platform)
    if "decode_attention" in results and da_base:
        base_ms = _decode_kernel_ms(da_base)
        cur_ms = _decode_kernel_ms(results["decode_attention"])
        if base_ms and cur_ms:
            ratio = cur_ms / base_ms
            vs["decode_attention"] = {"kernel_ms_median": {
                "current": cur_ms, "baseline": base_ms, "ratio": ratio,
                "ok": ratio <= 1.0 + threshold}}
    return vs


def _flatten_ok(vs: dict) -> List[str]:
    bad = []
    for suite, rows in vs.items():
        for metric, row in rows.items():
            if isinstance(row, dict) and row.get("ok") is False:
                bad.append(f"{suite}.{metric}: {row['current']:.3f} vs "
                           f"baseline {row['baseline']:.3f} "
                           f"(ratio {row['ratio']:.2f})")
    return bad


def _load(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _update_baseline(path: str, baseline: Optional[dict],
                     platform: str, slot: dict, what: str) -> None:
    """Write ``slot`` into the record's ``by_platform[platform]``,
    preserving every other platform's slot (a CPU fallback run can
    never clobber the TPU anchor). Legacy flat records are migrated
    into their labeled platform's slot first."""
    record = baseline if isinstance(baseline, dict) else {}
    by = record.get("by_platform")
    if not isinstance(by, dict):
        by = {}
        legacy = {k: v for k, v in record.items()
                  if k not in ("what", "command", "by_platform")}
        if legacy:
            label = str(record.get("platform")
                        or record.get("backend") or "cpu").split()[0]
            by[label] = legacy
        record = {"what": record.get("what", what),
                  "command": record.get("command", "nezha-bench"),
                  "by_platform": by}
    by[platform] = slot
    record["by_platform"] = by
    with open(path, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")


def run(args) -> dict:
    suites = [s.strip() for s in str(args.suites).split(",") if s.strip()]
    bad_suites = set(suites) - {"serving", "decode_attention",
                                "sharded_serve", "kv_churn",
                                "fleet_kv", "flash_prefill",
                                "long_context",
                                "scrape_overhead", "overload_storm"}
    if bad_suites:
        raise SystemExit(f"unknown suite(s) {sorted(bad_suites)}")
    if args.threshold <= 0:
        raise SystemExit(f"--threshold must be > 0, got {args.threshold}")
    platform = _resolve_platform(args.platform)

    results = {}
    if "serving" in suites:
        results["serving"] = _run_serving(args, platform)
    if "sharded_serve" in suites:
        results["sharded_serve"] = _run_sharded_serve(args, platform)
    if "kv_churn" in suites:
        results["kv_churn"] = _run_kv_churn(args, platform)
    if "fleet_kv" in suites:
        results["fleet_kv"] = _run_fleet_kv(args, platform)
    if "flash_prefill" in suites:
        results["flash_prefill"] = _run_flash_prefill(args, platform)
    if "long_context" in suites:
        results["long_context"] = _run_long_context(args, platform)
    if "scrape_overhead" in suites:
        results["scrape_overhead"] = _run_scrape_overhead(args, platform)
    if "overload_storm" in suites:
        results["overload_storm"] = _run_overload_storm(args, platform)
    if "decode_attention" in suites:
        results["decode_attention"] = _run_decode_attention(args,
                                                            platform)

    baselines = {"serving": _load(args.serving_baseline),
                 "decode_attention": _load(args.decode_baseline)}
    vs = _gate(results, baselines, platform, args.threshold)
    regressions = _flatten_ok(vs)
    record = {
        "platform": platform,
        "quick": bool(args.quick),
        "threshold": args.threshold,
        "results": results,
        "vs_baseline": vs,
        "regressions": regressions,
        "ok": not regressions,
    }
    if args.update:
        if ("serving" in results or "sharded_serve" in results
                or "kv_churn" in results or "fleet_kv" in results
                or "flash_prefill" in results
                or "long_context" in results
                or "scrape_overhead" in results
                or "overload_storm" in results):
            # The sharded_serve and kv_churn records ride INSIDE the
            # serving slot (one committed BENCH_serving.json). A
            # partial-suite --update preserves whatever the other
            # suites committed last — a serving-only rerun can never
            # drop the sharded or churn record, and vice versa.
            prev = _platform_slot(baselines.get("serving") or {},
                                  platform) or {}
            slot = (dict(results["serving"]) if "serving" in results
                    else dict(prev))
            for rider in ("sharded_serve", "kv_churn", "fleet_kv",
                          "flash_prefill", "long_context",
                          "scrape_overhead", "overload_storm"):
                if rider in results:
                    slot[rider] = results[rider]
                elif rider in prev:
                    slot.setdefault(rider, prev[rider])
            _update_baseline(args.serving_baseline,
                             baselines["serving"], platform, slot,
                             "nezha-bench serving sweep")
        if "decode_attention" in results:
            _update_baseline(args.decode_baseline,
                             baselines["decode_attention"], platform,
                             results["decode_attention"],
                             "nezha-bench decode-attention microbench")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
    if args.json:
        print(json.dumps(record, indent=2, sort_keys=True))
    else:
        for suite, rows in vs.items():
            for metric, row in rows.items():
                mark = "OK " if row.get("ok") else "REGRESSED"
                print(f"{mark} {suite}.{metric}: {row['current']:.3f} "
                      f"(baseline {row['baseline']:.3f}, ratio "
                      f"{row['ratio']:.2f})")
        if not vs:
            print(f"no {platform} baseline to gate against"
                  + (" — seeded" if args.update else
                     " (run with --update to seed one)"))
    return record


def main(argv=None) -> int:
    record = run(build_parser().parse_args(argv))
    if not record["ok"]:
        for line in record["regressions"]:
            print(f"REGRESSION: {line}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
