"""`nezha-export` — convert a `nezha-train` checkpoint to Hugging Face
weights.

Closes the interchange loop (models/convert.py maps both directions for
GPT-2 and BERT): train here, export to the HF key layout, load in torch.
Output formats:

- ``--format npz`` (default): one .npz of HF-keyed numpy arrays — no torch
  needed to write or read (`np.load`; torch users: `torch.tensor(z[k])`).
- ``--format torch``: a ``pytorch_model.bin`` state dict via torch.save,
  directly loadable by ``GPT2LMHeadModel``/``BertForMaskedLM``
  ``load_state_dict`` (requires the baked-in cpu torch).

    nezha-export --config gpt2_124m --ckpt-dir runs/gpt2 \
        --out gpt2_hf.npz
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nezha-export", description=__doc__)
    p.add_argument("--config", required=True,
                   choices=["gpt2_124m", "bert_base_zero1"],
                   help="which trained architecture the checkpoint holds "
                        "(GPT-2 -> GPT2LMHeadModel keys, BERT -> "
                        "BertForMaskedLM keys)")
    p.add_argument("--ckpt-dir", required=True,
                   help="checkpoint dir written by nezha-train (npz or "
                        "per-shard format — restore handles either)")
    p.add_argument("--model-preset", choices=["full", "tiny"],
                   default="full",
                   help="must match the preset the checkpoint was trained "
                        "with (mirrors nezha-train)")
    p.add_argument("--out", required=True, help="output file path")
    p.add_argument("--format", choices=["npz", "torch"], default="npz")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu)")
    return p


def _restore_params(args, model, optimizer):
    from nezha_tpu.cli.common import restore_variables_any

    return restore_variables_any(args.ckpt_dir, model, optimizer)["params"]


def run(args) -> dict:
    import jax

    from nezha_tpu.cli.common import setup_jax
    setup_jax(args)

    from nezha_tpu import optim
    from nezha_tpu.models import convert
    from nezha_tpu.models.bert import Bert, BertConfig
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config

    # Restore templates need the param SHAPES only (master params are fp32
    # under every policy), so default-policy models suffice.
    # --scan-layers checkpoints store the trunk stacked (h_scan /
    # layers_scan); restore with the matching template, then unstack to
    # the per-layer layout the HF conversions name.
    from nezha_tpu.cli.common import ckpt_has_scan_trunk
    scan = ckpt_has_scan_trunk(args.ckpt_dir)
    if args.config == "gpt2_124m":
        if args.model_preset == "full":
            model = GPT2(GPT2Config(scan_layers=scan))
        else:
            from nezha_tpu.cli.train import TINY_GPT2_KW
            model = GPT2(GPT2Config(**TINY_GPT2_KW, scan_layers=scan))
        params = _restore_params(args, model, optim.sgd(0.1))
        if scan:
            from nezha_tpu.models.gpt2 import unstack_layer_params
            params = unstack_layer_params(params, model.cfg.num_layers)
        state_dict = convert.gpt2_params_to_hf(
            jax.device_get(params), model.cfg.num_layers)
    else:
        if args.model_preset == "full":
            cfg = BertConfig(scan_layers=scan)
        else:
            from nezha_tpu.cli.train import TINY_BERT_KW
            cfg = BertConfig(**TINY_BERT_KW, scan_layers=scan)
        model = Bert(cfg)
        params = _restore_params(args, model, optim.sgd(0.1))
        if scan:
            from nezha_tpu.nn.module import unstack_prefixed_params
            params = unstack_prefixed_params(params, "layers",
                                             cfg.num_layers, "layers_scan")
        state_dict = convert.bert_params_to_hf(
            jax.device_get(params), cfg.num_layers, cfg.hidden_size)

    state_dict = {k: np.asarray(v, np.float32)
                  for k, v in state_dict.items()}
    out_path = args.out
    if args.format == "npz":
        # np.savez silently appends .npz — normalize FIRST so the reported
        # path is the real one.
        if not out_path.endswith(".npz"):
            out_path += ".npz"
        np.savez(out_path, **state_dict)
    else:
        import torch

        torch.save({k: torch.tensor(v) for k, v in state_dict.items()},
                   out_path)
    result = {"keys": len(state_dict), "format": args.format,
              "out": out_path}
    print(json.dumps(result))
    return result


def main(argv=None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
