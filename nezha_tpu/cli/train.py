"""`nezha-train`: run any of the five benchmark configs end-to-end.

    python -m nezha_tpu.cli.train --config mlp_mnist --steps 200
    python -m nezha_tpu.cli.train --config resnet50_imagenet --mesh dp=8 \
        --batch-size 256 --steps 50 --platform cpu

Configs mirror BASELINE.json (SURVEY.md §0): mlp_mnist (single-process),
resnet50_imagenet (DP all-reduce), gpt2_124m (bf16 GEMM), bert_base_zero1
(ZeRO-1 reduce-scatter/all-gather), wrn101_large_batch (mixed bf16/fp32).
"""

from __future__ import annotations

import argparse
import itertools
import json
import re
import sys
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np


def _parse_mesh(spec: Optional[str]) -> Optional[Dict[str, int]]:
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    return axes


class Config:
    def __init__(self, build_model: Callable, loss_fn: Callable,
                 batches: Callable[[int], Iterator[dict]],
                 build_optimizer: Callable, default_batch: int,
                 parallel_mode: str = "dp",
                 eval_batches: Optional[Callable] = None,
                 eval_stat: Optional[Callable] = None,
                 tiny: Optional[Dict[str, Callable]] = None,
                 tp_rules=None, pipeline_spec: Optional[Callable] = None,
                 sp_model: Optional[Callable] = None,
                 graph_opt: Optional[Dict[str, Any]] = None):
        self.build_model = build_model
        self.loss_fn = loss_fn
        self.batches = batches
        self.build_optimizer = build_optimizer
        self.default_batch = default_batch
        self.parallel_mode = parallel_mode  # default --parallel for the config
        self.eval_batches = eval_batches  # bs -> finite iterator, or None
        self.eval_stat = eval_stat        # stat fn for train.eval.evaluate
        # --model-preset tiny: field overrides (build_model/batches/...)
        # producing a seconds-scale variant for CLI mechanics tests.
        self.tiny = tiny
        # Advanced parallelism hooks (None = the mode is unsupported here):
        self.tp_rules = tp_rules            # gspmd Megatron rule table
        self.pipeline_spec = pipeline_spec  # model -> PipelineSpec
        self.sp_model = sp_model            # attn_impl -> Module (seq-par)
        # Graph-engine optimizer pieces ({"schedule": steps -> sched,
        # "weight_decay": float}) — shared with build_optimizer so the two
        # engines can't drift apart.
        self.graph_opt = graph_opt


# The tiny GPT-2 preset's hyperparameters — one definition shared by the
# train and generate CLIs so a `--model-preset tiny` checkpoint always
# round-trips (fp32 DEFAULT_POLICY, for tight mode-vs-mode tolerances).
TINY_GPT2_KW = dict(vocab_size=512, max_positions=96, num_layers=4,
                    num_heads=4, hidden_size=64)
TINY_BERT_KW = dict(vocab_size=512, max_positions=96, num_layers=2,
                    num_heads=4, hidden_size=64)


def _configs() -> Dict[str, Config]:
    # Imports deferred so `--help` stays instant.
    from nezha_tpu import data, models, ops, optim
    from nezha_tpu.models import bert as bert_mod
    from nezha_tpu.models import gpt2 as gpt2_mod
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train import eval as eval_mod

    from nezha_tpu.parallel import BERT_TP_RULES, GPT2_TP_RULES
    from nezha_tpu.parallel import pipeline as pp_mod

    ce = lambda logits, b: ops.softmax_cross_entropy_with_integer_labels(
        logits, b["label"])

    # Tiny presets run the same code paths at seconds scale (fp32 for the
    # transformers so mode-vs-mode numerics tests have tight tolerances).
    def tiny_gpt2(**overrides):
        kw = dict(TINY_GPT2_KW)
        kw.update(overrides)
        return models.GPT2(models.GPT2Config(**kw))

    def tiny_bert(**overrides):
        kw = dict(TINY_BERT_KW)
        kw.update(overrides)
        return models.Bert(bert_mod.BertConfig(**kw))

    tiny_tokens = lambda bs, seq_len=64, **kw: data.synthetic_token_batches(
        bs, seq_len=seq_len, vocab_size=512, **kw)
    tiny_images = lambda bs: data.synthetic_image_batches(
        bs, image_size=32, num_classes=100)

    # One schedule factory per config for BOTH engines (module adamw +
    # graph AdamW-update programs) — tuning it here tunes them together.
    gpt2_sched = lambda steps: optim.warmup_cosine_schedule(
        6e-4, 100, max(steps, 200))
    bert_sched = lambda steps: optim.warmup_cosine_schedule(
        1e-4, 100, max(steps, 200))

    return {
        "mlp_mnist": Config(
            build_model=lambda: models.MLP(),
            loss_fn=ce,
            batches=lambda bs: data.mnist_batches(bs),
            build_optimizer=lambda steps: optim.momentum(0.1),
            default_batch=128,
            parallel_mode="single",
            eval_batches=lambda bs: data.mnist_batches(bs, split="test",
                                                       epochs=1),
            eval_stat=eval_mod.accuracy,
            tiny={}),  # already seconds-scale
        "resnet50_imagenet": Config(
            build_model=lambda **ov: models.resnet50(
                stem="s2d", policy=bf16_policy(), **ov),
            loss_fn=ce,
            batches=lambda bs: data.synthetic_image_batches(bs),
            build_optimizer=lambda steps: optim.momentum(
                optim.warmup_cosine_schedule(0.4, 5 * 312, max(steps, 10)),
                beta=0.9, weight_decay=1e-4),
            default_batch=256,
            parallel_mode="dp",
            tiny={"build_model": lambda **ov: models.ResNet(
                      (1, 1), num_classes=100, policy=bf16_policy(), **ov),
                  "batches": tiny_images}),
        "gpt2_124m": Config(
            # fused_loss_chunk=-1: CE never materializes fp32 [B,S,V]
            # logits (see GPT2Config) — the training-CLI default.
            build_model=lambda **ov: models.gpt2_124m(fused_loss_chunk=-1,
                                                      **ov),
            loss_fn=gpt2_mod.lm_loss,
            batches=lambda bs, seq_len=1024: data.synthetic_token_batches(
                bs, seq_len=seq_len),
            build_optimizer=lambda steps, **kw: optim.adamw(
                gpt2_sched(steps), weight_decay=0.1, **kw),
            default_batch=8,
            parallel_mode="dp",
            eval_batches=lambda bs, seq_len=1024: itertools.islice(
                data.synthetic_token_batches(bs, seq_len=seq_len, seed=1),
                8),
            eval_stat=eval_mod.lm_token_stats,
            tiny={"build_model": tiny_gpt2,
                  "batches": tiny_tokens,
                  "eval_batches": lambda bs, seq_len=64: itertools.islice(
                      tiny_tokens(bs, seed=1, seq_len=seq_len), 4),
                  "sp_model": lambda impl, **ov: tiny_gpt2(
                      attn_impl=impl, fused_loss_chunk=-1, **ov)},
            tp_rules=GPT2_TP_RULES,
            pipeline_spec=pp_mod.gpt2_pipeline_spec,
            sp_model=lambda impl, **ov: models.gpt2_124m(
                attn_impl=impl, fused_loss_chunk=-1, **ov),
            graph_opt={"schedule": gpt2_sched, "weight_decay": 0.1}),
        "bert_base_zero1": Config(
            # fused_loss_chunk=-1: bf16 MLM logits with the fp32 upcast
            # fused into logsumexp (same default as gpt2_124m's head).
            build_model=lambda **ov: models.bert_base(fused_loss_chunk=-1,
                                                      **ov),
            loss_fn=bert_mod.mlm_loss,
            batches=lambda bs: data.synthetic_mlm_batches(bs, seq_len=512),
            build_optimizer=lambda steps, **kw: optim.adamw(
                bert_sched(steps), weight_decay=0.01, **kw),
            default_batch=16,
            parallel_mode="zero1",
            eval_batches=lambda bs: itertools.islice(
                data.synthetic_mlm_batches(bs, seq_len=512, seed=1), 8),
            eval_stat=eval_mod.mlm_token_stats,
            tiny={"build_model": tiny_bert,
                  "batches": lambda bs: data.synthetic_mlm_batches(
                      bs, seq_len=64, vocab_size=512, mask_token=1),
                  "eval_batches": lambda bs: itertools.islice(
                      data.synthetic_mlm_batches(bs, seq_len=64,
                                                 vocab_size=512,
                                                 mask_token=1, seed=1), 4)},
            tp_rules=BERT_TP_RULES,
            graph_opt={"schedule": bert_sched, "weight_decay": 0.01}),
        "wrn101_large_batch": Config(
            build_model=lambda **ov: models.wide_resnet101(
                stem="s2d", policy=bf16_policy(), **ov),
            loss_fn=ce,
            batches=lambda bs: data.synthetic_image_batches(bs),
            build_optimizer=lambda steps: optim.momentum(
                optim.warmup_cosine_schedule(1.6, 500, max(steps, 1000)),
                beta=0.9, weight_decay=1e-4),
            default_batch=512,
            parallel_mode="dp",
            tiny={"build_model": lambda **ov: models.ResNet(
                      (1, 1), num_classes=100, width_factor=2,
                      policy=bf16_policy(), **ov),
                  "batches": tiny_images}),
    }


def _join_world(args):
    """Multi-process launch: dial the coordinator before touching devices
    (SURVEY.md §3 call stack 1 — the reference dialed its gRPC coordinator
    for rank/world rendezvous, then initialized the device runtime).
    Returns (group, coordinator) — either may be None."""
    if not args.coordinator:
        return None, None
    from nezha_tpu import dist
    from nezha_tpu.utils import get_logger, set_rank

    host, _, port = args.coordinator.rpartition(":")
    coord = None
    if args.serve_coordinator:
        coord = dist.Coordinator(world_size=args.world_size, port=int(port))
    group = dist.join(host or "127.0.0.1", int(port),
                      rank_hint=args.rank_hint)
    set_rank(group.rank)
    get_logger("nezha_tpu.cli").info(
        "joined world: rank %d / %d", group.rank, group.world_size)
    if group.world_size > 1 and not args.no_jax_distributed:
        # Rank 0 advertises the jax.distributed address; all ranks enter.
        dist.initialize_jax_distributed(group)
    return group, coord


_IMAGE_CONFIGS = ("resnet50_imagenet", "wrn101_large_batch")


def _nzr_count(path) -> int:
    """Record count from an NZR1 header (magic + int32 n,h,w,c)."""
    with open(path, "rb") as f:
        header = f.read(8)
    return int(np.frombuffer(header[4:8], np.int32)[0])


def _slice_rows(it: Iterator[dict], rank: int, local: int) -> Iterator[dict]:
    """Rows [rank*local, (rank+1)*local) of each globally-identical batch —
    turns a same-seed synthetic stream into per-host-distinct local rows."""
    for b in it:
        yield {k: v[rank * local:(rank + 1) * local] for k, v in b.items()}


def _data_source(args, cfg, batch_size: int, group=None):
    """Training batches: real records via the native C++ loaders when
    ``--data-dir`` holds them (SURVEY.md §2 data loaders), synthetic
    fallback otherwise. Returns (iterator, closer).

    With ``group`` set (multi-process dp/zero1), ``batch_size`` is the
    GLOBAL batch and each host yields only its batch_size/world local rows:
    record loaders read a disjoint shard of each epoch (same-seed shuffle,
    batches ``b % world == rank``, zero coordination traffic), token
    loaders draw a decorrelated window stream, and synthetic streams are
    row-sliced out of the same-seed global batch. The per-mode ``shard``
    fn then assembles the global array from process-local rows
    (``parallel.shard_batch_process_local``)."""
    import os

    world = group.world_size if group is not None else 1
    rank = group.rank if group is not None else 0
    local = batch_size // world
    shard = {"shard_index": rank, "shard_count": world} if world > 1 else {}
    if args.data_dir:
        from nezha_tpu.data.native import ImageRecordLoader, TokenLoader
        if args.config in _IMAGE_CONFIGS:
            rec = os.path.join(args.data_dir, "train.nzr")
            if os.path.exists(rec):
                loader = ImageRecordLoader(rec, local, crop=args.crop,
                                           seed=args.seed, train_augment=True,
                                           **shard)
                print(f"data: {loader.num_examples} image records from {rec}"
                      + (f" (shard {rank}/{world})" if shard else ""),
                      file=sys.stderr)
                return iter(loader), loader.close
        elif args.config == "gpt2_124m":
            for name, dtype in (("train.tokens.u16", np.uint16),
                                ("train.tokens.i32", np.int32)):
                tok = os.path.join(args.data_dir, name)
                if os.path.exists(tok):
                    # Loud range check (mirrors the MLM path): ids at or
                    # beyond the model vocab NaN the CE via out-of-range
                    # target gathers — with no diagnostic at all. Sample
                    # the stream and refuse up front.
                    vocab = cfg.build_model().cfg.vocab_size
                    sample = np.fromfile(tok, dtype=dtype, count=65536)
                    if sample.size and int(sample.max()) >= vocab:
                        raise SystemExit(
                            f"{tok} holds token ids up to "
                            f"{int(sample.max())} but the model vocab is "
                            f"{vocab}; re-pack with a matching tokenizer "
                            f"(nezha-pack-text --tokenizer/--learn-bpe) "
                            f"or train the full-vocab preset")
                    loader = TokenLoader(tok, seq_len=args.seq_len or 1024,
                                         batch_size=local, dtype=dtype,
                                         seed=args.seed, **shard)
                    print(f"data: {loader.num_tokens} tokens from {tok}"
                          + (f" (shard {rank}/{world})" if shard else ""),
                          file=sys.stderr)
                    return iter(loader), loader.close
        elif args.config == "bert_base_zero1":
            # MLM pretraining on the same packed-token format as GPT-2:
            # random [B, S] windows + dynamic masking per batch
            # (data/mlm.py; 80/10/10 recipe, labels -100 off-prediction).
            from nezha_tpu.data.mlm import mlm_batches_from_tokens
            # Geometry comes from the ACTUAL model config (module
            # construction is paramless and cheap), so preset/default
            # edits can't drift the data path out from under the model.
            mcfg = cfg.build_model().cfg
            seq, vocab = mcfg.max_positions, mcfg.vocab_size
            for name, dtype in (("train.tokens.u16", np.uint16),
                                ("train.tokens.i32", np.int32)):
                tok = os.path.join(args.data_dir, name)
                if os.path.exists(tok):
                    mask_token = _resolve_mlm_mask_token(
                        args, mcfg, tok,
                        np.fromfile(tok, dtype=dtype, count=32768))
                    loader = TokenLoader(tok, seq_len=seq, batch_size=local,
                                         dtype=dtype, seed=args.seed,
                                         **shard)
                    print(f"data: {loader.num_tokens} tokens from {tok} "
                          f"(dynamic MLM masking, mask_token="
                          f"{mask_token})"
                          + (f" (shard {rank}/{world})" if shard else ""),
                          file=sys.stderr)
                    it = mlm_batches_from_tokens(
                        iter(loader), vocab_size=vocab,
                        mask_token=mask_token, seed=args.seed,
                        drop_last_column=True)
                    return it, loader.close
        elif args.config == "mlp_mnist":
            os.environ.setdefault("NEZHA_DATA_DIR", args.data_dir)
            if os.path.isdir(os.path.join(args.data_dir, "mnist")):
                print(f"data: MNIST IDX files from {args.data_dir}/mnist",
                      file=sys.stderr)
                it = cfg.batches(batch_size)
                return (_slice_rows(it, rank, local) if world > 1 else it,
                        None)
        print(f"data: no records for {args.config} in {args.data_dir}; "
              f"using synthetic data", file=sys.stderr)
    it = cfg.batches(batch_size)
    return (_slice_rows(it, rank, local) if world > 1 else it), None


def _mask_token_from_corpus_sidecar(tok_path: str) -> Optional[int]:
    """The packed corpus's OWN [MASK] id, when discoverable: the
    ``<tokens>.meta.json`` sidecar nezha-pack-text writes (carries the
    packing tokenizer's mask id), else a ``vocab.txt`` sitting next to the
    tokens file (the `--save-tokenizer <data-dir>` layout). None when
    neither exists."""
    import os

    meta_path = tok_path + ".meta.json"
    if os.path.isfile(meta_path):
        try:
            with open(meta_path, encoding="utf-8") as f:
                meta = json.load(f)
        except (OSError, ValueError):
            meta = {}
        if meta.get("mask_token_id") is not None:
            return int(meta["mask_token_id"])
    vocab_txt = os.path.join(os.path.dirname(os.path.abspath(tok_path)),
                             "vocab.txt")
    if os.path.isfile(vocab_txt):
        with open(vocab_txt, encoding="utf-8") as f:
            for i, line in enumerate(f):
                if line.rstrip("\n") == "[MASK]":
                    return i
    return None


def _resolve_mlm_mask_token(args, mcfg, tok_path: str, sample_ids) -> int:
    """MLM mask id for a packed-token file: the explicit flag; else the
    corpus's own tokenizer metadata (pack-text meta sidecar or an adjacent
    vocab.txt — a --learn-wordpiece vocab puts [MASK] at id 4, where the
    103 convention would silently collide with a real subword, ADVICE r5);
    else the BERT-wordpiece default 103 — refused when the corpus looks
    byte-packed (every sampled id < 256), where 103 is a REAL byte value
    and genuine 0x67 tokens would be indistinguishable from [MASK]
    (ADVICE r4). ONE resolution shared by the train and held-out-eval
    paths."""
    import numpy as np

    if args.mlm_mask_token is not None:
        return args.mlm_mask_token
    resolved = _mask_token_from_corpus_sidecar(tok_path)
    if resolved is not None:
        if resolved >= mcfg.vocab_size:
            raise SystemExit(
                f"{tok_path}: the corpus tokenizer's [MASK] id {resolved} "
                f"is outside the model vocab ({mcfg.vocab_size}); the "
                f"corpus and model vocabularies do not match")
        print(f"mlm: [MASK] id {resolved} resolved from the corpus "
              f"tokenizer metadata next to {tok_path}", file=sys.stderr)
        return resolved
    mask_token = min(103, mcfg.vocab_size - 1)
    sample = np.asarray(sample_ids).ravel()
    if sample.size and int(sample.max()) < 256:
        raise SystemExit(
            f"{tok_path} looks byte-packed (sampled ids all < 256), so "
            f"the default mask_token {mask_token} is a real byte value; "
            f"pass an explicit --mlm-mask-token (>= 256 reserves an id "
            f"byte data cannot produce) or use a WordPiece-tokenized "
            f"corpus")
    return mask_token


def _eval_source(args, cfg, batch_size: int):
    """Eval batches: val.nzr records (deterministic center crop) for the
    CNN configs when present, else the config's built-in eval split.
    Returns (iterator, closer, stat_fn) — iterator None means no eval."""
    import os

    from nezha_tpu.train import eval as eval_mod

    if args.data_dir and args.config in _IMAGE_CONFIGS:
        rec = os.path.join(args.data_dir, "val.nzr")
        if os.path.exists(rec):
            from nezha_tpu.data.native import ImageRecordLoader
            # Largest batch <= requested that divides the record count:
            # the loader emits only full batches per epoch, so any other
            # choice silently drops the tail and biases the accuracy (and
            # a batch > n would be rejected outright).
            n = _nzr_count(rec)
            bs = max(d for d in range(1, min(batch_size, n) + 1)
                     if n % d == 0)
            if bs != batch_size:
                print(f"eval: batch {batch_size} -> {bs} to cover all "
                      f"{n} val records exactly", file=sys.stderr)
            loader = ImageRecordLoader(rec, bs, crop=args.crop,
                                       train_augment=False, epochs=1)
            print(f"eval: {n} val records from {rec}", file=sys.stderr)
            return iter(loader), loader.close, eval_mod.accuracy
    if args.data_dir and args.config in ("gpt2_124m", "bert_base_zero1"):
        import numpy as np
        for name, dtype in (("val.tokens.u16", np.uint16),
                            ("val.tokens.i32", np.int32)):
            tok = os.path.join(args.data_dir, name)
            if os.path.exists(tok):
                # Held-out LM eval: deterministic SEQUENTIAL [B, S+1]
                # windows over the whole file, one epoch — exhaustive and
                # reproducible, unlike the training loader's sampled
                # windows. Geometry mirrors the train path.
                mcfg = cfg.build_model().cfg
                if args.config == "gpt2_124m":
                    seq = args.seq_len or 1024
                else:
                    seq = mcfg.max_positions
                ids = np.fromfile(tok, dtype=dtype).astype(np.int32)
                if ids.size and int(ids.max()) >= mcfg.vocab_size:
                    # Same loud refusal as the train path: out-of-range
                    # ids clip under jit and yield a finite, meaningless
                    # perplexity with no diagnostic.
                    raise SystemExit(
                        f"{tok} holds token ids up to {int(ids.max())} "
                        f"but the model vocab is {mcfg.vocab_size}; "
                        f"re-pack the val split with the matching "
                        f"tokenizer")
                win = seq + 1
                n_win = ids.size // win
                if n_win < 1:
                    raise SystemExit(f"{tok}: {ids.size} tokens is fewer "
                                     f"than one {win}-token eval window")
                ids = ids[:n_win * win].reshape(n_win, win)
                bs = min(batch_size, n_win)

                def batches(ids=ids, bs=bs):
                    # Full batches, then the remainder as a smaller final
                    # batch (one extra jit trace) — exhaustive coverage,
                    # as the log line claims.
                    full = (ids.shape[0] // bs) * bs
                    for i in range(0, full, bs):
                        yield {"tokens": ids[i:i + bs]}
                    if full < ids.shape[0]:
                        yield {"tokens": ids[full:]}

                print(f"eval: {n_win} held-out windows from {tok}",
                      file=sys.stderr)
                it = batches()
                if args.config == "bert_base_zero1":
                    from nezha_tpu.data.mlm import mlm_batches_from_tokens
                    mask_token = _resolve_mlm_mask_token(args, mcfg, tok,
                                                         ids)
                    it = mlm_batches_from_tokens(
                        ({"tokens": b["tokens"][:, :-1]} for b in it),
                        vocab_size=mcfg.vocab_size,
                        mask_token=mask_token, seed=args.seed)
                return it, None, cfg.eval_stat
    if cfg.eval_batches is not None:
        return cfg.eval_batches(batch_size), None, cfg.eval_stat
    return None, None, None


def _wrap_model_overrides(cfg, **overrides) -> None:
    """Rebind cfg.build_model (and sp_model) with extra model-config kwargs
    — the shared core of the gpt2 knobs (--moe-experts, --remat). Wraps
    compose; a duplicated kwarg fails loudly at build time."""
    build0 = cfg.build_model
    cfg.build_model = lambda **ov: build0(**overrides, **ov)
    if cfg.sp_model is not None:
        sp0 = cfg.sp_model
        cfg.sp_model = lambda impl, **ov: sp0(impl, **overrides, **ov)


def _make_batch_sharder(mesh, group):
    """dp/zero1 batch placement: single-process hosts hold the whole global
    batch (device_put row-split); multi-process hosts hold only their local
    shard rows, assembled into the global array with zero inter-host
    transfer (pairs with _data_source's per-rank sharded loading)."""
    from nezha_tpu import parallel

    if group is not None and group.world_size > 1:
        return lambda b: parallel.shard_batch_process_local(mesh, b)
    return lambda b: parallel.shard_batch(mesh, b)


def _parse_profile_steps(spec: str):
    """Validate START:COUNT (pure argv parsing — called before any setup so
    a typo can't strand multi-host peers past the rendezvous)."""
    m = re.match(r"^(\d+):(\d+)$", spec)
    if not m or int(m.group(1)) < 1 or int(m.group(2)) < 1:
        # START >= 1: the window opens after step START completes, so 0
        # cannot capture step 1 and would silently shift the window.
        raise SystemExit(f"--profile-steps takes START:COUNT with START "
                         f">= 1 and COUNT >= 1 (e.g. 10:3), got {spec!r}")
    return int(m.group(1)), int(m.group(2))


def run(args) -> Dict[str, float]:
    """Argv-validated entry. With ``--run-dir`` the whole run executes
    inside a telemetry run scope: the registry turns on, per-window
    metrics/spans stream into the directory, and ``summary.json`` lands on
    every exit path (success or raise) — `nezha-telemetry RUN_DIR` renders
    the report."""
    from nezha_tpu import faults
    # Chaos drills (docs/RUNBOOK.md §9): NEZHA_FAULT_PLAN arms the
    # registered fault points (e.g. checkpoint.save) for this run —
    # restored on exit so embedded callers don't leak the plan
    # (restoring an unchanged plan is a no-op).
    prev_plan = faults.active()
    faults.install_from_env()
    try:
        return _run_checked(args)
    finally:
        faults.install(prev_plan)


def _run_checked(args) -> Dict[str, float]:
    if args.trace_dir:
        # --trace-dir is the observability-workflow spelling of
        # --profile-dir (XProf/XLA trace window; see docs/RUNBOOK.md §7).
        if args.profile_dir and args.profile_dir != args.trace_dir:
            raise SystemExit("--trace-dir is an alias for --profile-dir; "
                             "pass one of them")
        args.profile_dir = args.trace_dir
    if not args.run_dir:
        return _run_traced(args)
    import os

    from nezha_tpu import obs
    run_dir = args.run_dir
    if args.coordinator:
        # Multi-process launch: every process captures into its own
        # subdirectory — the sink truncates its streams on open, so two
        # ranks sharing one dir would destroy each other's capture. Rank
        # is only assigned at the rendezvous (inside the run scope), so
        # the pre-join identity is the rank hint, else the PID.
        sub = (f"rank{args.rank_hint}" if args.rank_hint >= 0
               else f"pid{os.getpid()}")
        run_dir = os.path.join(run_dir, sub)
    obs.start_run(run_dir, meta={
        "config": args.config, "steps": args.steps,
        "engine": args.engine, "parallel": args.parallel,
        "model_preset": args.model_preset})
    try:
        return _run_traced(args)
    finally:
        obs.end_run()


def _run_traced(args) -> Dict[str, float]:
    if args.ckpt_keep is not None and args.ckpt_keep <= 0:
        raise SystemExit(f"--ckpt-keep must be >= 1 (got {args.ckpt_keep}); "
                         f"omit it to keep all checkpoints")
    if args.profile_steps:
        if not args.profile_dir:
            raise SystemExit("--profile-steps needs --profile-dir for the "
                             "trace output")
        _parse_profile_steps(args.profile_steps)
    if args.clip_norm is not None:
        # Pure-argv validation BEFORE the rendezvous (a post-join
        # SystemExit would strand multi-host peers in their next
        # collective); the wrap itself happens after the parallel mode is
        # known, since ZeRO-1 needs the cross-rank norm.
        if not args.clip_norm > 0:  # also catches NaN (every compare False)
            raise SystemExit(f"--clip-norm must be > 0, got {args.clip_norm}")
        if args.engine == "graph" and args.parallel in ("dp", "zero1"):
            raise SystemExit("--clip-norm with the graph engine's dp/zero1 "
                             "modes is unsupported: the clip must see the "
                             "REDUCED gradients, but their collectives "
                             "live inside the update graphs; use "
                             "single-device graph or the module engine")
    if args.eval_every is not None and args.eval_every < 1:
        raise SystemExit(f"--eval-every must be >= 1, got {args.eval_every}")
    if args.eval_batches is not None and args.eval_batches < 1:
        # An empty eval pass would raise MID-training under --eval-every,
        # after real progress — reject it before anything starts.
        raise SystemExit(f"--eval-batches must be >= 1, got "
                         f"{args.eval_batches}")
    if args.lr is not None and not args.optimizer:
        raise SystemExit("--lr only applies with --optimizer (each config's "
                         "default optimizer bakes its own tuned schedule)")
    if args.optimizer:
        if args.engine == "graph":
            raise SystemExit("the graph engine authors its optimizer update "
                             "in the IR (momentum/adamw programs); "
                             "--optimizer cannot swap it")
        if args.lr is None:
            raise SystemExit("--optimizer needs --lr (peak learning rate "
                             "for the warmup+cosine schedule)")
        if not args.lr > 0:  # also catches NaN
            raise SystemExit(f"--lr must be > 0, got {args.lr}")
    if args.on_failure == "rejoin":
        # All argv-level: reject before the rendezvous can strand peers.
        if not args.rejoin_timeout > 0:  # also catches NaN
            raise SystemExit(f"--rejoin-timeout must be > 0, got "
                             f"{args.rejoin_timeout}")
        if not args.coordinator:
            raise SystemExit("--on-failure rejoin needs --coordinator "
                             "(failure detection is the coordinator's "
                             "heartbeat)")
        if not args.ckpt_dir:
            raise SystemExit("--on-failure rejoin needs --ckpt-dir: "
                             "recovery reloads the rescue checkpoint")
        if not args.no_jax_distributed:
            raise SystemExit("--on-failure rejoin requires "
                             "--no-jax-distributed: XLA's distributed "
                             "runtime cannot absorb a restarted process "
                             "mid-run — with jax.distributed, use "
                             "--on-failure stop and a supervisor relaunch "
                             "(training resumes from --ckpt-dir)")
    group, coord = _join_world(args)

    import jax

    from nezha_tpu.cli.common import setup_jax
    setup_jax(args)

    from nezha_tpu import parallel
    from nezha_tpu.runtime import Prefetcher
    from nezha_tpu.train import checkpoint as ckpt
    from nezha_tpu.train import sharded_checkpoint as sckpt
    from nezha_tpu.train.loop import Trainer, init_train_state, make_train_step

    cfg = _configs()[args.config]
    if args.model_preset == "tiny":
        for field, value in cfg.tiny.items():
            setattr(cfg, field, value)
    batch_size = args.batch_size or cfg.default_batch

    if args.moe_experts:
        # Mixture-of-experts GPT-2: every other block's MLP becomes a
        # top-k routed expert layer; lm_loss adds the load-balance aux.
        if args.config != "gpt2_124m":
            raise SystemExit("--moe-experts applies to gpt2_124m")
        if args.engine == "graph":
            raise SystemExit("--moe-experts is not expressible in the "
                             "graph engine's GPT-2 program; drop --engine "
                             "graph")
        if args.parallel == "pp":
            raise SystemExit("--moe-experts cannot pipeline (MoE blocks "
                             "make the stage slabs heterogeneous); use "
                             "--parallel dp/zero1/sp, or gspmd with an ep "
                             "mesh axis (--mesh dp=X,tp=Y,ep=Z)")
        _wrap_model_overrides(cfg, moe_experts=args.moe_experts)

    if args.optimizer:
        # (Pairing/value/engine checks ran pre-rendezvous; the lars/lamb x
        # zero1 guard runs post-degrade below, where the real mode is known.)
        from nezha_tpu import optim as optim_mod
        factories = {
            "sgd": optim_mod.sgd,
            "momentum": lambda lr: optim_mod.momentum(
                lr, beta=0.9, weight_decay=1e-4),
            "adamw": lambda lr, **kw: optim_mod.adamw(lr,
                                                      weight_decay=0.1,
                                                      **kw),
            "lars": lambda lr: optim_mod.lars(lr, weight_decay=1e-4),
            "lamb": lambda lr, **kw: optim_mod.lamb(lr, weight_decay=0.01,
                                                    **kw),
            "adafactor": optim_mod.adafactor,
        }
        factory = factories[args.optimizer]
        cfg.build_optimizer = lambda steps, **kw: factory(
            optim_mod.warmup_cosine_schedule(
                args.lr, min(100, max(1, steps // 10)), max(steps, 200)),
            **kw)

    if args.graph_bf16:
        if args.engine != "graph" or args.config != "gpt2_124m":
            raise SystemExit("--graph-bf16 applies to --engine graph with "
                             "gpt2_124m (the bf16 policy authored in the "
                             "IR; the module engine's presets carry their "
                             "own policies)")

    if args.wd_exclude_1d:
        # The standard GPT-2/BERT recipe: no decoupled weight decay on
        # norm scales/biases (any leaf with ndim < 2). Composes with the
        # default AdamW schedules and with --optimizer adamw/lamb.
        if args.engine == "graph":
            raise SystemExit("--wd-exclude-1d: the graph engine's "
                             "IR-authored update decays every leaf")
        if args.optimizer and args.optimizer not in ("adamw", "lamb"):
            raise SystemExit(f"--wd-exclude-1d needs a masked-decay "
                             f"optimizer (adamw/lamb), not "
                             f"{args.optimizer}")
        if not args.optimizer and args.config not in ("gpt2_124m",
                                                      "bert_base_zero1"):
            raise SystemExit("--wd-exclude-1d applies to the AdamW "
                             "configs (gpt2_124m, bert_base_zero1) or "
                             "with --optimizer adamw/lamb")
        from nezha_tpu import optim as optim_mod
        _build_opt0 = cfg.build_optimizer
        cfg.build_optimizer = lambda steps: _build_opt0(
            steps, mask=optim_mod.matrix_decay_mask)

    if args.grad_accum is not None:
        if args.grad_accum < 1:
            raise SystemExit(f"--grad-accum must be >= 1, got "
                             f"{args.grad_accum}")
        if args.engine == "graph" and args.grad_accum > 1:
            raise SystemExit("--grad-accum is an optimizer wrapper the "
                             "graph engine's IR-authored update does not "
                             "express; drop --engine graph")
        # (The wrap itself happens late, composed outside --clip-norm.)

    if args.dropout is not None:
        if args.config != "gpt2_124m":
            raise SystemExit("--dropout applies to gpt2_124m")
        if args.engine == "graph":
            raise SystemExit("the graph engine's GPT-2 program has no "
                             "dropout path; drop --engine graph")
        if not 0.0 <= args.dropout < 1.0:
            raise SystemExit(f"--dropout must be in [0, 1), got "
                             f"{args.dropout}")
        _wrap_model_overrides(cfg, dropout=args.dropout)

    if args.label_smoothing:
        # Standard ImageNet recipe: train against (1-eps)*one_hot + eps/V.
        if args.config not in ("mlp_mnist",) + _IMAGE_CONFIGS:
            raise SystemExit("--label-smoothing applies to the integer-"
                             "label CE configs (mlp_mnist, "
                             + ", ".join(_IMAGE_CONFIGS) + ")")
        if args.engine == "graph":
            raise SystemExit("the graph engine's programs author the plain "
                             "CE; drop --engine graph")
        if not 0.0 < args.label_smoothing < 1.0:
            raise SystemExit(f"--label-smoothing must be in (0, 1), got "
                             f"{args.label_smoothing}")
        from nezha_tpu import ops
        eps = args.label_smoothing
        cfg.loss_fn = lambda logits, b: \
            ops.softmax_cross_entropy_with_integer_labels(
                logits, b["label"], label_smoothing=eps)

    if args.mlm_mask_token is not None and (
            args.config != "bert_base_zero1" or not args.data_dir):
        raise SystemExit("--mlm-mask-token applies to bert_base_zero1 "
                         "with --data-dir (the dynamic-MLM data path)")

    if args.remat:
        # Block rematerialization: the long-context/big-batch memory knob
        # (jax.checkpoint per transformer block / ResNet bottleneck; see
        # GPT2Config.remat, ResNet(remat=...)).
        if args.config not in ("gpt2_124m",) + _IMAGE_CONFIGS:
            raise SystemExit("--remat applies to gpt2_124m and the image "
                             "configs")
        if args.engine == "graph":
            raise SystemExit("--remat is a jax.checkpoint knob; the graph "
                             "engine does not rematerialize")
        _wrap_model_overrides(cfg, remat=True)

    if args.scan_layers:
        # Scan trunk: a params-layout change (h_scan, leading layer dim),
        # so restrict to the paths whose param handling is layout-agnostic
        # and parity-tested; gspmd TP rules and the pipeline/sp builders
        # address h{i} names explicitly.
        if args.config not in ("gpt2_124m", "bert_base_zero1"):
            raise SystemExit("--scan-layers applies to gpt2_124m / "
                             "bert_base_zero1")
        if args.engine == "graph":
            raise SystemExit("--scan-layers is a module-engine knob; the "
                             "graph engine authors its own trunk IR")
        eff = cfg.parallel_mode if args.parallel == "config" \
            else args.parallel
        if eff not in ("single", "dp", "zero1", "gspmd", "sp"):
            raise SystemExit("--scan-layers supports --parallel "
                             "single/dp/zero1/gspmd/sp (the pp builder "
                             "addresses unrolled h{i} names)")
        _wrap_model_overrides(cfg, scan_layers=True)

    if args.seq_len:
        # Long-context override: resize position table + data together.
        # With --parallel sp the sequence shards over the sp axis, so
        # per-chip activation memory stays O(seq_len / sp).
        if args.config != "gpt2_124m":
            raise SystemExit("--seq-len applies to gpt2_124m")
        sl = args.seq_len
        build0, sp0, batches0 = cfg.build_model, cfg.sp_model, cfg.batches
        eval0 = cfg.eval_batches
        cfg.build_model = lambda: build0(max_positions=sl)
        if sp0 is not None:
            cfg.sp_model = lambda impl, **ov: sp0(impl, max_positions=sl,
                                                  **ov)
        cfg.batches = lambda bs: batches0(bs, seq_len=sl)
        if eval0 is not None:
            cfg.eval_batches = lambda bs: eval0(bs, seq_len=sl)

    # --- graph-IR engine (north star: Graph -> StableHLO -> Executor) -----
    # Resolved before any parallel-mode/mesh logic: the engine is single-
    # device by design, so it must neither trip the multi-device degrade
    # warning nor build a mesh it will never use.
    if args.engine == "graph":
        graph_mode = "single" if args.parallel == "config" else args.parallel
        if graph_mode not in ("single", "dp", "zero1"):
            raise SystemExit(f"--engine graph supports --parallel dp "
                             f"(IR all_reduce) or zero1 (IR reduce_scatter "
                             f"+ all_gather) or single-device, not "
                             f"{graph_mode!r}")
        if graph_mode == "zero1":
            if args.config != "mlp_mnist":
                raise SystemExit("graph-engine zero1 is authored for "
                                 "mlp_mnist (graph/programs.py "
                                 "zero1_update_graph); other configs run "
                                 "the module engine's zero1")
            if group is not None and group.world_size > 1:
                raise SystemExit("graph-engine zero1 is single-controller "
                                 "(its flat dp-sharded state cannot be "
                                 "fetched/checkpointed across OS "
                                 "processes); multi-process zero1 runs the "
                                 "module engine")
        if graph_mode == "single" and args.mesh:
            raise SystemExit("--mesh needs --parallel dp/zero1 with the "
                             "graph engine (single-device IR does not "
                             "partition)")
        if args.grad_allreduce != "fp32":
            raise SystemExit("--grad-allreduce int8 is the module engine's "
                             "dp/zero1 wire; the graph engine's all-reduce "
                             "is an IR op (fp32 only)")
        if args.sp_flash != "auto":
            raise SystemExit("--sp-flash tunes the sequence-parallel "
                             "attention kernels; it needs --parallel sp "
                             "(module engine)")
        import numpy as _np

        from nezha_tpu.graph import programs
        mode, mesh = graph_mode, None
        if mode in ("dp", "zero1") and len(jax.devices()) == 1:
            print(f"WARNING: --engine graph --parallel {mode} with 1 "
                  f"visible device; running single-device", file=sys.stderr)
            mode = "single"
        if mode in ("dp", "zero1"):
            mesh_axes = _parse_mesh(args.mesh) or _parse_mesh("dp=-1")
            if list(mesh_axes) != ["dp"]:
                raise SystemExit(f"graph-engine {mode} consumes mesh axis "
                                 f"'dp' only; got {list(mesh_axes)}")
            mesh = parallel.make_mesh(mesh_axes)
            world = mesh.shape["dp"]
            if batch_size % world:
                raise SystemExit(f"--batch-size {batch_size} is not "
                                 f"divisible by mesh axis dp={world} (it is "
                                 f"the GLOBAL batch; shards must be equal)")
        model = cfg.build_model()
        optimizer = cfg.build_optimizer(args.steps)
        rng = jax.random.PRNGKey(args.seed)
        if args.config == "mlp_mnist":
            dims = [784, 256, 256, 10]
            # dp: _make_batch_sharder pairs with _data_source, so
            # multi-process launches feed LOCAL rows assembled
            # process-locally like module-engine dp. zero1 is validated
            # single-process above (its state fetch is single-controller).
            onehot = programs.onehot_shard_fn(dims[-1])
            if mode == "zero1":
                state = programs.init_graph_mlp_zero1_state(dims, rng, mesh)
                step_fn = programs.make_mlp_graph_zero1_train_step(
                    dims, batch_size, lr=0.1, mesh=mesh)
                shard = lambda b: parallel.shard_batch(mesh, onehot(b))
            elif mode == "dp":
                state = programs.init_graph_mlp_state(dims, rng)
                step_fn = programs.make_mlp_graph_dp_train_step(
                    dims, batch_size, lr=0.1, mesh=mesh)
                shard = onehot  # placement hoisted below (all dp configs)
            else:
                state = programs.init_graph_mlp_state(dims, rng)
                step_fn = programs.make_mlp_graph_train_step(
                    dims, batch_size, lr=0.1, clip_norm=args.clip_norm)
                shard = onehot
        elif args.config in ("resnet50_imagenet", "wrn101_large_batch"):
            if args.eval or args.eval_every:
                raise SystemExit("graph-engine ResNet runs training-mode "
                                 "batch stats only (no running BN stats); "
                                 "drop --eval/--eval-every")
            state = programs.init_graph_resnet_state(model, rng)
            if mode == "dp":
                step_fn = programs.make_resnet_graph_dp_train_step(
                    model, batch_size, lr=0.1, mesh=mesh)
                shard = programs.image_shard_fn()
            else:
                step_fn = programs.make_resnet_graph_train_step(
                    model, lr=0.1, clip_norm=args.clip_norm)
                shard = programs.image_shard_fn()
        elif args.config == "bert_base_zero1":
            state = programs.init_graph_bert_state(model, rng)
            sched = cfg.graph_opt["schedule"](args.steps)
            step_fn = programs.make_bert_graph_train_step(
                model, lambda t: float(sched(_np.int32(t))),
                weight_decay=cfg.graph_opt["weight_decay"],
                clip_norm=args.clip_norm,
                mesh=mesh if mode == "dp" else None)
            shard = programs.bert_shard_fn()
        else:  # gpt2_124m: the transformer authored in the IR
            state = programs.init_graph_gpt2_state(model, rng)
            sched = cfg.graph_opt["schedule"](args.steps)
            step_fn = programs.make_gpt2_graph_train_step(
                model, lambda t: float(sched(_np.int32(t))),
                weight_decay=cfg.graph_opt["weight_decay"],
                clip_norm=args.clip_norm,
                mesh=mesh if mode == "dp" else None,
                compute_dtype="bfloat16" if args.graph_bf16
                else "float32")
            shard = programs.lm_shard_fn()
        if mode == "dp":
            # One placement composition for every graph-dp config:
            # _make_batch_sharder pairs with _data_source so multi-process
            # launches feed LOCAL rows assembled process-locally.
            _base_shard = shard
            _place = _make_batch_sharder(mesh, group)
            shard = lambda b: _place(_base_shard(b))
        start_step = 0
        if args.ckpt_dir:
            restored, start_step = ckpt.try_restore(args.ckpt_dir, state)
            if restored is not None:
                state = restored
                print(f"resumed from step {start_step}", file=sys.stderr)
        if mode == "dp":
            state = parallel.replicate(mesh, state)
        elif mode == "zero1" and start_step:
            # A resume restored numpy leaves; re-shard the flat 1-D state
            # over dp. (Fresh init is already placed — no gather round-trip.)
            from jax.sharding import NamedSharding, PartitionSpec as _P
            _sh = NamedSharding(mesh, _P("dp"))
            state = jax.tree_util.tree_map(
                lambda x: jax.device_put(np.asarray(x), _sh), state)
        save_fn = None
    else:
        mode = cfg.parallel_mode if args.parallel == "config" else args.parallel
        if mode == "single" and args.mesh:
            raise SystemExit("--mesh has no effect in single-device mode; "
                             "drop it or pick a --parallel mode that "
                             "consumes it")
        # An EXPLICIT all-ones mesh (e.g. --mesh dp=1,sp=1) fits one device
        # by construction and must run the requested mode — it is the
        # 1-chip smoke of a parallel path (kernel compiles, shard_map
        # wiring), not a mis-launch.
        _req = _parse_mesh(args.mesh)
        _req_size = 1
        for _v in (_req or {"": -1}).values():
            _req_size *= _v  # any -1 ("all devices") counts as multi
        if (mode != "single" and len(jax.devices()) == 1
                and _req_size != 1):
            # Degrade, but never silently: a mis-launched multi-host job
            # would otherwise "succeed" at 1/Nth scale.
            print(f"WARNING: config {args.config!r} requests parallel mode "
                  f"{mode!r} but only 1 device is visible; running "
                  f"single-device (check your mesh/launch if this is a "
                  f"multi-chip job)", file=sys.stderr)
            mode = "single"
        # After the degrade: a mode that will not run the dp/zero1 wire
        # cannot consume the int8 request — reject, don't ignore (the
        # degrade would otherwise silently swap exact fp32 semantics in).
        if args.grad_allreduce != "fp32" and mode not in ("dp", "zero1"):
            raise SystemExit("--grad-allreduce int8 is the dp/zero1 "
                             f"gradient wire format; mode {mode!r} does "
                             "not consume it (reject, don't ignore)")
        if args.sp_flash != "auto" and mode != "sp":
            raise SystemExit(f"--sp-flash tunes the sequence-parallel "
                             f"attention kernels; mode {mode!r} does not "
                             f"consume it (reject, don't ignore)")
        if args.optimizer in ("lars", "lamb") and mode == "zero1":
            raise SystemExit(f"--optimizer {args.optimizer} computes "
                             f"layerwise trust ratios, which ZeRO-1's flat "
                             f"per-rank chunks cannot preserve; use "
                             f"--parallel dp (or adamw/momentum with zero1)")
        if args.wd_exclude_1d and mode in ("zero1", "pp"):
            raise SystemExit("--wd-exclude-1d: this mode's flat/stacked "
                             "param layout (zero1 chunks, pp stage slabs) "
                             "erases the leaf shapes the ndim-based decay "
                             "mask keys on; use --parallel dp/single/gspmd")

        # Mesh axes are validated against the chosen mode: an axis the mode
        # cannot consume is an error, never silently ignored — and every
        # axis the mode's shard/step functions hardcode must be present
        # (all modes shard the batch over "dp"; pass dp=1 to opt out of
        # data parallelism).
        mode_axes = {"single": (), "dp": ("dp",), "zero1": ("dp",),
                     "gspmd": ("dp", "tp"), "pp": ("dp", "pp"),
                     "sp": ("dp", "sp")}
        mode_default_mesh = {"dp": "dp=-1", "zero1": "dp=-1",
                             "gspmd": "dp=1,tp=-1", "pp": "dp=1,pp=-1",
                             "sp": "dp=1,sp=-1"}
        if args.moe_experts and mode == "gspmd":
            # MoE under GSPMD adds the expert axis: dp x tp x ep (tp=1 to
            # disable tensor parallelism; experts shard over ep).
            mode_axes["gspmd"] = ("dp", "tp", "ep")
            mode_default_mesh["gspmd"] = "dp=1,tp=1,ep=-1"
        mesh = None
        if mode != "single":
            mesh_axes = (_parse_mesh(args.mesh)
                         or _parse_mesh(mode_default_mesh[mode]))
            unusable = [a for a in mesh_axes if a not in mode_axes[mode]]
            if unusable:
                raise SystemExit(
                    f"parallel mode {mode!r} cannot use mesh axis(es) "
                    f"{unusable} (it consumes {list(mode_axes[mode])}); "
                    f"pass --parallel to select the mode that uses them")
            missing = [a for a in mode_axes[mode] if a not in mesh_axes]
            if missing:
                raise SystemExit(
                    f"parallel mode {mode!r} needs mesh axis(es) {missing} "
                    f"(use size 1 to disable an axis); got "
                    f"{list(mesh_axes)}")
            mesh = parallel.make_mesh(mesh_axes)
            ep_size = mesh.shape.get("ep")
            if ep_size and args.moe_experts % ep_size:
                raise SystemExit(
                    f"--moe-experts {args.moe_experts} is not divisible by "
                    f"mesh axis ep={ep_size}; expert stacks shard over ep "
                    f"(pass --mesh dp=X,tp=Y,ep=Z with Z dividing the "
                    f"expert count)")

        if mode == "sp":
            if cfg.sp_model is None:
                raise SystemExit(f"config {args.config!r} has no sequence-"
                                 f"parallel model; --parallel sp supports: "
                                 f"gpt2_124m")
            model = cfg.sp_model(
                args.attn_impl,
                sp_use_flash={"auto": None, "on": True,
                              "off": False}[args.sp_flash])
        else:
            model = cfg.build_model()
        if args.clip_norm is not None:
            # ZeRO-1's optimizer sees per-rank gradient SHARDS, so the
            # clip's norm must psum over dp; every other mode's optimizer
            # sees full gradients.
            from nezha_tpu import optim as optim_mod
            clip_build = cfg.build_optimizer
            clip_axis = "dp" if mode == "zero1" else None
            cfg.build_optimizer = lambda steps: optim_mod.with_grad_clipping(
                clip_build(steps), args.clip_norm, axis_name=clip_axis)
        if args.grad_accum is not None and args.grad_accum > 1:
            # Outside the clip: accumulate RAW micro-grads, clip the
            # flushed mean. The inner optimizer (and its LR schedule)
            # steps once per FLUSH — size the horizon to real updates or
            # the cosine never finishes.
            from nezha_tpu import optim as optim_mod
            acc_build = cfg.build_optimizer
            cfg.build_optimizer = lambda steps: optim_mod.accumulate_gradients(
                acc_build(max(1, steps // args.grad_accum)),
                args.grad_accum)
        optimizer = cfg.build_optimizer(args.steps)
        rng = jax.random.PRNGKey(args.seed)

        # --- state + per-mode step/shard/checkpoint format ----------------
        # ZeRO-1/GSPMD/pipeline state is sharded by construction, so those
        # modes use the per-shard checkpoint format (restore needs the
        # sharded template, hence after layout); the replicated-state modes
        # (single/dp/sp) restore plain npz before layout. Pipeline state
        # never materializes a dense optimizer state at all (its slots are
        # born sharded over the stage slabs), so it inits from variables
        # alone below.
        start_step = 0
        save_fn = None
        if mode != "pp":
            state = init_train_state(model, optimizer, rng)
            if mode in ("single", "dp", "sp") and args.ckpt_dir:
                restored, start_step = ckpt.try_restore(args.ckpt_dir, state)
                if restored is not None:
                    state = restored
                    print(f"resumed from step {start_step}", file=sys.stderr)

        if mode == "single":
            step_fn = make_train_step(model, optimizer, cfg.loss_fn)
            shard = None
        elif mode == "dp":
            state = parallel.replicate(mesh, state)
            step_fn = parallel.make_dp_train_step(
                model, optimizer, cfg.loss_fn, mesh,
                grad_reduce=args.grad_allreduce)
            shard = _make_batch_sharder(mesh, group)
        elif mode == "sp":
            from nezha_tpu.parallel import sequence_parallel as sp_mod
            state = parallel.replicate(mesh, state)
            step_fn = sp_mod.make_sp_train_step(model, optimizer, mesh)
            shard = lambda b: sp_mod.shard_lm_batch(mesh, b)
        elif mode == "gspmd":
            if cfg.tp_rules is None:
                raise SystemExit(
                    f"config {args.config!r} has no tensor-parallel rule "
                    f"table; --parallel gspmd supports: gpt2_124m, "
                    f"bert_base_zero1")
            rules = cfg.tp_rules
            if args.moe_experts:
                from nezha_tpu.parallel.expert import gpt2_moe_gspmd_rules
                rules = gpt2_moe_gspmd_rules(cfg.tp_rules)
            if args.scan_layers:
                # Stacked-trunk layout: same rule table, specs computed on
                # the unrolled view with a leading layer dim (the
                # canonical scan-over-layers + GSPMD TP shape).
                prefix, key = (("h", "h_scan") if args.config == "gpt2_124m"
                               else ("layers", "layers_scan"))
                specs = parallel.scan_param_specs(
                    state["variables"]["params"], rules,
                    model.cfg.num_layers, prefix, key, strict=True)
            else:
                specs = parallel.param_specs_from_rules(
                    state["variables"]["params"], rules, strict=True)
            state = parallel.shard_train_state(state, mesh, specs)
            save_fn = sckpt.save_sharded
            step_fn = parallel.make_gspmd_train_step(
                model, optimizer, cfg.loss_fn, mesh, specs)
            from nezha_tpu.parallel.gspmd import shard_batch_gspmd
            shard = lambda b: shard_batch_gspmd(mesh, b)
        elif mode == "pp":
            if cfg.pipeline_spec is None:
                raise SystemExit(f"config {args.config!r} has no pipeline "
                                 f"spec; --parallel pp supports: gpt2_124m")
            from nezha_tpu.parallel import pipeline as pp_mod
            pspec = cfg.pipeline_spec(model)
            state = pp_mod.init_pipeline_state(
                model.init(rng), pspec, optimizer, mesh, rng)
            save_fn = sckpt.save_sharded
            # dropout_rng/remat resolve from the spec's own fields (set
            # from the model config by gpt2_pipeline_spec).
            step_fn = pp_mod.make_pipeline_train_step(
                pspec, optimizer, cfg.loss_fn, mesh,
                num_microbatches=args.microbatches,
                dropout_rng=bool(pspec.dropout))
            shard = lambda b: parallel.shard_batch(mesh, b)
        elif mode == "zero1":
            variables = state["variables"]
            state = {
                "variables": parallel.replicate(mesh, variables),
                "opt_state": parallel.zero1_init_opt_state(
                    optimizer, variables["params"], mesh),
                "rng": parallel.replicate(mesh, state["rng"]),
            }
            save_fn = sckpt.save_sharded
            step_fn = parallel.make_zero1_train_step(
                model, optimizer, cfg.loss_fn, mesh,
                grad_reduce=args.grad_allreduce)
            shard = _make_batch_sharder(mesh, group)
        else:
            raise ValueError(mode)

        # Sharded-state modes restore AFTER layout: the per-shard format
        # rebuilds each leaf against the live template sharding (one shared
        # block — the gspmd/pp/zero1 layouts all restore identically).
        if save_fn is sckpt.save_sharded and args.ckpt_dir:
            restored, start_step = sckpt.try_restore_sharded(
                args.ckpt_dir, state)
            if restored is None and mode == "zero1":
                # Legacy dense zero1 checkpoints (pre-sharded-format CLI)
                # restore into the same laid-out template.
                restored, start_step = ckpt.try_restore(args.ckpt_dir, state)
            if restored is not None:
                state = restored
                print(f"resumed from step {start_step} (sharded)",
                      file=sys.stderr)

    # Sharded saves go through the AsyncCheckpointer by default: the step
    # path pays only the device->host shard copies; file IO runs off-thread
    # (wait() commits before the failure-path raise and after the final
    # save).
    async_ckpt = None
    if save_fn is sckpt.save_sharded and args.ckpt_dir:
        async_ckpt = sckpt.AsyncCheckpointer()
        save_fn = async_ckpt.save
    # Retention (--ckpt-keep) flows through Trainer.checkpoint_keep for
    # every save path — the Trainer forwards keep_last to the save_fn.

    # --- loop (one shared Trainer for every mode, so failure detection /
    # checkpoint-before-raise is live in real CLI runs) --------------------
    # Multi-process data sharding pairs with process-local batch assembly,
    # which only the dp/zero1 sharders do; other modes keep the documented
    # identical-stream semantics of shard_batch.
    data_group = (group if group is not None and group.world_size > 1
                  and mode in ("dp", "zero1") else None)
    if data_group is not None and batch_size % data_group.world_size:
        raise SystemExit(
            f"--batch-size {batch_size} must be divisible by the process "
            f"world size {data_group.world_size} (it is the GLOBAL batch; "
            f"each host loads batch/world local rows)")
    source, close_source = _data_source(args, cfg, batch_size,
                                        group=data_group)
    prefetch = Prefetcher(source, depth=args.prefetch)
    from nezha_tpu.utils import MetricsLogger
    metrics_log = MetricsLogger(args.metrics_file) if args.metrics_file else None

    def log_metrics(step_no: int, metrics: Dict[str, float]) -> None:
        if args.log_memory:
            # Live/peak HBM per step (empty off-TPU: CPU exposes no stats).
            from nezha_tpu.tensor import memory_metrics
            metrics = {**metrics, **memory_metrics()}
        print(json.dumps(metrics), file=sys.stderr)
        if metrics_log:
            metrics_log.log(step_no, metrics)

    tracer = None
    if args.profile_steps:
        # Validated at the top of run(); the Tracer itself is cheap.
        start, count = _parse_profile_steps(args.profile_steps)
        from nezha_tpu.utils import Tracer
        tracer = Tracer(args.profile_dir, start_step=start, num_steps=count)

    if args.on_failure == "rejoin" and (mode not in ("single", "dp", "sp")
                                        or args.engine == "graph"):
        # The recovery reload goes through Trainer.initialize's plain-npz
        # restore, which pairs with the replicated-state module-engine
        # modes; sharded-state modes (zero1/gspmd/pp) and the graph
        # engine's own state layouts recover via supervisor restart.
        raise SystemExit(f"--on-failure rejoin supports the "
                         f"replicated-state module-engine modes "
                         f"(single/dp/sp); got mode {mode!r}, engine "
                         f"{args.engine!r} — use --on-failure stop with a "
                         f"supervisor relaunch")
    trainer = Trainer(
        model, optimizer, cfg.loss_fn,
        checkpoint_dir=args.ckpt_dir,
        checkpoint_every=args.ckpt_every,
        log_every=args.log_every,
        metric_logger=log_metrics,
        tracer=tracer,
        process_group=group,
        failure_check_every=args.failure_check_every if group is not None
        else 0,
        failure_mode=args.on_failure,
        rejoin_timeout_s=args.rejoin_timeout,
        step_fn=step_fn,
        shard_fn=shard,
        save_fn=save_fn,
        save_wait=async_ckpt.wait if async_ckpt is not None else None,
        checkpoint_keep=args.ckpt_keep,
        examples_per_step=batch_size)
    trainer.state = state
    trainer.global_step = start_step

    eval_cache: Dict[str, Any] = {}  # jitted eval step reused across passes
    whole_run_trace = args.profile_dir and tracer is None
    if whole_run_trace:
        import os as _os
        _os.makedirs(args.profile_dir, exist_ok=True)
        jax.profiler.start_trace(args.profile_dir)

    last: Dict[str, float] = {}
    try:
        if args.eval_every:
            # Periodic eval: train in chunks aligned to GLOBAL-step
            # multiples of --eval-every (same cadence convention as
            # --ckpt-every/--log-every, so a resumed run's eval points
            # line up with the pre-restart stream), full eval pass between
            # chunks. The final pass happens at the tail with the
            # end-of-run --eval handling.
            done = 0
            while done < args.steps:
                to_boundary = (args.eval_every
                               - trainer.global_step % args.eval_every)
                n = min(to_boundary, args.steps - done)
                last = trainer.fit(prefetch, n)
                done += n
                if done < args.steps:
                    results = _run_eval(args, cfg, batch_size, mode, model,
                                        trainer,
                                        pspec if mode == "pp" else None,
                                        cache=eval_cache)
                    if results is not None:
                        log_metrics(trainer.global_step, {
                            "step": trainer.global_step,
                            **{f"eval_{k}": v for k, v in results.items()}})
        else:
            last = trainer.fit(prefetch, args.steps)
    finally:
        prefetch.close()
        if close_source is not None:
            close_source()
        if whole_run_trace:
            jax.profiler.stop_trace()
        elif tracer is not None:
            tracer.stop()  # window may still be open on early exit
        if metrics_log:
            metrics_log.close()
        if group is not None:
            unwinding = sys.exc_info()[0] is not None
            if not unwinding:
                try:
                    group.barrier(timeout_s=600)  # all ranks finish first
                except Exception as e:
                    print(f"shutdown barrier skipped: {e}", file=sys.stderr)
            # Unwinding an exception: peers may never arrive — leave at
            # once so survivors' failure detectors see a clean departure
            # and the real error surfaces without a 600 s stall.
            group.leave()
        if coord is not None:
            coord.stop()
    if args.ckpt_dir:
        trainer._save(start_step + args.steps)
        if async_ckpt is not None:
            async_ckpt.wait()
    if args.eval or args.eval_every:
        results = _run_eval(args, cfg, batch_size, mode, model, trainer,
                            pspec if mode == "pp" else None,
                            cache=eval_cache)
        if results is not None:
            print(json.dumps({"eval": results}), file=sys.stderr)
            last.update({f"eval_{k}": v for k, v in results.items()})
    return last


def _run_eval(args, cfg, batch_size, mode, model, trainer, pspec,
              cache=None):
    """One full pass over the eval split against the CURRENT train state.
    Returns the results dict, or None when the config has no eval split.
    Safe to call repeatedly (--eval-every): the eval SOURCE re-opens each
    time, while the jitted eval step (and the sp eval model) live in
    ``cache`` so repeated passes hit jit's cache instead of retracing."""
    eval_iter, eval_close, stat_fn = _eval_source(args, cfg, batch_size)
    if eval_iter is None:
        return None
    from nezha_tpu.train.eval import evaluate, make_eval_step

    # Graph-engine state stores module-layout params without the
    # variables wrapper; pipeline state stores stacked stage slabs
    # (merged back to the native tree here); sequence-parallel
    # models only run inside shard_map, so eval uses the plain
    # single-device model with the same (replicated) params.
    cache = cache if cache is not None else {}
    eval_model = model
    if args.engine == "graph":
        if "flat" in trainer.state:  # zero1's flat dp-sharded layout
            from nezha_tpu.graph import programs as _programs
            params = _programs.materialize_graph_zero1_params(
                [784, 256, 256, 10], trainer.state)  # mlp_mnist only
        else:
            params = trainer.state["params"]
        variables = {"params": params, "state": {}}
    elif mode == "pp":
        from nezha_tpu.parallel import pipeline as pp_mod
        variables = {"params": pp_mod.merge_pipeline_params(
            pspec, trainer.state["pparams"]), "state": {}}
    else:
        variables = trainer.state["variables"]
        if mode == "sp":
            if "sp_model" not in cache:
                cache["sp_model"] = cfg.build_model()
            eval_model = cache["sp_model"]
    import contextlib

    # gspmd/pp leave params sharded; eval traces fresh (outside the
    # train-step jit), where attn "auto" would otherwise pick the
    # Mosaic flash kernel XLA can't partition over tp/stage shards.
    scope = contextlib.nullcontext()
    if mode in ("gspmd", "pp"):
        from nezha_tpu.parallel.gspmd import auto_partitioner_scope
        scope = auto_partitioner_scope()
    if "step" not in cache:
        cache["step"] = make_eval_step(eval_model, stat_fn)
    try:
        with scope:
            return evaluate(eval_model, variables, eval_iter,
                            stat_fn=stat_fn, max_batches=args.eval_batches,
                            step=cache["step"])
    finally:
        if eval_close is not None:
            eval_close()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nezha-train",
        description="TPU-native training CLI (configs mirror BASELINE.json)")
    p.add_argument("--config", required=True,
                   choices=["mlp_mnist", "resnet50_imagenet", "gpt2_124m",
                            "bert_base_zero1", "wrn101_large_batch"])
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=None,
                   help="global batch (default: per-config)")
    p.add_argument("--model-preset", choices=["full", "tiny"], default="full",
                   help="tiny = seconds-scale model/data variant of the "
                        "config (same code paths; for tests and smoke runs)")
    p.add_argument("--mesh", default=None,
                   help='mesh axes, e.g. "dp=8" or "dp=2,tp=4" (-1 = rest); '
                        "axes must match what --parallel consumes")
    p.add_argument("--parallel", default="config",
                   choices=["config", "single", "dp", "zero1", "gspmd", "pp",
                            "sp"],
                   help="parallelism strategy: config (per-config default), "
                        "dp (all-reduce), zero1 (sharded optimizer), gspmd "
                        "(dp x tp tensor parallel), pp (dp x pp GPipe "
                        "pipeline), sp (dp x sp ring/Ulysses sequence "
                        "parallel)")
    p.add_argument("--microbatches", type=int, default=4,
                   help="pipeline microbatches per step (--parallel pp)")
    p.add_argument("--sp-flash", default="auto",
                   choices=["auto", "on", "off"],
                   help="ring/ulysses flash kernels: auto = Pallas on TPU "
                        "backends, composed XLA elsewhere; off = force the "
                        "composed fallback (the on-hardware escape hatch); "
                        "on = force flash (interpret mode off-TPU)")
    p.add_argument("--attn-impl", default="ring", choices=["ring", "ulysses"],
                   help="sequence-parallel attention (--parallel sp)")
    p.add_argument("--seq-len", type=int, default=None,
                   help="long-context override for gpt2_124m: sequence "
                        "length for model + data (shard it with "
                        "--parallel sp --mesh dp=X,sp=Y)")
    p.add_argument("--moe-experts", type=int, default=None,
                   help="gpt2_124m only: swap every other block's MLP for "
                        "a top-k routed mixture of this many experts")
    p.add_argument("--optimizer", default=None,
                   choices=["sgd", "momentum", "adamw", "lars", "lamb",
                            "adafactor"],
                   help="swap the config's optimizer (requires --lr; gets "
                        "a warmup+cosine schedule over --steps). The "
                        "config defaults stay the tuned choice.")
    p.add_argument("--lr", type=float, default=None,
                   help="peak learning rate for --optimizer's schedule")
    p.add_argument("--clip-norm", type=float, default=None,
                   help="clip gradients to this global L2 norm before the "
                        "optimizer update (any config/parallel mode)")
    p.add_argument("--grad-accum", type=int, default=None,
                   help="accumulate gradients over N micro-steps before "
                        "each optimizer update (any config/parallel mode; "
                        "effective batch = batch-size x N)")
    p.add_argument("--dropout", type=float, default=None,
                   help="gpt2_124m only: dropout rate override (works in "
                        "every parallel mode incl. pp, where per-(layer, "
                        "microbatch) keys thread through the schedule)")
    p.add_argument("--label-smoothing", type=float, default=None,
                   help="integer-label CE configs (mlp/resnet/wrn): train "
                        "against (1-eps)*one_hot + eps/num_classes")
    p.add_argument("--mlm-mask-token", type=int, default=None,
                   help="bert --data-dir only: [MASK] id (default 103, the "
                        "BERT-wordpiece convention; byte-packed text needs "
                        "an id >= 256 so masks are unambiguous)")
    p.add_argument("--remat", action="store_true",
                   help="gpt2_124m + image configs: rematerialize each "
                        "block/bottleneck in backward (jax.checkpoint) — "
                        "O(1) activation residuals per block for ~1/3 "
                        "extra FLOPs; the long-context / big-batch memory "
                        "knob (pairs with --seq-len and --parallel sp)")
    p.add_argument("--graph-bf16", action="store_true",
                   help="--engine graph, gpt2_124m: author the bf16 "
                        "compute policy in the IR (fp32 master params, "
                        "bf16 GEMMs/activations, fp32 softmax stats and "
                        "logits) — the module policy, in graph form")
    p.add_argument("--wd-exclude-1d", action="store_true",
                   help="AdamW/LAMB configs: exclude ndim<2 leaves (norm "
                        "scales, biases) from decoupled weight decay — "
                        "the standard GPT-2/BERT recipe (module engine; "
                        "not under zero1's flat chunking)")
    p.add_argument("--scan-layers", action="store_true",
                   help="gpt2_124m / bert_base_zero1 (single/dp/zero1/"
                        "gspmd/sp, module engine): layer-stacked trunk via "
                        "lax.scan — one compiled block program instead of "
                        "num_layers inlined copies (params live under "
                        "h_scan / layers_scan with a leading layer dim; "
                        "see GPT2Config.scan_layers)")
    p.add_argument("--grad-allreduce", default="fp32",
                   choices=["fp32", "int8"],
                   help="dp/zero1 gradient wire format: exact fp32 or "
                        "EQuARX/ZeRO++-style block-scaled int8 (~4x less "
                        "ICI traffic; dp all-reduce, zero1 reduce-scatter "
                        "+ update all-gather)")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--prefetch", type=int, default=2)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=0)
    p.add_argument("--ckpt-keep", type=int, default=None,
                   help="keep only the N newest checkpoints (sharded "
                        "retention counts fully-complete saves only); "
                        "default keeps all")
    p.add_argument("--metrics-file", default=None,
                   help="append JSONL metrics here")
    p.add_argument("--run-dir", default=None,
                   help="telemetry run directory: stream metrics.jsonl + "
                        "spans.jsonl and write a final summary.json "
                        "(step-rate percentiles, per-collective payload "
                        "bytes, compile-cache stats); read it back with "
                        "nezha-telemetry RUN_DIR. With --coordinator each "
                        "process captures into its own rank<K>/ (or "
                        "pid<P>/) subdirectory")
    p.add_argument("--trace-dir", default=None,
                   help="XProf/XLA profiler trace directory (alias for "
                        "--profile-dir; bound the window with "
                        "--profile-steps)")
    p.add_argument("--data-dir", default=None,
                   help="directory with real datasets (train.nzr image "
                        "records / train.tokens.* / mnist IDX); synthetic "
                        "fallback when absent")
    p.add_argument("--crop", type=int, default=224,
                   help="crop size for image-record training")
    p.add_argument("--failure-check-every", type=int, default=10,
                   help="poll the coordinator for dead peers every N steps "
                        "(multi-process runs)")
    p.add_argument("--on-failure", choices=["stop", "rejoin"],
                   default="stop",
                   help="dead-peer response: 'stop' checkpoints then raises "
                        "(supervisor restarts the world and training "
                        "resumes from --ckpt-dir); 'rejoin' additionally "
                        "waits for the crashed rank to be relaunched "
                        "(--rank-hint), reloads the rescue checkpoint, and "
                        "continues in-process")
    p.add_argument("--rejoin-timeout", type=float, default=300.0,
                   help="seconds --on-failure rejoin waits for the "
                        "replacement rank before giving up (then raises, "
                        "checkpoint already committed)")
    p.add_argument("--log-memory", action="store_true",
                   help="add live/peak HBM bytes to every metrics line "
                        "(TPU backends; no-op where the backend exposes "
                        "no memory stats)")
    p.add_argument("--profile-dir", default=None,
                   help="capture an XLA/TPU profiler trace here (whole run "
                        "unless --profile-steps bounds it)")
    p.add_argument("--profile-steps", default=None, metavar="START:COUNT",
                   help="bounded trace into --profile-dir: capture begins "
                        "once step START has completed and covers the next "
                        "COUNT steps (e.g. 10:3 traces steps 11-13 — the "
                        "standard steady-state window)")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="rendezvous address for multi-process launch")
    p.add_argument("--serve-coordinator", action="store_true",
                   help="also run the coordinator here (rank-0 host)")
    p.add_argument("--world-size", type=int, default=1,
                   help="processes in the job (with --serve-coordinator)")
    p.add_argument("--rank-hint", type=int, default=-1,
                   help="preferred rank (e.g. for restart-in-place)")
    p.add_argument("--no-jax-distributed", action="store_true",
                   help="skip the jax.distributed bootstrap (single-host "
                        "multi-process runs that share no accelerators)")
    p.add_argument("--engine", choices=["module", "graph"], default="module",
                   help="training engine: Module tracing (default) or the "
                        "Graph IR -> StableHLO -> Executor path")
    p.add_argument("--eval", action="store_true",
                   help="run the config's eval split after training")
    p.add_argument("--eval-every", type=int, default=None,
                   help="also run the eval split every N training steps "
                        "(results logged to the metrics stream; implies a "
                        "final --eval pass)")
    p.add_argument("--eval-batches", type=int, default=None,
                   help="cap eval to N batches")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    last = run(args)
    print(json.dumps({"final": last}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
