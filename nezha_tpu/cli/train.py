"""`nezha-train`: run any of the five benchmark configs end-to-end.

    python -m nezha_tpu.cli.train --config mlp_mnist --steps 200
    python -m nezha_tpu.cli.train --config resnet50_imagenet --mesh dp=8 \
        --batch-size 256 --steps 50 --platform cpu

Configs mirror BASELINE.json (SURVEY.md §0): mlp_mnist (single-process),
resnet50_imagenet (DP all-reduce), gpt2_124m (bf16 GEMM), bert_base_zero1
(ZeRO-1 reduce-scatter/all-gather), wrn101_large_batch (mixed bf16/fp32).
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time
from typing import Callable, Dict, Iterator, Optional

import numpy as np


def _parse_mesh(spec: Optional[str]) -> Optional[Dict[str, int]]:
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    return axes


class Config:
    def __init__(self, build_model: Callable, loss_fn: Callable,
                 batches: Callable[[int], Iterator[dict]],
                 build_optimizer: Callable, default_batch: int,
                 parallel_mode: str = "dp", default_mesh: str = "dp=-1",
                 eval_batches: Optional[Callable] = None,
                 eval_stat: Optional[Callable] = None):
        self.build_model = build_model
        self.loss_fn = loss_fn
        self.batches = batches
        self.build_optimizer = build_optimizer
        self.default_batch = default_batch
        self.parallel_mode = parallel_mode  # "single" | "dp" | "zero1"
        self.default_mesh = default_mesh
        self.eval_batches = eval_batches  # bs -> finite iterator, or None
        self.eval_stat = eval_stat        # stat fn for train.eval.evaluate


def _configs() -> Dict[str, Config]:
    # Imports deferred so `--help` stays instant.
    from nezha_tpu import data, models, ops, optim
    from nezha_tpu.models import bert as bert_mod
    from nezha_tpu.models import gpt2 as gpt2_mod
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train import eval as eval_mod

    ce = lambda logits, b: ops.softmax_cross_entropy_with_integer_labels(
        logits, b["label"])

    return {
        "mlp_mnist": Config(
            build_model=lambda: models.MLP(),
            loss_fn=ce,
            batches=lambda bs: data.mnist_batches(bs),
            build_optimizer=lambda steps: optim.momentum(0.1),
            default_batch=128,
            parallel_mode="single",
            eval_batches=lambda bs: data.mnist_batches(bs, split="test",
                                                       epochs=1),
            eval_stat=eval_mod.accuracy),
        "resnet50_imagenet": Config(
            build_model=lambda: models.resnet50(policy=bf16_policy()),
            loss_fn=ce,
            batches=lambda bs: data.synthetic_image_batches(bs),
            build_optimizer=lambda steps: optim.momentum(
                optim.warmup_cosine_schedule(0.4, 5 * 312, max(steps, 10)),
                beta=0.9, weight_decay=1e-4),
            default_batch=256,
            parallel_mode="dp"),
        "gpt2_124m": Config(
            build_model=lambda: models.gpt2_124m(),
            loss_fn=gpt2_mod.lm_loss,
            batches=lambda bs: data.synthetic_token_batches(bs, seq_len=1024),
            build_optimizer=lambda steps: optim.adamw(
                optim.warmup_cosine_schedule(6e-4, 100, max(steps, 200)),
                weight_decay=0.1),
            default_batch=8,
            parallel_mode="dp",
            eval_batches=lambda bs: itertools.islice(
                data.synthetic_token_batches(bs, seq_len=1024, seed=1), 8),
            eval_stat=eval_mod.lm_token_stats),
        "bert_base_zero1": Config(
            build_model=lambda: models.bert_base(),
            loss_fn=bert_mod.mlm_loss,
            batches=lambda bs: data.synthetic_mlm_batches(bs, seq_len=512),
            build_optimizer=lambda steps: optim.adamw(
                optim.warmup_cosine_schedule(1e-4, 100, max(steps, 200)),
                weight_decay=0.01),
            default_batch=16,
            parallel_mode="zero1"),
        "wrn101_large_batch": Config(
            build_model=lambda: models.wide_resnet101(policy=bf16_policy()),
            loss_fn=ce,
            batches=lambda bs: data.synthetic_image_batches(bs),
            build_optimizer=lambda steps: optim.momentum(
                optim.warmup_cosine_schedule(1.6, 500, max(steps, 1000)),
                beta=0.9, weight_decay=1e-4),
            default_batch=512,
            parallel_mode="dp"),
    }


def _join_world(args):
    """Multi-process launch: dial the coordinator before touching devices
    (SURVEY.md §3 call stack 1 — the reference dialed its gRPC coordinator
    for rank/world rendezvous, then initialized the device runtime).
    Returns (group, coordinator) — either may be None."""
    if not args.coordinator:
        return None, None
    from nezha_tpu import dist
    from nezha_tpu.utils import get_logger, set_rank

    host, _, port = args.coordinator.rpartition(":")
    coord = None
    if args.serve_coordinator:
        coord = dist.Coordinator(world_size=args.world_size, port=int(port))
    group = dist.join(host or "127.0.0.1", int(port),
                      rank_hint=args.rank_hint)
    set_rank(group.rank)
    get_logger("nezha_tpu.cli").info(
        "joined world: rank %d / %d", group.rank, group.world_size)
    if group.world_size > 1:
        # Rank 0 advertises the jax.distributed address; all ranks enter.
        dist.initialize_jax_distributed(group)
    return group, coord


def run(args) -> Dict[str, float]:
    group, coord = _join_world(args)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from nezha_tpu import parallel
    from nezha_tpu.runtime import Prefetcher
    from nezha_tpu.train import checkpoint as ckpt
    from nezha_tpu.train.loop import init_train_state, make_train_step

    cfg = _configs()[args.config]
    batch_size = args.batch_size or cfg.default_batch
    model = cfg.build_model()
    optimizer = cfg.build_optimizer(args.steps)
    rng = jax.random.PRNGKey(args.seed)

    mode = cfg.parallel_mode if len(jax.devices()) > 1 else "single"
    mesh = None
    if mode != "single":
        mesh_axes = _parse_mesh(args.mesh) or _parse_mesh(cfg.default_mesh)
        mesh = parallel.make_mesh(mesh_axes)

    # --- state ------------------------------------------------------------
    state = init_train_state(model, optimizer, rng)
    start_step = 0
    if args.ckpt_dir:
        restored, start_step = ckpt.try_restore(args.ckpt_dir, state)
        if restored is not None:
            state = restored
            print(f"resumed from step {start_step}", file=sys.stderr)

    if mode == "single":
        step_fn = make_train_step(model, optimizer, cfg.loss_fn)
        shard = lambda b: b
    elif mode == "dp":
        state = parallel.replicate(mesh, state)
        step_fn = parallel.make_dp_train_step(model, optimizer, cfg.loss_fn, mesh)
        shard = lambda b: parallel.shard_batch(mesh, b)
    elif mode == "zero1":
        variables = state["variables"]
        state = {
            "variables": parallel.replicate(mesh, variables),
            "opt_state": parallel.zero1_init_opt_state(
                optimizer, variables["params"], mesh),
            "rng": parallel.replicate(mesh, state["rng"]),
        }
        step_fn = parallel.make_zero1_train_step(model, optimizer,
                                                 cfg.loss_fn, mesh)
        shard = lambda b: parallel.shard_batch(mesh, b)
    else:
        raise ValueError(mode)

    # --- loop -------------------------------------------------------------
    source = cfg.batches(batch_size)
    prefetch = Prefetcher(source, depth=args.prefetch)
    from nezha_tpu.utils import MetricsLogger
    metrics_log = MetricsLogger(args.metrics_file) if args.metrics_file else None

    if args.profile_dir:
        import os as _os
        _os.makedirs(args.profile_dir, exist_ok=True)
        jax.profiler.start_trace(args.profile_dir)

    last: Dict[str, float] = {}
    t0 = time.perf_counter()
    window_t0, window_examples = t0, 0
    try:
        for i in range(args.steps):
            batch = shard(next(prefetch))
            state, metrics = step_fn(state, batch)
            window_examples += batch_size
            step_no = start_step + i + 1
            if step_no % args.log_every == 0:
                now = time.perf_counter()
                last = {k: float(v) for k, v in metrics.items()}
                last["examples_per_sec"] = window_examples / (now - window_t0)
                last["step"] = step_no
                window_t0, window_examples = now, 0
                print(json.dumps(last), file=sys.stderr)
                if metrics_log:
                    metrics_log.log(step_no, last)
            if (args.ckpt_every and args.ckpt_dir
                    and step_no % args.ckpt_every == 0):
                ckpt.save_checkpoint(args.ckpt_dir, state, step_no)
    finally:
        prefetch.close()
        if args.profile_dir:
            jax.profiler.stop_trace()
        if metrics_log:
            metrics_log.close()
        if group is not None:
            unwinding = sys.exc_info()[0] is not None
            if not unwinding:
                try:
                    group.barrier(timeout_s=600)  # all ranks finish first
                except Exception as e:
                    print(f"shutdown barrier skipped: {e}", file=sys.stderr)
            # Unwinding an exception: peers may never arrive — leave at
            # once so survivors' failure detectors see a clean departure
            # and the real error surfaces without a 600 s stall.
            group.leave()
        if coord is not None:
            coord.stop()
    if args.ckpt_dir:
        ckpt.save_checkpoint(args.ckpt_dir, state, start_step + args.steps)
    if args.eval and cfg.eval_batches is not None:
        from nezha_tpu.train.eval import evaluate
        results = evaluate(model, state["variables"],
                           cfg.eval_batches(batch_size),
                           stat_fn=cfg.eval_stat,
                           max_batches=args.eval_batches)
        print(json.dumps({"eval": results}), file=sys.stderr)
        last.update({f"eval_{k}": v for k, v in results.items()})
    return last


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nezha-train",
        description="TPU-native training CLI (configs mirror BASELINE.json)")
    p.add_argument("--config", required=True,
                   choices=["mlp_mnist", "resnet50_imagenet", "gpt2_124m",
                            "bert_base_zero1", "wrn101_large_batch"])
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=None,
                   help="global batch (default: per-config)")
    p.add_argument("--mesh", default=None,
                   help='mesh axes, e.g. "dp=8" or "dp=4,sp=2" (-1 = rest)')
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--prefetch", type=int, default=2)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=0)
    p.add_argument("--metrics-file", default=None,
                   help="append JSONL metrics here")
    p.add_argument("--profile-dir", default=None,
                   help="capture an XLA/TPU profiler trace here")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="rendezvous address for multi-process launch")
    p.add_argument("--serve-coordinator", action="store_true",
                   help="also run the coordinator here (rank-0 host)")
    p.add_argument("--world-size", type=int, default=1,
                   help="processes in the job (with --serve-coordinator)")
    p.add_argument("--rank-hint", type=int, default=-1,
                   help="preferred rank (e.g. for restart-in-place)")
    p.add_argument("--eval", action="store_true",
                   help="run the config's eval split after training")
    p.add_argument("--eval-batches", type=int, default=None,
                   help="cap eval to N batches")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    last = run(args)
    print(json.dumps({"final": last}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
