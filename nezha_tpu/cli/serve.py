"""`nezha-serve` — continuous-batching inference server.

The serving counterpart of `nezha-generate`: same three weight sources
(--ckpt-dir / --hf-dir / --random-init), but requests are admitted and
retired individually against the slot-pooled engine
(`nezha_tpu.serve`) — a late request joins the running batch instead of
waiting for it. Two front ends, zero new dependencies:

stdio JSONL (default) — one request object per stdin line, streamed
events per stdout line::

    {"id": "a", "prompt_tokens": [5, 17, 3], "max_new_tokens": 8}
    {"id": "b", "prompt": "hello", "temperature": 0.8, "top_p": 0.9}

    -> {"id": "a", "event": "token", "token": 42}
       ...
       {"id": "a", "event": "done", "tokens": [...], "finish_reason":
        "length", "ttft_s": ..., "latency_s": ...}

HTTP (--http PORT, stdlib http.server) — POST /generate with the same
request object (response once finished; queue-full = 503), GET /healthz
for liveness + occupancy, GET /stats for the LIVE telemetry registry
snapshot (stats schema v1 — counters/gauges/histogram summaries you can
curl mid-run; the router's version aggregates the whole fleet).
Requests may carry a distributed ``trace_id`` (field or X-Nezha-Trace
header; minted automatically per --trace-sample when a --run-dir run is
active) — ``nezha-telemetry RUN_DIR --trace`` stitches the resulting
per-replica span fragments into per-request timelines.

Lifecycle: SIGTERM/SIGINT triggers a GRACEFUL DRAIN — admission closes
immediately (stdio stops reading stdin; HTTP answers 503 "draining" on
POST /generate and flips /healthz to 503), in-flight requests keep
decoding for up to --drain-timeout seconds, stragglers retire with
finish_reason "deadline", and stdio flushes a final {"event": "drain"}
line before exit. A second signal during the drain is ignored (the
drain is already as fast as the deadline allows). With
--decode-horizon N the drain cutoff lands on a block boundary, so the
drain (like deadlines) is granular to one horizon — up to N tokens
later than the signal. NEZHA_FAULT_PLAN / NEZHA_FAULT_SEED install a
fault-injection plan for chaos drills (docs/RUNBOOK.md §9).

Scale-out (--replicas N, N > 1, requires --http): the process becomes a
ROUTER/SUPERVISOR front end instead of an engine — the supervisor
spawns N worker processes (each this same single-replica stack, via
run_worker(), on its own port), the router probes their /healthz,
load-balances by live queue depth, fails a request over to another
replica when its replica dies before answering, and restarts crashed
workers with capped backoff (circuit breaker after --max-restart-
failures consecutive startup failures). SIGTERM then performs a
ROLLING drain: replicas stop one at a time, each finishing its
in-flight work, so capacity never drops to zero until the end
(docs/RUNBOOK.md §10).

With --run-dir the run writes the standard telemetry artifacts;
`nezha-telemetry RUN_DIR` then renders the serving section (TTFT/TPOT
percentiles, tokens/sec, batch occupancy).

    nezha-serve --ckpt-dir runs/gpt2 --model-preset tiny \
        --max-batch-size 8 --max-len 96 --run-dir /tmp/serve
    nezha-serve --hf-dir /ckpts/gpt2 --http 8000
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nezha-serve", description=__doc__)
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--ckpt-dir",
                     help="checkpoint dir written by nezha-train")
    src.add_argument("--hf-dir",
                     help="Hugging Face GPT2LMHeadModel directory")
    src.add_argument("--random-init", action="store_true",
                     help="fresh random weights (smoke/benchmark runs)")
    p.add_argument("--model-preset", choices=["full", "tiny"],
                   default="full")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer dir for text prompts/output (defaults "
                        "to --hf-dir's shipped tokenizer; else text "
                        "prompts use byte-level ids)")
    p.add_argument("--max-batch-size", type=int, default=4,
                   help="decode slots (concurrent in-flight requests)")
    p.add_argument("--max-len", type=int, default=96,
                   help="per-slot KV capacity: prompt + generated tokens")
    p.add_argument("--max-prefill-len", type=int, default=32,
                   help="widest single prefill chunk; longer prompts "
                        "(up to --max-len) prefill in successive chunks")
    p.add_argument("--prefill-buckets", default=None,
                   help="comma-separated static prompt pad widths (one "
                        "compiled prefill program each, last must equal "
                        "--max-prefill-len); default: powers of two up "
                        "to --max-prefill-len")
    p.add_argument("--decode-impl",
                   choices=["auto", "kernel", "xla"], default=None,
                   help="decode attention: auto = Pallas flash-decode "
                        "kernel on TPU / composed elsewhere, kernel = "
                        "force the kernel (interpret off-TPU), xla = "
                        "force the composed masked path; default: the "
                        "model config's choice (auto)")
    p.add_argument("--prefill-impl",
                   choices=["auto", "kernel", "xla"], default=None,
                   help="paged prefill attention: auto = Pallas "
                        "flash-prefill kernel on TPU / composed "
                        "elsewhere, kernel = force the kernel "
                        "(interpret off-TPU; int8 pools fuse the block "
                        "write into its epilogue), xla = force the "
                        "composed masked path; NEZHA_NO_PREFILL_KERNEL=1 "
                        "is the env escape hatch; default: the model "
                        "config's choice (auto)")
    p.add_argument("--prefill-mode", choices=["replicated", "sequence"],
                   default="replicated",
                   help="prefill chunk parallelism: replicated = every "
                        "mesh device computes the full chunk (default); "
                        "sequence = shard the chunk over the sequence "
                        "axis of the 1xM mesh (ring/ulysses attention, "
                        "blocks land head-sharded in the paged pool — "
                        "long-context prompts, docs/RUNBOOK.md §8). "
                        "Requires --mesh M > 1; "
                        "NEZHA_NO_SEQ_PREFILL=1 is the env escape "
                        "hatch back to replicated")
    p.add_argument("--long-prefill-buckets", default=None,
                   help="comma-separated extra prefill pad widths "
                        "ABOVE --max-prefill-len (one compiled program "
                        "each, still inside --max-len) so an 8k/32k "
                        "prompt prefills in a few wide chunks instead "
                        "of hundreds of --max-prefill-len strides; "
                        "default: none")
    p.add_argument("--seq-prefill-variant",
                   choices=["auto", "ulysses", "ring"], default="auto",
                   help="sequence-mode attention algorithm: ulysses = "
                        "all-to-all head exchange (bitwise-identical "
                        "outputs, needs heads %% mesh == 0); ring = "
                        "ppermute ring hops (greedy-equivalent); auto "
                        "= ulysses (docs/RUNBOOK.md §8 selection "
                        "table)")
    p.add_argument("--decode-horizon", type=int, default=1,
                   help="tokens decoded per compiled step dispatch (the "
                        "device-resident sampling loop): 1 = classic "
                        "per-token stepping; N > 1 amortizes the host "
                        "gap over N tokens — streaming still emits "
                        "per-token events, but deadline/drain "
                        "granularity coarsens to one horizon "
                        "(docs/RUNBOOK.md §8)")
    p.add_argument("--kv-layout", choices=["paged", "dense"],
                   default="paged",
                   help="KV pool layout: paged = block-paged pool with "
                        "ref-counted blocks, lazy binding, and shared-"
                        "prefix prefill reuse (default); dense = the "
                        "classic worst-case per-slot reservation")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="paged layout: tokens per KV block")
    p.add_argument("--kv-num-blocks", type=int, default=None,
                   help="paged layout: total pool blocks (block 0 is "
                        "scratch); default = dense-equivalent capacity "
                        "(1 + max_batch_size * ceil(max_len/block)); "
                        "smaller makes resident tokens, not slots, the "
                        "admission limit")
    p.add_argument("--kv-dtype", choices=["bf16", "int8"],
                   default="bf16",
                   help="KV block storage: bf16 = store --cache-dtype "
                        "(bit-identical to the classic engine); int8 = "
                        "int8 blocks + per-block fp32 scales (paged "
                        "layout only) — ~2x resident requests at the "
                        "same device budget, dequantized inside the "
                        "flash-decode kernel (docs/RUNBOOK.md §8)")
    p.add_argument("--prefix-cache", choices=["on", "off"], default="on",
                   help="paged layout: reuse cached blocks for "
                        "requests whose prompt prefix matches (TTFT "
                        "collapses for templated traffic)")
    p.add_argument("--kv-eviction", choices=["lru", "none"],
                   default="lru",
                   help="paged layout: when the free list runs dry, "
                        "evict LRU prefix-cache blocks (lru) or go "
                        "straight to typed backpressure (none)")
    p.add_argument("--kv-host-blocks", type=int, default=0,
                   help="host KV spill tier (requires --kv-dtype int8 "
                        "+ --kv-eviction lru): evicted prefix-cache "
                        "blocks demote their int8+scales payload into "
                        "a host-RAM LRU of up to N blocks instead of "
                        "being discarded, and a returning prefix hit "
                        "promotes them back with an async host-to-"
                        "device copy ahead of the prefill — turn-N+1 "
                        "chat traffic pays one tail chunk, not a cold "
                        "prefill; /healthz reports the tier's "
                        "occupancy (docs/RUNBOOK.md §8). 0 = off")
    p.add_argument("--speculative", action="store_true",
                   help="speculative decoding: a cheap DRAFT model "
                        "proposes --draft-k tokens per window, one "
                        "batched target forward verifies them all, and "
                        "the longest agreeing prefix is emitted — "
                        ">1 token per verify dispatch at unchanged "
                        "outputs (greedy bit-identical; sampled via "
                        "lossless rejection sampling). Draft KV lives "
                        "in a mirrored paged pool (int8 included); see "
                        "docs/RUNBOOK.md §8 for when a draft pays off")
    p.add_argument("--draft-k", type=int, default=4,
                   help="speculative: draft tokens proposed per verify "
                        "window (a window emits 1..draft_k+1 tokens)")
    p.add_argument("--draft-layers", type=int, default=None,
                   help="speculative: SELF-DRAFT depth — the draft is "
                        "the target's first N layers sharing its "
                        "weights (early-exit drafting, no second "
                        "checkpoint); default: full depth (identity "
                        "draft, accept-rate ~1). Ignored with "
                        "--draft-ckpt-dir/--draft-hf-dir")
    p.add_argument("--draft-ckpt-dir", default=None,
                   help="speculative: load a SEPARATE draft model from "
                        "this nezha-train checkpoint dir (same "
                        "tokenizer/vocab as the target)")
    p.add_argument("--draft-hf-dir", default=None,
                   help="speculative: load the draft model from a "
                        "Hugging Face GPT2LMHeadModel directory")
    p.add_argument("--k-max", type=int, default=64,
                   help="static top-k cap; per-request top_k is clamped "
                        "to it")
    p.add_argument("--queue-capacity", type=int, default=16,
                   help="admission queue bound (backpressure past it)")
    p.add_argument("--priority-weights", default=None, metavar="SPEC",
                   help="WFQ admission-grant weights per priority lane "
                        "as 'interactive=4,batch=2,background=1' (the "
                        "default split): per 7 grants under full "
                        "backlog, 4 go interactive, 2 batch, 1 "
                        "background — lower lanes slow, never starve. "
                        "All three classes required, integer weights "
                        ">= 1")
    p.add_argument("--tenant-queue-cap", type=int, default=None,
                   help="max queued requests any ONE tenant may hold; "
                        "past it the tenant gets a typed "
                        "tenant_over_limit 503 while others keep "
                        "admitting (default: no per-tenant cap — only "
                        "the global --queue-capacity)")
    p.add_argument("--preemption", choices=["on", "off"], default="off",
                   help="under slot/block pressure (or a burning "
                        "interactive --slo), SUSPEND the lowest-"
                        "priority running decode — its KV blocks move "
                        "to the prefix trie (LRU-evictable, host-tier "
                        "demotable) — and resume it bit-identically "
                        "when pressure clears (docs/RUNBOOK.md §10)")
    p.add_argument("--preemption-budget", type=int, default=2,
                   help="times one request may be preempted before it "
                        "becomes unpreemptable (the anti-thrash bound)")
    p.add_argument("--autoscale-min", type=int, default=None,
                   help="with --replicas and --autoscale-max: elastic "
                        "LOWER bound on the replica count — the "
                        "supervisor rolling-drains one replica at a "
                        "time down to it when the fleet goes idle")
    p.add_argument("--autoscale-max", type=int, default=None,
                   help="with --replicas and --autoscale-min: elastic "
                        "UPPER bound — the supervisor spawns one "
                        "replica at a time up to it under sustained "
                        "queue/prefill-wait pressure (hysteresis: "
                        "sustained signal + cooldown between actions)")
    p.add_argument("--max-new-tokens", type=int, default=32,
                   help="default for requests that don't set "
                        "max_new_tokens, and the cap for those that do")
    p.add_argument("--eos-id", type=int, default=None,
                   help="default EOS for requests that don't set one; "
                        "defaults to the tokenizer's EOS when loaded, "
                        "-1 disables even then")
    p.add_argument("--cache-dtype", choices=["bf16", "f32"], default="bf16",
                   help="KV pool dtype (f32 for bit-exact parity checks)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="graceful-drain budget in seconds after SIGTERM/"
                        "SIGINT: admission closes at the signal, "
                        "in-flight requests may finish within this "
                        "window, stragglers retire with finish_reason "
                        "'deadline'")
    p.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="serve HTTP on PORT instead of stdio JSONL")
    p.add_argument("--role", choices=["prefill", "decode", "both"],
                   default="both",
                   help="this replica's serving tier (surfaced in "
                        "/healthz and the router's replica table): "
                        "'prefill' members take admissions and park "
                        "prompt KV for migration, 'decode' members "
                        "pull migrated KV and stream tokens, 'both' "
                        "(default) does everything — the role is "
                        "routing metadata; every worker keeps the full "
                        "engine so degraded topologies still serve")
    p.add_argument("--prefill-replicas", type=int, default=0,
                   help="with --decode-replicas: run a DISAGGREGATED "
                        "front end of this many role=prefill workers "
                        "plus the decode tier (overrides --replicas; "
                        "requires --http) — admissions land on the "
                        "prefill tier and finished prompts' KV "
                        "migrates to the decode tier "
                        "(docs/RUNBOOK.md §10)")
    p.add_argument("--decode-replicas", type=int, default=0,
                   help="number of role=decode workers of the "
                        "disaggregated front end (see "
                        "--prefill-replicas)")
    p.add_argument("--replicas", type=int, default=1,
                   help="N > 1 turns this process into a router/"
                        "supervisor front end over N engine worker "
                        "processes (requires --http; each worker is "
                        "the single-replica stack on its own port)")
    p.add_argument("--mesh", type=int, default=1,
                   help="M > 1 makes each replica an M-device TENSOR-"
                        "PARALLEL engine (serve/sharded): parameters "
                        "Megatron-sharded and the paged K/V pools "
                        "head-sharded across a 1xM mesh, block tables "
                        "host-side, the frozen program contract "
                        "preserved per mesh. With --ckpt-dir the "
                        "train->serve resharding (nezha-reshard) runs "
                        "implicitly at startup, CRC-verified — a "
                        "corrupt checkpoint refuses to start. Composes "
                        "with --replicas: N routed replicas x M-device "
                        "meshes (docs/RUNBOOK.md §10). Requires "
                        "kv-layout=paged and num_heads %% M == 0")
    p.add_argument("--replica-backend", choices=["process", "thread"],
                   default="process",
                   help="how workers are hosted: 'process' spawns real "
                        "nezha-serve subprocesses (production — an OS "
                        "failure domain each); 'thread' hosts them "
                        "in-process (tests/benchmarks — no spawn cost, "
                        "no OS isolation)")
    p.add_argument("--probe-interval", type=float, default=0.5,
                   help="seconds between per-replica /healthz probes")
    p.add_argument("--probe-misses", type=int, default=3,
                   help="consecutive missed probes that eject a replica "
                        "from routing (one success readmits it)")
    p.add_argument("--route-retries", type=int, default=2,
                   help="times one request may be re-dispatched after "
                        "its replica died before answering (seeded "
                        "backoff between attempts); a committed "
                        "response is never retried")
    p.add_argument("--restart-backoff", type=float, default=0.25,
                   help="base seconds of the capped-exponential restart "
                        "backoff for crashed replicas")
    p.add_argument("--max-restart-failures", type=int, default=5,
                   help="consecutive startup failures after which a "
                        "replica's circuit breaker opens (the "
                        "supervisor stops restarting it)")
    p.add_argument("--affinity-routing", choices=["on", "off"],
                   default=None,
                   help="route multi-replica token-id requests by "
                        "prefix AFFINITY (serve/fleetcache): each "
                        "replica piggybacks a bounded trie digest on "
                        "/healthz, the router scores candidates by "
                        "expected-prefix-hit-length x load and hands "
                        "near-miss picks a peer pull_from hint over "
                        "the /kv_export wire. Default: on when "
                        "--replicas > 1, off otherwise")
    p.add_argument("--digest-interval", type=float, default=2.0,
                   help="seconds between fleet-digest rebuilds on each "
                        "replica (the /healthz digest payload's "
                        "staleness cadence)")
    p.add_argument("--digest-max-entries", type=int, default=256,
                   help="bound on prefix-hash entries one replica "
                        "advertises per digest (recency-first "
                        "truncation)")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="fraction of requests that carry a distributed "
                        "trace id (per-request lifecycle spans stitched "
                        "by 'nezha-telemetry RUN_DIR --trace'): 1.0 "
                        "traces every request, 0.0 disables minting — "
                        "the load knob for high-traffic fleets. Only "
                        "meaningful with --run-dir (no run = no spans)")
    p.add_argument("--run-dir", default=None,
                   help="write telemetry artifacts (metrics.jsonl / "
                        "spans.jsonl / events.jsonl / summary.json) "
                        "here")
    p.add_argument("--slo", action="append", default=None,
                   metavar="SPEC",
                   help="declarative SLO evaluated per window, e.g. "
                        "'serve.ttft_s p99 < 0.5 over 60s "
                        "[objective 0.99]' (repeatable, or "
                        "';'-separated). Evaluations and burn-rate "
                        "alerts stream to events.jsonl as typed "
                        "records; 'nezha-telemetry RUN_DIR --slo' "
                        "renders compliance/burn. Implies the "
                        "watchdog thread")
    p.add_argument("--watchdog-interval", type=float, default=0.0,
                   metavar="SECONDS",
                   help="run the anomaly watchdog (sustained queue "
                        "depth, TTFT regression vs trailing baseline, "
                        "replica flap, SLO burn) every SECONDS, "
                        "emitting typed events to events.jsonl; 0 "
                        "disables (default; --slo implies 10s)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu)")
    return p


def _build_stack(args):
    """(scheduler, tokenizer, eos_id) from parsed args."""
    import jax.numpy as jnp

    from nezha_tpu.cli.common import load_gpt2_for_inference
    from nezha_tpu.cli.generate import _load_tokenizer
    from nezha_tpu.serve import Engine, ServeConfig, Scheduler

    mesh_m = int(getattr(args, "mesh", 1) or 1)
    if mesh_m > 1 and getattr(args, "ckpt_dir", None):
        # The implicit nezha-reshard: build the serve mesh first, then
        # stream the training checkpoint straight into the head-sharded
        # layout (CRC-verified, one leaf of host memory at a time) —
        # the full-gather-then-scatter a naive load would do is exactly
        # what arXiv:2112.01075 exists to avoid. A corrupt or missing
        # checkpoint is a typed REFUSAL to start, never garbage served.
        import jax as _jax

        from nezha_tpu.cli.common import gpt2_for_preset
        from nezha_tpu.parallel.mesh import make_mesh
        from nezha_tpu.serve.sharded import (ReshardError,
                                             reshard_checkpoint)
        model = gpt2_for_preset(args.model_preset)
        # Engine-topology constraints checked BEFORE the (potentially
        # minutes-long) checkpoint load — a doomed mesh must refuse in
        # milliseconds, typed, not traceback after the reshard.
        if model.cfg.num_heads % mesh_m:
            raise SystemExit(
                f"--mesh {mesh_m}: num_heads="
                f"{model.cfg.num_heads} not divisible by the mesh — "
                f"K/V pools shard on the head axis")
        if args.kv_layout != "paged":
            raise SystemExit(
                f"--mesh {mesh_m} requires --kv-layout paged (the "
                f"dense layout has no head-sharded pool)")
        mesh = make_mesh({"tp": mesh_m},
                         devices=_jax.devices()[:mesh_m])
        try:
            variables, step = reshard_checkpoint(args.ckpt_dir, model,
                                                 mesh)
        except ReshardError as e:
            raise SystemExit(f"--mesh {mesh_m}: reshard refused: {e}")
        print(f"resharded step {step} from {args.ckpt_dir} onto a "
              f"1x{mesh_m} serve mesh", file=sys.stderr)
    else:
        model, variables = load_gpt2_for_inference(args)
    tokenizer = _load_tokenizer(args)
    from nezha_tpu.cli.common import resolve_eos_id
    eos_id = resolve_eos_id(args.eos_id, tokenizer, model.cfg.vocab_size)
    max_len = min(args.max_len, model.cfg.max_positions)
    buckets = ()
    if args.prefill_buckets:
        try:
            buckets = tuple(int(b) for b in
                            str(args.prefill_buckets).split(","))
        except ValueError:
            raise SystemExit(
                f"--prefill-buckets must be comma-separated ints, got "
                f"{args.prefill_buckets!r}")
    long_buckets = ()
    if getattr(args, "long_prefill_buckets", None):
        try:
            long_buckets = tuple(
                int(b) for b in
                str(args.long_prefill_buckets).split(","))
        except ValueError:
            raise SystemExit(
                f"--long-prefill-buckets must be comma-separated ints, "
                f"got {args.long_prefill_buckets!r}")
    prefill_mode = getattr(args, "prefill_mode", "replicated")
    if prefill_mode == "sequence" and mesh_m < 2:
        # Typed refusal BEFORE any engine build: sequence sharding
        # splits the chunk over mesh devices, so a 1-device mesh has
        # nothing to shard over.
        raise SystemExit(
            "--prefill-mode sequence requires --mesh M with M > 1 "
            "(the chunk is sharded over the mesh's sequence axis)")
    spec = None
    draft_model = draft_variables = None
    if not getattr(args, "speculative", False) and (
            getattr(args, "draft_ckpt_dir", None)
            or getattr(args, "draft_hf_dir", None)):
        # A draft checkpoint without the knob would silently serve
        # classic — the operator believes their draft is in play.
        raise SystemExit(
            "--draft-ckpt-dir/--draft-hf-dir require --speculative")
    if getattr(args, "speculative", False):
        from nezha_tpu.serve.engine import SpeculativeConfig
        spec = SpeculativeConfig(draft_k=args.draft_k,
                                 draft_layers=args.draft_layers)
        if getattr(args, "draft_ckpt_dir", None) \
                or getattr(args, "draft_hf_dir", None):
            # An explicit draft checkpoint rides the SAME cli/common
            # loader as the target (either nezha-train format or an HF
            # dir); without one the engine builds an early-exit
            # self-draft from the target's own weights.
            dargs = argparse.Namespace(**vars(args))
            dargs.ckpt_dir = args.draft_ckpt_dir
            dargs.hf_dir = args.draft_hf_dir
            dargs.random_init = False
            draft_model, draft_variables = load_gpt2_for_inference(dargs)
    try:
        cfg = ServeConfig(
            max_batch_size=args.max_batch_size, max_len=max_len,
            max_prefill_len=args.max_prefill_len,
            prefill_buckets=buckets,
            long_prefill_buckets=long_buckets,
            prefill_mode=prefill_mode,
            seq_prefill_variant=getattr(args, "seq_prefill_variant",
                                        "auto"),
            k_max=args.k_max,
            queue_capacity=args.queue_capacity,
            cache_dtype=jnp.float32 if args.cache_dtype == "f32"
            else jnp.bfloat16,
            decode_impl=args.decode_impl,
            prefill_impl=args.prefill_impl,
            decode_horizon=args.decode_horizon,
            kv_layout=args.kv_layout,
            kv_block_size=args.kv_block_size,
            kv_num_blocks=args.kv_num_blocks,
            prefix_cache=args.prefix_cache == "on",
            kv_eviction=args.kv_eviction,
            kv_dtype=args.kv_dtype,
            kv_host_blocks=args.kv_host_blocks,
            speculative=spec,
            priority_weights=_parse_priority_weights(
                getattr(args, "priority_weights", None)),
            tenant_queue_cap=getattr(args, "tenant_queue_cap", None),
            preemption=getattr(args, "preemption", "off") == "on",
            preemption_budget=getattr(args, "preemption_budget", 2))
    except ValueError as e:
        # ServeConfig's own validation (bucket ordering, long buckets
        # outside (max_prefill_len, max_len], unknown modes) as the
        # CLI's typed refusal.
        raise SystemExit(f"serve config: {e}")
    if mesh_m > 1:
        from nezha_tpu.serve.sharded import ShardedEngine
        try:
            engine = ShardedEngine(model, variables, cfg,
                                   mesh_devices=mesh_m,
                                   draft_model=draft_model,
                                   draft_variables=draft_variables)
        except ValueError as e:
            # Topology constraints (heads %% mesh, paged-only, device
            # count) as the CLI's typed refusal — the non-ckpt paths
            # reach here without the pre-reshard check above.
            raise SystemExit(f"--mesh {mesh_m}: {e}")
    else:
        engine = Engine(model, variables, cfg, draft_model=draft_model,
                        draft_variables=draft_variables)
    scheduler = Scheduler(engine)
    if getattr(args, "slo", None):
        # The first serve.ttft_s SLO spec doubles as the scheduler's
        # preemption control signal (PR 16 -> PR 19): its burn rate,
        # fed per interactive first token, widens the preemption quota
        # while the error budget is burning. The watchdog keeps its
        # own independent trackers.
        from nezha_tpu import obs
        for slo_cfg in obs.parse_slo_args(args.slo):
            if slo_cfg.metric == "serve.ttft_s":
                scheduler.slo_tracker = obs.SLOTracker(slo_cfg)
                break
    return scheduler, tokenizer, eos_id


def _parse_priority_weights(spec):
    """'interactive=4,batch=2,background=1' -> dict (None passes
    through — ServeConfig then applies the default split)."""
    if spec is None:
        return None
    out = {}
    for part in str(spec).split(","):
        name, eq, val = part.partition("=")
        try:
            out[name.strip()] = int(val)
        except ValueError:
            raise SystemExit(
                f"--priority-weights must be 'class=int,...' pairs, "
                f"got {part!r}")
        if not eq:
            raise SystemExit(
                f"--priority-weights must be 'class=int,...' pairs, "
                f"got {part!r}")
    return out


def _parse_request(obj: dict, args, tokenizer, eos_id, vocab: int):
    """One wire object -> serve.Request. Raises ValueError on bad input."""
    from nezha_tpu.serve import Request
    if not isinstance(obj, dict):
        raise ValueError("request must be a JSON object")
    if ("prompt_tokens" in obj) == ("prompt" in obj):
        raise ValueError("pass exactly one of prompt_tokens / prompt")
    if "prompt_tokens" in obj:
        prompt = [int(t) for t in obj["prompt_tokens"]]
    else:
        text = obj["prompt"]
        if not isinstance(text, str) or not text:
            raise ValueError("prompt must be a non-empty string")
        if tokenizer is not None:
            from nezha_tpu.data.tokenizer import encode_plain
            prompt = encode_plain(tokenizer, text)
        else:
            prompt = list(text.encode("utf-8"))
    if not prompt:
        raise ValueError("prompt encoded to zero tokens")
    if max(prompt) >= vocab or min(prompt) < 0:
        raise ValueError(f"prompt ids must be in [0, {vocab})")
    def num(key, cast, default=None):
        # Coerce HERE so a malformed field is a per-request error (400 /
        # error event), never an exception inside the decode loop.
        v = obj.get(key, default)
        if v is None:
            return None
        try:
            return cast(v)
        except (TypeError, ValueError):
            raise ValueError(f"{key} must be a number, got {v!r}")

    # --max-new-tokens is both the default and the per-request CAP: the
    # operator's bound on how long one request may monopolize a slot.
    max_new = min(num("max_new_tokens", int, args.max_new_tokens),
                  args.max_new_tokens)
    trace_id = obj.get("trace_id")
    if trace_id is not None and not isinstance(trace_id, str):
        raise ValueError(f"trace_id must be a string, got {trace_id!r}")
    # Multi-tenant scheduling fields (PR 19). Defaults reproduce the
    # pre-priority wire bit for bit: every request lands in the
    # interactive lane of the "default" tenant, where WFQ degenerates
    # to the classic bounded FIFO. Value validation (known class,
    # non-empty tenant) is submit()'s — it owns the typed 400.
    priority = obj.get("priority", "interactive")
    if not isinstance(priority, str):
        raise ValueError(f"priority must be a string, got {priority!r}")
    tenant_id = obj.get("tenant_id", "default")
    if not isinstance(tenant_id, str):
        raise ValueError(
            f"tenant_id must be a string, got {tenant_id!r}")
    return Request(
        priority=priority, tenant_id=tenant_id,
        prompt=prompt, max_new_tokens=max_new,
        temperature=num("temperature", float, 0.0),
        top_k=num("top_k", int), top_p=num("top_p", float),
        eos_id=num("eos_id", int, eos_id),
        seed=num("seed", int, args.seed),
        deadline_s=num("deadline_s", float),
        request_id=obj.get("id"),
        # Disaggregation: prefill and PARK for migration (the router's
        # phase-one dispatch) instead of decoding here.
        prefill_only=bool(obj.get("prefill_only", False)),
        # Distributed tracing: the router-minted id this request's
        # lifecycle spans carry. "" is a real verdict — "routed and
        # sampled out" — which the scheduler honors by NOT minting;
        # only an absent field (None) lets it mint for itself.
        trace_id=trace_id)


def _decode_text(tokens, tokenizer):
    if tokenizer is not None:
        return tokenizer.decode(tokens)
    return bytes(t for t in tokens if t < 256).decode(
        "utf-8", errors="replace")


def _result_obj(res, tokenizer) -> dict:
    out = {"id": res.request_id, "event": "done", "tokens": res.tokens,
           "text": _decode_text(res.tokens, tokenizer),
           "finish_reason": res.finish_reason, "ttft_s": res.ttft_s,
           "latency_s": res.latency_s}
    if res.error is not None:     # finish_reason "error": what broke
        out["error"] = res.error
    return out


def _drain(scheduler, budget_s: float, drive: bool,
           dead: Optional[threading.Event] = None,
           abort: Optional[threading.Event] = None) -> int:
    """Graceful-drain tail shared by both front ends: keep the decode
    loop running (``drive=True`` steps it here; ``drive=False`` trusts a
    live decode thread, passing its death signal as ``dead`` and the
    server's shutdown signal as ``abort``) until in-flight work
    finishes, ``budget_s`` expires, or one of the signals fires —
    nothing will ever finish after the engine dies, so waiting out the
    budget only delays shutdown. Stragglers are cancelled with
    finish_reason "deadline" — or "error" when the engine died, so an
    engine crash at shutdown is never dressed up as a routine deadline.
    Returns how many were cancelled; the whole window is the
    ``serve.drain`` span the telemetry report surfaces."""
    from nezha_tpu import obs
    from nezha_tpu.serve import FinishReason
    reason, error = FinishReason.DEADLINE, None
    with obs.span("serve.drain", budget_s=budget_s) as sp:
        t_end = time.monotonic() + budget_s
        while scheduler.has_work() and time.monotonic() < t_end:
            if dead is not None and dead.is_set():
                reason = FinishReason.ERROR
                error = "decode loop died during drain"
                break
            if abort is not None and abort.is_set():
                break
            if drive:
                if not scheduler.step():
                    time.sleep(0.002)
            else:
                time.sleep(0.005)
        cancelled = scheduler.cancel_remaining(reason, error=error)
        sp.set(cancelled=cancelled, reason=reason)
    return cancelled


# ------------------------------------------------------------- stdio mode
def run_stdio(scheduler, args, tokenizer, eos_id,
              stdin=None, stdout=None, drain=None) -> int:
    """JSONL in, JSONL events out. A reader thread feeds the admission
    queue as lines arrive (QueueFull = wait: stdin IS the backpressure
    channel); the caller's thread drives the decode loop. Setting
    ``drain`` (the signal handlers do) closes admission, finishes
    in-flight work within --drain-timeout, and flushes one final
    ``{"event": "drain"}`` line."""
    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    drain = drain if drain is not None else threading.Event()
    vocab = scheduler.engine.vocab
    out_lock = threading.Lock()

    def emit(obj):
        with out_lock:
            stdout.write(json.dumps(obj) + "\n")
            stdout.flush()

    scheduler.on_token = lambda rid, tok: emit(
        {"id": rid, "event": "token", "token": tok})

    def on_finish(res):
        emit(_result_obj(res, tokenizer))
        # The done event IS the delivery — drop the stored result, or a
        # long-lived server leaks every retired request's token list.
        scheduler.results.pop(res.request_id, None)

    scheduler.on_finish = on_finish

    from nezha_tpu.serve import QueueFull
    done_reading = threading.Event()

    def reader():
        try:
            for line in stdin:
                if drain.is_set():
                    # Admission closed with this line already read off
                    # stdin: answer it (the stdio analogue of HTTP's
                    # 503) before stopping, so the client isn't left
                    # waiting for an event that will never come. Lines
                    # never read stay un-accepted — the final drain
                    # event tells the client to stop expecting answers.
                    if line.strip():
                        try:
                            obj = json.loads(line)
                            rid = obj.get("id") \
                                if isinstance(obj, dict) else None
                        except ValueError:
                            rid = None
                        emit({"id": rid, "event": "error",
                              "error": "draining"})
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    emit({"id": None, "event": "error",
                          "error": "line is not valid JSON"})
                    continue
                try:
                    req = _parse_request(obj, args, tokenizer, eos_id,
                                         vocab)
                except ValueError as e:
                    emit({"id": obj.get("id")
                          if isinstance(obj, dict) else None,
                          "event": "error", "error": str(e)})
                    continue
                while True:
                    if drain.is_set():
                        # Admission closed with this request parsed but
                        # never submitted: answer it (the stdio analogue
                        # of HTTP's 503) so the client isn't left
                        # waiting for an event that will never come.
                        emit({"id": req.request_id, "event": "error",
                              "error": "draining"})
                        break
                    # Wait for queue room rather than spamming submit:
                    # stdin is the backpressure channel, and QueueFull
                    # increments the rejected_total SHED metric.
                    if scheduler.queue_depth >= scheduler.queue_capacity:
                        time.sleep(0.005)
                        continue
                    try:
                        scheduler.submit(req)
                        break
                    except QueueFull:   # raced a burst; keep waiting
                        time.sleep(0.005)
                    except ValueError as e:
                        emit({"id": req.request_id, "event": "error",
                              "error": str(e)})
                        break
        finally:
            done_reading.set()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    while ((not done_reading.is_set() or scheduler.has_work())
           and not drain.is_set()):
        if not scheduler.step():
            time.sleep(0.002)
    if drain.is_set():
        cancelled = _drain(scheduler, args.drain_timeout, drive=True)
        # The final flushed event: supervisors tailing stdout know the
        # drain ran and whether the deadline cut anything off.
        emit({"id": None, "event": "drain", "cancelled": cancelled})
    return 0


# -------------------------------------------------------------- http mode
def run_http(scheduler, args, tokenizer, eos_id, port: int,
             ready_cb=None, drain=None) -> int:
    """Stdlib http.server front end: POST /generate (blocks until the
    request retires; 503 on queue-full backpressure), GET /healthz.
    Handlers run on server threads; one daemon thread drives decode.
    Setting ``drain`` (the signal handlers do) closes admission (POST ->
    503 "draining", /healthz -> 503), lets in-flight requests finish
    within --drain-timeout, then shuts the server down."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from nezha_tpu.serve import QueueFull, TenantOverLimit

    drain = drain if drain is not None else threading.Event()
    vocab = scheduler.engine.vocab
    events = {}
    events_lock = threading.Lock()

    def on_finish(res):
        with events_lock:
            ev = events.get(res.request_id)
        if ev is not None:
            ev.set()

    scheduler.on_finish = on_finish
    stop = threading.Event()          # server is shutting down (any cause)
    engine_dead = threading.Event()   # the decode loop CRASHED (subset)

    def loop():
        # Fail LOUD and release every waiter: a dead decode thread with
        # handlers parked on ev.wait() would hang the server silently
        # (healthz keeps answering) — instead surface 500s/503s.
        try:
            while not stop.is_set():
                if not scheduler.step():
                    time.sleep(0.002)
        except Exception:
            import traceback
            traceback.print_exc()
            engine_dead.set()
            stop.set()
            with events_lock:
                for ev in events.values():
                    ev.set()

    decode_thread = threading.Thread(target=loop, daemon=True)
    decode_thread.start()

    class Handler(BaseHTTPRequestHandler):
        # Bound the life of a stalled connection (a client that never
        # finishes its upload) so joining handler threads at shutdown
        # can't hang on it.
        timeout = 60

        def log_message(self, *a):  # stderr noise off the request path
            pass

        def _send(self, code: int, obj: dict):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/stats":
                # Live registry snapshot (stats schema v1): the
                # counters/gauges/histogram summaries RIGHT NOW,
                # curl-able mid-run without waiting for the run-dir
                # flush. Answered even while draining.
                from nezha_tpu import obs
                payload = obs.stats_snapshot()
                payload["role"] = getattr(args, "role", "both")
                payload["tenants"] = scheduler.tenant_queue_depths()
                return self._send(200, payload)
            if self.path == "/windows":
                # Mergeable rolled-up window views (the router's fleet
                # /metrics scrapes these and merges the sketches).
                from nezha_tpu import obs
                return self._send(200, obs.windows_payload())
            if self.path == "/metrics":
                # Prometheus text exposition: cumulative totals plus
                # window-labeled rates/quantiles.
                from nezha_tpu import obs
                body = obs.render_prometheus(
                    obs.stats_snapshot(), obs.windows_payload()).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path != "/healthz":
                return self._send(404, {"error": "unknown path"})
            pool = scheduler.engine.pool
            if stop.is_set():
                status = "decode loop stopped"
            elif drain.is_set():
                # Draining flips healthz FIRST: load balancers stop
                # routing here while in-flight requests finish.
                status = "draining"
            else:
                status = "ok"
            payload = {
                "status": status,
                "active": pool.num_active,
                "capacity": pool.capacity,
                "queued": scheduler.queue_depth,
                "occupancy": pool.occupancy,
                "role": getattr(args, "role", "both"),
                "parked": scheduler.parked_count,
                # Per-tenant queue depths + suspended count (PR 19):
                # the router's autoscale signal reads "queued"; these
                # give operators the fairness view behind it.
                "tenants": scheduler.tenant_queue_depths(),
                "preempted": scheduler.preempted_count,
                # Host spill tier occupancy (0/0 when --kv-host-blocks
                # is off or the layout is dense): what the router's
                # replica table and operators size the tier against.
                "host_blocks": pool.host_blocks,
                "host_blocks_used": pool.host_blocks_used}
            if status == "ok":
                # Fleet digest piggyback (PR 17): the router's prober
                # is the digest transport — no extra endpoint.
                payload.update(scheduler.fleet_digest(
                    getattr(args, "digest_interval", 2.0),
                    getattr(args, "digest_max_entries", 256)))
            self._send(200 if status == "ok" else 503, payload)

        def do_POST(self):
            from nezha_tpu.serve import migrate
            if self.path in ("/kv_export", "/kv_ack"):
                # Migration endpoints (docs/RUNBOOK.md §10): the source
                # side of the pull and the two-phase ACK. Allowed
                # during drain — an in-flight migration finishing is
                # strictly better than its park being swept.
                n = int(self.headers.get("Content-Length", 0))
                return self._send(*migrate.dispatch_kv_endpoint(
                    scheduler, self.path, self.rfile.read(n)))
            if self.path != "/generate":
                return self._send(404, {"error": "unknown path"})
            if drain.is_set():   # admission is closed for good
                return self._send(503, {"error": "draining"})
            try:
                n = int(self.headers.get("Content-Length", 0))
                obj = json.loads(self.rfile.read(n))
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": str(e)})
            from nezha_tpu import obs
            obs.adopt_trace_header(self.headers, obj)
            if isinstance(obj, dict) and obj.get("resume"):
                return self._handle_resume(str(obj["resume"]))
            mig_meta = None
            fleet_meta = None
            pull = obj.get("pull_from") if isinstance(obj, dict) else None
            if isinstance(pull, dict) and "tokens" in pull \
                    and "request_id" not in pull:
                # Fleet peer pull (PR 17): fetch covering prefix
                # blocks from the sibling the router named, then fall
                # through to ordinary admission so the submit below
                # prefix-hits them. Failure DEGRADES to a cold prefill
                # — never an HTTP error; the pull is an optimization,
                # not a dependency.
                try:
                    fleet_meta = migrate.pull_prefix_into(scheduler,
                                                          pull)
                except migrate.MigrationError as e:
                    fleet_meta = {"bytes": 0, "blocks": 0,
                                  "installed": 0, "degraded": str(e),
                                  "error_type": e.kind}
            elif pull is not None:
                # Decode side of a migration: pull + install + ACK
                # BEFORE admission so the submit below prefix-hits the
                # installed blocks; failure is the typed 424 the router
                # retries on.
                try:
                    mig_meta = migrate.pull_into(scheduler, pull)
                except migrate.MigrationError as e:
                    return self._send(424, {
                        "error": str(e), "error_type": e.kind})
            try:
                req = _parse_request(obj, args, tokenizer, eos_id, vocab)
            except ValueError as e:
                return self._send(400, {"error": str(e)})
            if stop.is_set():
                return self._send(503, {"error": "decode loop stopped"})
            # Register the event BEFORE submit (the decode thread could
            # retire a short request between submit and a later
            # registration), and never hold events_lock across submit —
            # on_finish runs under the scheduler lock and takes
            # events_lock, so holding both here in the opposite order
            # would deadlock.
            import uuid
            rid = req.request_id or f"http-{uuid.uuid4().hex[:12]}"
            req.request_id = rid
            ev = threading.Event()
            with events_lock:
                if rid in events:
                    # A duplicate would overwrite the first waiter's
                    # event and strand it forever on ev.wait().
                    return self._send(409, {
                        "error": f"request id {rid!r} already in flight"})
                events[rid] = ev
            try:
                scheduler.submit(req)
            except QueueFull as e:
                with events_lock:
                    events.pop(rid, None)
                # Typed like every other client-visible failure: the
                # router sweeps past ANY replica 503, but a direct
                # client must be able to tell "this tenant is over ITS
                # cap" from "the whole queue is full".
                return self._send(503, {
                    "error": str(e),
                    "error_type": ("tenant_over_limit"
                                   if isinstance(e, TenantOverLimit)
                                   else "queue_full")})
            except ValueError as e:
                with events_lock:
                    events.pop(rid, None)
                return self._send(400, {"error": str(e)})
            if stop.is_set():
                # TOCTOU guard: the drain (or a decode-loop death)
                # completed between the admission check above — which
                # ran before this request's body finished uploading —
                # and the submit. Nobody will ever retire this request,
                # so answer 503 now instead of parking on ev.wait()
                # forever.
                with events_lock:
                    events.pop(rid, None)
                return self._send(503, {"error": "draining"})
            ev.wait()
            with events_lock:
                events.pop(rid, None)
            res = scheduler.results.pop(rid, None)
            if res is None:   # decode loop died before retiring us
                return self._send(500, {"error": "decode loop failed"})
            out = _result_obj(res, tokenizer)
            out.pop("event")
            if mig_meta is not None:
                out["migration"] = mig_meta
            if fleet_meta is not None:
                out["fleet_pull"] = fleet_meta
            self._send(200, out)

        def _handle_resume(self, rid: str):
            """Local-decode fallback: move a parked request into the
            live set and answer with its finished result (the
            ``role=both`` degradation)."""
            ev = threading.Event()
            with events_lock:
                if rid in events:
                    return self._send(409, {
                        "error": f"request id {rid!r} already in "
                                 f"flight"})
                events[rid] = ev
            if not scheduler.resume_parked(rid):
                with events_lock:
                    events.pop(rid, None)
                return self._send(404, {
                    "error": f"request {rid!r} is not parked here",
                    "error_type": "migration_failed"})
            if stop.is_set():
                with events_lock:
                    events.pop(rid, None)
                return self._send(503, {"error": "draining"})
            ev.wait()
            with events_lock:
                events.pop(rid, None)
            res = scheduler.results.pop(rid, None)
            if res is None:
                return self._send(500, {"error": "decode loop failed"})
            out = _result_obj(res, tokenizer)
            out.pop("event")
            out["resumed"] = True
            self._send(200, out)

    class Server(ThreadingHTTPServer):
        # Join handler threads on close instead of abandoning them as
        # daemons: a client whose in-flight POST was cancelled at the
        # drain deadline gets its final "deadline" response flushed
        # before the process exits, not a connection reset. The drain
        # sweeps release every parked handler first, and the per-
        # connection timeout above bounds stalled ones.
        daemon_threads = False

    server = Server(("127.0.0.1", port), Handler)

    def drain_watch():
        # Runs the drain off the signal handler: handlers must return
        # immediately, so they only set the event; this thread does the
        # waiting, the straggler cancellation (which releases every
        # parked POST via on_finish), and the server shutdown. With the
        # decode loop already dead there is nothing left to drain, but
        # the signal must STILL stop the server — shutdown() is a no-op
        # if serve_forever already exited.
        from nezha_tpu.serve import FinishReason

        def cancel_stragglers():
            # A request whose body upload straddled the drain can slip
            # past the admission check and submit late; retire it before
            # releasing events, so its handler finds a RESULT (deadline
            # on a healthy shutdown, error on a dead engine), not a
            # spurious 500.
            if engine_dead.is_set():
                scheduler.cancel_remaining(FinishReason.ERROR,
                                           error="decode loop died")
            else:
                scheduler.cancel_remaining()

        drain.wait()
        if not stop.is_set():
            # If the engine dies mid-drain the wait breaks immediately
            # (and the cancellations say "error") instead of idling out
            # the budget over work that can never finish; a server-exit
            # abort (the serve_forever finally) just cuts it short.
            _drain(scheduler, args.drain_timeout, drive=False,
                   dead=engine_dead, abort=stop)
            stop.set()
        cancel_stragglers()
        with events_lock:
            for ev in events.values():
                ev.set()
        server.shutdown()
        # Once more after shutdown: a handler registering later than
        # this sweep sees stop already set and answers 503 itself.
        cancel_stragglers()
        with events_lock:
            for ev in events.values():
                ev.set()

    threading.Thread(target=drain_watch, daemon=True).start()
    if ready_cb is not None:
        ready_cb(server)
    print(f"nezha-serve listening on http://127.0.0.1:"
          f"{server.server_address[1]} (POST /generate, GET /healthz)",
          file=sys.stderr)
    try:
        server.serve_forever(poll_interval=0.1)
    except KeyboardInterrupt:
        pass
    finally:
        stop.set()
        drain.set()    # unblock the watcher thread on non-signal exits
        server.server_close()
    return 0


def _start_watchdog(args):
    """Start the anomaly watchdog thread when ``--watchdog-interval``
    or ``--slo`` asks for one (an SLO implies the watchdog — something
    must evaluate it). Returns the started WatchdogThread or None.
    Spec errors exit with the offending ``--slo`` string."""
    from nezha_tpu import obs
    try:
        slos = obs.parse_slo_args(getattr(args, "slo", None))
    except ValueError as e:
        raise SystemExit(f"--slo: {e}")
    interval = float(getattr(args, "watchdog_interval", 0.0) or 0.0)
    if interval <= 0 and not slos:
        return None
    if interval <= 0:
        interval = 10.0
    wd = obs.Watchdog(slos=slos,
                      config=obs.WatchdogConfig(interval_s=interval))
    return obs.WatchdogThread(wd).start()


def run_worker(args, stdin=None, stdout=None, ready_cb=None,
               drain_event=None) -> int:
    """The single-replica stack — the classic ``--replicas 1`` entry
    AND the worker the supervisor spawns (``--replicas N`` workers run
    exactly this, one per port), so there is one code path to keep
    correct. The ``replica.exec`` fault point fires at entry: the
    crash-at-startup drill behind the supervisor's restart backoff."""
    import signal

    from nezha_tpu import faults
    from nezha_tpu.cli.common import setup_jax
    setup_jax(args)

    # Chaos drills: NEZHA_FAULT_PLAN installs a seeded fault plan for
    # this serve process (restored on exit so embedded callers — tests —
    # don't leak plans across runs; restoring an unchanged plan is a
    # no-op).
    prev_plan = faults.active()
    faults.install_from_env()
    from nezha_tpu.serve.supervisor import replica_exec_point
    try:
        replica_exec_point()
    except BaseException:     # crash-at-startup drill: die loudly, but
        faults.install(prev_plan)   # never leak the plan into embedders
        raise

    drain = drain_event if drain_event is not None else threading.Event()
    old_handlers = {}

    from nezha_tpu import obs
    try:
        obs.set_trace_sample(getattr(args, "trace_sample", 1.0))
    except ValueError as e:
        raise SystemExit(f"--trace-sample: {e}")
    # Watchdog first: a bad --slo spec must exit before a sink opens.
    # Its checks are harmless pre-run (telemetry still disabled).
    watchdog = _start_watchdog(args)
    sink = None
    if args.run_dir:
        sink = obs.start_run(args.run_dir, meta={
            "kind": "serve", "mode": "http" if args.http else "stdio"})
    try:
        scheduler, tokenizer, eos_id = _build_stack(args)
        # SIGTERM/SIGINT = graceful drain, not an exception mid-decode.
        # Installed only AFTER the stack is built: during the (possibly
        # minutes-long) weight load + compile there is nothing to drain,
        # and a wedged startup must stay killable with plain Ctrl-C.
        # The handler only sets the event; the front ends own the drain
        # itself. ``drain_event`` lets embedded callers trigger the same
        # path without a signal (run() off the main thread cannot
        # install handlers — the ValueError guard below).
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                old_handlers[sig] = signal.signal(
                    sig, lambda signum, frame: drain.set())
            except ValueError:
                break   # not the main thread of the main interpreter
        if args.http is not None:
            return run_http(scheduler, args, tokenizer, eos_id, args.http,
                            ready_cb=ready_cb, drain=drain)
        return run_stdio(scheduler, args, tokenizer, eos_id,
                         stdin=stdin, stdout=stdout, drain=drain)
    finally:
        if watchdog is not None:
            watchdog.stop()
        if sink is not None:
            from nezha_tpu import obs
            obs.end_run()
        for sig, handler in old_handlers.items():
            signal.signal(sig, handler)
        faults.install(prev_plan)


# ------------------------------------------------------- multi-replica
def _worker_argv(args, rid: int, port: int, role: Optional[str] = None
                 ) -> list:
    """The argv for one spawned worker process: the front end's own
    flags minus the router-only ones, plus the worker's port, its tier
    role (disaggregated topologies), and a per-replica run-dir
    subdirectory when telemetry is on."""
    argv = [sys.executable, "-m", "nezha_tpu.cli.serve",
            "--role", role or getattr(args, "role", "both")]
    if args.random_init:
        argv.append("--random-init")
    elif args.ckpt_dir:
        argv += ["--ckpt-dir", args.ckpt_dir]
    elif args.hf_dir:
        argv += ["--hf-dir", args.hf_dir]
    argv += ["--model-preset", args.model_preset,
             "--max-batch-size", str(args.max_batch_size),
             "--max-len", str(args.max_len),
             "--max-prefill-len", str(args.max_prefill_len),
             "--k-max", str(args.k_max),
             "--queue-capacity", str(args.queue_capacity),
             "--max-new-tokens", str(args.max_new_tokens),
             "--cache-dtype", args.cache_dtype,
             "--decode-horizon", str(args.decode_horizon),
             "--kv-layout", args.kv_layout,
             "--kv-block-size", str(args.kv_block_size),
             "--kv-dtype", args.kv_dtype,
             "--prefix-cache", args.prefix_cache,
             "--kv-eviction", args.kv_eviction,
             "--kv-host-blocks", str(args.kv_host_blocks),
             # Multi-tenant scheduling knobs (PR 19) ride into every
             # worker: admission, WFQ, and preemption are replica-side
             # (the router only routes; autoscale stays router-side).
             "--preemption", getattr(args, "preemption", "off"),
             "--preemption-budget",
             str(getattr(args, "preemption_budget", 2)),
             # Digest knobs ride into every worker: the /healthz
             # digest payload is built replica-side (PR 17).
             "--digest-interval",
             str(getattr(args, "digest_interval", 2.0)),
             "--digest-max-entries",
             str(getattr(args, "digest_max_entries", 256)),
             "--drain-timeout", str(args.drain_timeout),
             "--trace-sample", str(getattr(args, "trace_sample", 1.0)),
             "--watchdog-interval",
             str(getattr(args, "watchdog_interval", 0.0) or 0.0),
             "--seed", str(args.seed),
             "--mesh", str(getattr(args, "mesh", 1) or 1),
             # Long-context prefill knobs ride into every worker: the
             # router is chunk-blind — sequence sharding happens on
             # each worker's own mesh (PR 20).
             "--prefill-mode",
             getattr(args, "prefill_mode", "replicated"),
             "--seq-prefill-variant",
             getattr(args, "seq_prefill_variant", "auto"),
             "--http", str(port)]
    # SLOs ride into every worker: each process-backend replica
    # evaluates them against its own registry and streams typed events
    # to its replica run-dir (the router evaluates the fleet's).
    for spec in getattr(args, "slo", None) or []:
        argv += ["--slo", str(spec)]
    if args.kv_num_blocks is not None:
        argv += ["--kv-num-blocks", str(args.kv_num_blocks)]
    if getattr(args, "priority_weights", None):
        argv += ["--priority-weights", str(args.priority_weights)]
    if getattr(args, "tenant_queue_cap", None) is not None:
        argv += ["--tenant-queue-cap", str(args.tenant_queue_cap)]
    if getattr(args, "speculative", False):
        # Speculation rides into every worker: the router is
        # draft-blind (accept/verify is engine-internal).
        argv += ["--speculative", "--draft-k", str(args.draft_k)]
        if args.draft_layers is not None:
            argv += ["--draft-layers", str(args.draft_layers)]
        if getattr(args, "draft_ckpt_dir", None):
            argv += ["--draft-ckpt-dir", args.draft_ckpt_dir]
        if getattr(args, "draft_hf_dir", None):
            argv += ["--draft-hf-dir", args.draft_hf_dir]
    if args.tokenizer:
        argv += ["--tokenizer", args.tokenizer]
    if args.prefill_buckets:
        argv += ["--prefill-buckets", str(args.prefill_buckets)]
    if getattr(args, "long_prefill_buckets", None):
        argv += ["--long-prefill-buckets",
                 str(args.long_prefill_buckets)]
    if args.decode_impl:
        argv += ["--decode-impl", args.decode_impl]
    if args.prefill_impl:
        argv += ["--prefill-impl", args.prefill_impl]
    if args.eos_id is not None:
        argv += ["--eos-id", str(args.eos_id)]
    if args.platform:
        argv += ["--platform", args.platform]
    if args.run_dir:
        import os
        argv += ["--run-dir", os.path.join(args.run_dir,
                                           f"replica{rid}")]
    return argv


def run_multi(args, ready_cb=None, drain_event=None) -> int:
    """The ``--replicas N`` front end: supervisor spawns N workers,
    router serves HTTP over them, SIGTERM/SIGINT rolls the drain
    through the replicas one at a time. This process never initializes
    a jax backend or compiles a program — the workers own the engines
    (the parent package import itself is still paid once at CLI
    startup). With ``--replica-backend thread`` the workers share this
    process instead, trading OS isolation for spawn cost
    (tests/benchmarks)."""
    import copy
    import signal

    from nezha_tpu import faults
    from nezha_tpu.serve.router import Router, run_front_end
    from nezha_tpu.serve.supervisor import (ProcessBackend, RouterConfig,
                                            Supervisor, ThreadBackend)
    if args.http is None:
        raise SystemExit("--replicas N > 1 (or --prefill-replicas/"
                         "--decode-replicas) requires --http PORT "
                         "(the router is an HTTP front end)")
    prev_plan = faults.active()
    faults.install_from_env()

    roles: tuple = ()
    total = args.replicas
    if args.prefill_replicas or args.decode_replicas:
        # Disaggregated tiers: N prefill workers + M decode workers;
        # admissions land on the prefill tier and finished prompts'
        # KV migrates to the decode tier (RUNBOOK §10).
        if args.prefill_replicas < 1 or args.decode_replicas < 1:
            raise SystemExit("--prefill-replicas and --decode-replicas "
                             "must both be >= 1 for a disaggregated "
                             "front end")
        roles = (("prefill",) * args.prefill_replicas
                 + ("decode",) * args.decode_replicas)
        total = len(roles)

    def role_of(rid: int) -> str:
        return roles[rid] if roles else args.role

    # Affinity routing defaults ON for a genuine multi-replica fleet
    # (that is where cross-replica reuse exists to win) and OFF for a
    # single replica, unless the flag pins it either way.
    affinity = getattr(args, "affinity_routing", None) \
        or ("on" if total > 1 else "off")
    cfg = RouterConfig(
        replicas=total, roles=roles,
        probe_interval_s=args.probe_interval,
        probe_misses=args.probe_misses,
        route_retries=args.route_retries,
        restart_backoff_base_s=args.restart_backoff,
        max_restart_failures=args.max_restart_failures,
        drain_timeout_s=args.drain_timeout,
        seed=args.seed,
        affinity_routing=(affinity == "on"),
        digest_interval_s=getattr(args, "digest_interval", 2.0),
        digest_max_entries=getattr(args, "digest_max_entries", 256),
        autoscale_min=getattr(args, "autoscale_min", None),
        autoscale_max=getattr(args, "autoscale_max", None))
    from nezha_tpu import obs
    try:
        # The router is the trace-minting edge: the sample knob lives
        # here (workers inherit it via argv passthrough so a replica
        # minting for a direct request agrees with the router).
        obs.set_trace_sample(getattr(args, "trace_sample", 1.0))
    except ValueError as e:
        raise SystemExit(f"--trace-sample: {e}")
    # The fleet-level watchdog: sees the router registry (replica-flap
    # rule) — and, in thread mode, the shared registry every member
    # writes, so the per-replica rules cover the whole fleet too.
    # Started first so a bad --slo spec exits before a sink opens.
    watchdog = _start_watchdog(args)
    sink = None
    if args.run_dir:
        from nezha_tpu.serve.router import register_router_instruments
        sink = obs.start_run(args.run_dir, meta={
            "kind": "serve_router", "replicas": total,
            "roles": ",".join(roles) if roles else "both",
            "backend": args.replica_backend})
        register_router_instruments()
    if args.replica_backend == "thread":
        wargs = copy.copy(args)
        wargs.replicas, wargs.http, wargs.run_dir = 1, None, None
        wargs.prefill_replicas = wargs.decode_replicas = 0
        backend = ThreadBackend(wargs,
                                drain_timeout_s=args.drain_timeout,
                                roles=roles)
    else:
        import os
        backend = ProcessBackend(
            lambda rid, port: _worker_argv(args, rid, port,
                                           role_of(rid)),
            log_dir=(os.path.join(args.run_dir, "logs")
                     if args.run_dir else None))
    sup = Supervisor(backend, cfg)
    router = Router(sup, cfg)
    drain = drain_event if drain_event is not None else threading.Event()
    old_handlers = {}
    try:
        sup.start()
        router.start()
        # Same contract as the worker: handlers only set the event; the
        # front end owns the rolling drain. Installed after the
        # supervisor is up so a wedged spawn stays Ctrl-C-able.
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                old_handlers[sig] = signal.signal(
                    sig, lambda signum, frame: drain.set())
            except ValueError:
                break   # not the main thread of the main interpreter
        return run_front_end(router, sup, args.http, ready_cb=ready_cb,
                             drain=drain,
                             drain_timeout_s=args.drain_timeout)
    finally:
        router.stop()
        sup.shutdown()
        if watchdog is not None:
            watchdog.stop()
        if sink is not None:
            from nezha_tpu import obs
            obs.end_run()
        for sig, handler in old_handlers.items():
            signal.signal(sig, handler)
        faults.install(prev_plan)


def run(args, stdin=None, stdout=None, ready_cb=None,
        drain_event=None) -> int:
    if (getattr(args, "replicas", 1) > 1
            or getattr(args, "autoscale_min", None) is not None
            or getattr(args, "autoscale_max", None) is not None
            or getattr(args, "prefill_replicas", 0)
            or getattr(args, "decode_replicas", 0)):
        # Autoscale bounds force router mode even at --replicas 1: an
        # elastic fleet that STARTS at one replica still needs the
        # supervisor/router pair to grow past it.
        return run_multi(args, ready_cb=ready_cb,
                         drain_event=drain_event)
    return run_worker(args, stdin=stdin, stdout=stdout,
                      ready_cb=ready_cb, drain_event=drain_event)


def main(argv=None) -> int:
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
