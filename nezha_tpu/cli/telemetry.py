"""``nezha-telemetry``: render the report for a ``--run-dir`` telemetry
capture.

    nezha-train --config mlp_mnist --steps 100 --run-dir /tmp/run
    python -m nezha_tpu.cli.telemetry /tmp/run

Reads the artifacts the run sink wrote (``metrics.jsonl``,
``spans.jsonl``, ``summary.json`` — a crashed run may have only the
streams) and prints step-rate percentiles, per-chip throughput, the
per-collective payload/bandwidth table, compile-cache hit ratio, and the
slowest spans. ``--json`` dumps the raw summary instead, for scripting.

``--trace`` switches to the DISTRIBUTED-TRACE view: walk this run dir
plus the per-replica subdirectories a ``--replicas`` serve run writes,
stitch every replica's span fragments by trace id, and render the
per-request timelines — the TTFT decomposition (router queue ->
prefill wait -> prefill compute -> migration transfer -> decode wait ->
first token) and the slowest-requests table with critical-path
attribution (docs/RUNBOOK.md "Tracing a slow request").

``--slo`` renders the SLO/watchdog view instead: per-SLO compliance
and error-budget burn recomputed from the typed ``events.jsonl``
records (``slo.eval``), plus the watchdog event log — the offline twin
of the live ``/metrics`` + events stream (docs/RUNBOOK.md
"Monitoring & SLOs").
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nezha-telemetry",
        description="Render the telemetry report for a nezha-train "
                    "--run-dir capture.")
    p.add_argument("run_dir", help="run directory (holds metrics.jsonl / "
                                   "spans.jsonl / summary.json)")
    p.add_argument("--json", action="store_true",
                   help="print the raw summary.json (recomputed from the "
                        "streams when the file is missing) instead of the "
                        "rendered report; with --trace, the stitched "
                        "timelines as JSON")
    p.add_argument("--trace", action="store_true",
                   help="stitch the run's distributed trace fragments "
                        "(this dir + per-replica subdirs) into "
                        "per-request timelines and render the TTFT "
                        "decomposition + slowest-requests table instead "
                        "of the metrics report")
    p.add_argument("--slo", action="store_true",
                   help="render the SLO/watchdog view from the run's "
                        "events.jsonl (this dir + per-replica subdirs): "
                        "per-SLO compliance and error-budget burn rate, "
                        "plus the watchdog event log; with --json, the "
                        "raw rows")
    p.add_argument("--check", action="store_true",
                   help="also validate the artifacts against the frozen "
                        "telemetry schema (exit 1 on drift)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"no such run directory: {args.run_dir}", file=sys.stderr)
        return 2
    # Deferred so `--help` stays instant (repo convention for CLI entries).
    from nezha_tpu.obs.report import (load_fleet_events, load_run,
                                      render_report, render_slo_report,
                                      render_trace_report, slo_rows,
                                      stitch_run_dir, summarize_streams)

    if args.slo:
        if args.json:
            events = load_fleet_events(args.run_dir)
            print(json.dumps({"slos": slo_rows(events),
                              "events": events},
                             indent=2, sort_keys=True))
        else:
            print(render_slo_report(args.run_dir))
    elif args.trace:
        # The fleet view: walk this dir plus the per-replica subdirs a
        # --replicas run writes, stitch fragments by trace id, render
        # per-request timelines (docs/RUNBOOK.md "Tracing a slow
        # request").
        if args.json:
            print(json.dumps(stitch_run_dir(args.run_dir), indent=2,
                             sort_keys=True))
        else:
            print(render_trace_report(args.run_dir))
    elif args.json:
        run = load_run(args.run_dir)
        summary = run["summary"]
        if summary is None:
            summary = summarize_streams(run["metrics"], run["spans"])
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print(render_report(args.run_dir))
    if args.check:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        sys.path.insert(0, os.path.join(root, "tools"))
        try:
            from check_telemetry_schema import check_run_dir
        except ImportError:
            print("schema checker (tools/check_telemetry_schema.py) not "
                  "found; skipping --check", file=sys.stderr)
            return 0
        errors = check_run_dir(args.run_dir)
        if errors:
            for e in errors:
                print(f"schema: {e}", file=sys.stderr)
            return 1
        print("schema: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
