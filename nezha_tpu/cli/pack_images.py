"""``nezha-pack-images``: real images -> NZR1 records for `nezha-train`.

The dataset-prep half of the image input path (SURVEY.md §2 data loaders;
benchmark config 2): decode/resize once here, then the C++ record loader
(csrc/dataloader.cpp) streams the fixed-size records with train-time
augmentation. Usage::

    nezha-pack-images /data/imagenet --out-dir /data/imagenet-nzr \
        --size 256
    nezha-train --config resnet50_imagenet \
        --data-dir /data/imagenet-nzr --crop 224 --eval

Accepts ``src/train/<class>/`` + ``src/val/<class>/`` (packed as-is) or
flat ``src/<class>/`` (seeded stratified val split, ``--val-fraction``).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nezha-pack-images",
        description="Pack an ImageFolder-style directory into NZR1 records "
                    "(train.nzr / val.nzr / classes.txt) for nezha-train "
                    "--data-dir.")
    p.add_argument("src", help="dataset root: train/<class>/ + val/<class>/ "
                               "subdirs, or flat <class>/ subdirs")
    p.add_argument("--out-dir", required=True,
                   help="output directory for train.nzr/val.nzr/classes.txt")
    p.add_argument("--size", type=int, default=256,
                   help="stored record size: short-side resize + center crop "
                        "to SIZE x SIZE (default 256; train with --crop 224)")
    p.add_argument("--val-fraction", type=float, default=0.1,
                   help="val split per class when src has no train/+val/ "
                        "layout (default 0.1; 0 disables)")
    p.add_argument("--seed", type=int, default=0,
                   help="seed for the stratified val split")
    p.add_argument("--workers", type=int, default=8,
                   help="decode threads (default 8)")
    return p


def run(args) -> dict:
    from nezha_tpu.data.images import pack_image_folder

    if args.size <= 0:
        raise SystemExit(f"--size must be positive, got {args.size}")
    if not 0 <= args.val_fraction < 1:
        raise SystemExit(f"--val-fraction must be in [0, 1), got "
                         f"{args.val_fraction}")
    try:
        summary = pack_image_folder(args.src, args.out_dir, size=args.size,
                                    val_fraction=args.val_fraction,
                                    seed=args.seed, workers=args.workers)
    except (ValueError, OSError) as e:
        raise SystemExit(f"nezha-pack-images: {e}")
    print(f"packed {summary['num_train']} train + {summary['num_val']} val "
          f"records ({summary['num_classes']} classes, "
          f"{summary['size']}x{summary['size']}) -> {args.out_dir}",
          file=sys.stderr)
    return summary


def main() -> None:
    run(build_parser().parse_args())


if __name__ == "__main__":
    main()
