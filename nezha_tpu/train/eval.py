"""Evaluation: jit'd metric accumulation over a batch stream.

The training counterpart lives in ``train.loop``; this is the read-only
side — one compiled eval step, metrics accumulated on device (sums, not
per-batch host fetches), a single host transfer at the end. Sharded
evaluation works the same way: pass batches already placed with a mesh
sharding and jit partitions the step like any other program.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, Optional

import jax
import jax.numpy as jnp

from nezha_tpu.nn.module import Module


def accuracy(logits, batch) -> Dict[str, jax.Array]:
    """Top-1 accuracy against ``batch["label"]``. Returns sum + count."""
    pred = jnp.argmax(logits, axis=-1)
    correct = (pred == batch["label"]).sum()
    return {"correct": correct, "count": jnp.asarray(pred.size)}


def lm_token_stats(out, batch) -> Dict[str, jax.Array]:
    """Next-token NLL sums over {"tokens": [B, S+1]} — yields perplexity.

    ``out``: dense logits, or the fused-head {"hidden", "wte"} dict (see
    ``GPT2Config.fused_loss_chunk``)."""
    targets = batch["tokens"][:, 1:]
    if isinstance(out, dict):
        if "logits" in out:  # MoE logits dict: NLL only, no aux in eval
            out = out["logits"]
        else:
            from nezha_tpu.ops.losses import lm_ce_from_fused
            mean_nll = lm_ce_from_fused(out, targets)
            return {"nll_sum": mean_nll * targets.size,
                    "count": jnp.asarray(targets.size)}
    logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return {"nll_sum": nll.sum(), "count": jnp.asarray(targets.size)}


def make_eval_step(model: Module, stat_fn: Callable):
    """Build ``step(variables, batch, acc) -> acc`` accumulating sums."""

    def widen(v):
        v = jnp.asarray(v)
        if jnp.issubdtype(v.dtype, jnp.floating):
            return v.astype(jnp.float32)
        return v.astype(jnp.int32)

    def step(variables, batch, acc):
        out, _ = model.apply(variables, batch, training=False)
        stats = {k: widen(v) for k, v in stat_fn(out, batch).items()}
        if acc is None:
            return stats
        return {k: acc[k] + stats[k] for k in stats}

    return jax.jit(step)


def evaluate(model: Module, variables: dict, batches: Iterator[dict],
             stat_fn: Callable = accuracy,
             max_batches: Optional[int] = None,
             step: Optional[Callable] = None) -> Dict[str, float]:
    """Run the model over ``batches`` and reduce the accumulated stats.

    Returns the raw sums plus derived metrics: ``accuracy`` when the
    stat_fn produced correct/count, ``perplexity`` for nll_sum/count.
    ``step``: a prebuilt ``make_eval_step`` — pass it when evaluating
    repeatedly (periodic eval) so jit's cache is hit instead of retracing
    a fresh closure every pass.
    """
    if step is None:
        step = make_eval_step(model, stat_fn)
    acc = None
    n = 0
    for batch in batches:
        if max_batches is not None and n >= max_batches:
            break
        acc = step(variables, batch, acc)
        n += 1
    if acc is None:
        raise ValueError("no batches to evaluate")
    out = {k: float(v) for k, v in acc.items()}
    if "correct" in out and out.get("count"):
        out["accuracy"] = out["correct"] / out["count"]
    if "nll_sum" in out and out.get("count"):
        import math
        out["perplexity"] = math.exp(out["nll_sum"] / out["count"])
    out["batches"] = n
    return out


def mlm_token_stats(out, batch) -> Dict[str, jax.Array]:
    """Masked-LM NLL sums over the predicted positions (labels != -100) —
    yields masked perplexity. ``out``: dense logits (the eval-mode BERT
    path; the fused head is training-only) or the fused-head dict."""
    labels = batch["labels"]
    valid = labels != -100
    count = valid.sum()
    if isinstance(out, dict) and "logits" not in out:
        from nezha_tpu.ops.losses import lm_ce_from_fused
        mean_nll = lm_ce_from_fused(out, labels, ignore_index=-100)
        return {"nll_sum": mean_nll * count, "count": count}
    if isinstance(out, dict):
        out = out["logits"]
    logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
    safe = jnp.where(valid, labels, 0)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return {"nll_sum": jnp.where(valid, nll, 0.0).sum(), "count": count}
