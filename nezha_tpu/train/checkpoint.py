"""Checkpoint save/restore + resume.

Flat-key ``.npz`` snapshots of the full TrainState (params, optimizer state,
BatchNorm stats, RNG) with atomic rename, plus ``try_restore`` for
crash-resume (aux subsystem per the build brief; the reference's equivalent
was not observable — SURVEY.md §5). Format is plain numpy so checkpoints are
portable and inspectable without the framework.
"""

from __future__ import annotations

import os
import re
import tempfile
from pathlib import Path
from typing import Any, Optional, Tuple

import jax
import numpy as np


def _flatten(tree: Any) -> dict:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(_path_str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _unflatten(template: Any, flat: dict) -> Any:
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_path:
        key = "/".join(_path_str(p) for p in path)
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        val = flat[key]
        if hasattr(leaf, "dtype") and val.dtype != leaf.dtype:
            val = val.astype(leaf.dtype)
        new_leaves.append(val)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def save_checkpoint(ckpt_dir: str, state: Any, step: int,
                    keep_last: Optional[int] = None) -> str:
    """Atomically write ``step_<N>.npz``; returns the path.

    ``keep_last=N`` prunes all but the N newest checkpoints AFTER the new
    one is durably in place (a failed save never costs an old checkpoint).
    """
    d = Path(ckpt_dir)
    d.mkdir(parents=True, exist_ok=True)
    flat = _flatten(jax.device_get(state))
    final = d / f"step_{step:08d}.npz"
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **flat)
        os.replace(tmp, final)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    if keep_last is not None and keep_last > 0:
        prune_old_checkpoints(ckpt_dir, keep_last)
    return str(final)


def prune_old_checkpoints(ckpt_dir: str, keep_last: int) -> None:
    """Delete all but the ``keep_last`` newest ``step_*.npz`` files.
    Concurrent pruners (multi-host) race benignly: a loser's missing path
    is ignored. (Sharded checkpoints have their own pruner with
    completeness checks — sharded_checkpoint.prune_old_sharded.)"""
    d = Path(ckpt_dir)
    entries = sorted(p for p in d.glob("step_*.npz")
                     if re.match(r"step_\d+\.npz$", p.name))
    for p in entries[:-keep_last]:
        try:
            p.unlink()
        except FileNotFoundError:
            pass


def latest_step(ckpt_dir: str) -> Optional[int]:
    d = Path(ckpt_dir)
    if not d.exists():
        return None
    steps = [int(m.group(1)) for p in d.glob("step_*.npz")
             if (m := re.match(r"step_(\d+)\.npz$", p.name))]
    return max(steps) if steps else None


def restore_checkpoint(ckpt_dir: str, template: Any, step: Optional[int] = None) -> Tuple[Any, int]:
    """Restore into the structure of ``template`` (a freshly-init'd state)."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    path = Path(ckpt_dir) / f"step_{step:08d}.npz"
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    return _unflatten(template, flat), step


def try_restore(ckpt_dir: str, template: Any) -> Tuple[Optional[Any], int]:
    step = latest_step(ckpt_dir)
    if step is None:
        return None, 0
    state, step = restore_checkpoint(ckpt_dir, template, step)
    return state, step
