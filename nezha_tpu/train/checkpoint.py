"""Checkpoint save/restore + resume — durable and verified.

Flat-key ``.npz`` snapshots of the full TrainState (params, optimizer
state, BatchNorm stats, RNG) in plain numpy, portable and inspectable
without the framework. The durability contract (hardened for the
robustness leg, PR 4):

- **save** writes the npz — including a per-leaf CRC32 manifest
  EMBEDDED as a ``__manifest__`` entry, so data and checksums publish
  in one atomic rename with no sidecar-pairing window — to a temp file,
  fsyncs the FILE, ``os.replace``s it into place, and fsyncs the
  DIRECTORY, so "atomically write" holds across power loss, not just
  process crash (neither fsync happened before).
- **restore** verifies integrity under a ``checkpoint.verify`` span:
  the npz must unzip cleanly and, when it carries a manifest, hold
  exactly the manifested leaves with matching CRC32s. Corruption raises
  the typed :class:`CheckpointCorrupt` instead of whatever zipfile
  error a torn write happens to produce.
- **try_restore** walks steps newest -> oldest and resumes from the
  newest INTACT checkpoint: a torn/truncated file or stray ``.tmp`` at
  the head (the kill-during-save signature) costs one step of progress,
  never the run. Rejected steps count into ``checkpoint.corrupt_total``.

(The per-shard format has its own path — train/sharded_checkpoint.py.)
"""

from __future__ import annotations

import json
import logging
import os
import re
import tempfile
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from nezha_tpu import faults, obs

MANIFEST_VERSION = 1
MANIFEST_KEY = "__manifest__"   # reserved npz entry holding the JSON
                                # CRC32 manifest — never a state leaf

_log = logging.getLogger("nezha_tpu.checkpoint")


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed integrity verification: torn zip, truncated
    leaf, manifest/leaf-set mismatch, or CRC32 mismatch."""


def _flatten(tree: Any) -> dict:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(_path_str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _unflatten(template: Any, flat: dict) -> Any:
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_path:
        key = "/".join(_path_str(p) for p in path)
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        val = flat[key]
        if hasattr(leaf, "dtype") and val.dtype != leaf.dtype:
            val = val.astype(leaf.dtype)
        new_leaves.append(val)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _leaf_crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _fsync_dir(path: Path) -> None:
    """Make a rename durable: fsync the containing directory so the new
    directory entry itself survives power loss."""
    fd = os.open(str(path), os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save_checkpoint(ckpt_dir: str, state: Any, step: int,
                    keep_last: Optional[int] = None) -> str:
    """Durably and atomically write ``step_<N>.npz``; returns the path.

    The per-leaf CRC32 manifest travels INSIDE the npz (the
    ``__manifest__`` entry), so checksums and data publish in one
    atomic rename — there is no state where a reader can pair one
    step's data with another save's manifest. Publication order: npz
    bytes (leaves + manifest) -> file fsync -> rename -> directory
    fsync; a crash at any point leaves at worst a stray ``*.tmp``,
    which restore ignores.

    ``keep_last=N`` prunes all but the N newest checkpoints AFTER the new
    one is durably in place (a failed save never costs an old checkpoint).
    """
    d = Path(ckpt_dir)
    d.mkdir(parents=True, exist_ok=True)
    flat = _flatten(jax.device_get(state))
    if MANIFEST_KEY in flat:
        raise ValueError(
            f"state tree contains a leaf named {MANIFEST_KEY!r} — that "
            f"key is reserved for the checkpoint integrity manifest")
    final = d / f"step_{step:08d}.npz"
    manifest = json.dumps({
        "manifest_version": MANIFEST_VERSION,
        "step": int(step),
        "leaves": {k: {"crc32": _leaf_crc(v), "shape": list(v.shape),
                       "dtype": str(v.dtype)}
                   for k, v in flat.items()},
    })
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **flat, **{MANIFEST_KEY: np.asarray(manifest)})
            f.flush()
            os.fsync(f.fileno())
        faults.point("checkpoint.save")
        os.replace(tmp, final)
        _fsync_dir(d)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    if keep_last is not None and keep_last > 0:
        prune_old_checkpoints(ckpt_dir, keep_last)
    return str(final)


def prune_old_checkpoints(ckpt_dir: str, keep_last: int) -> None:
    """Delete all but the ``keep_last`` newest ``step_*.npz`` files.
    Concurrent pruners (multi-host) race benignly: a loser's missing
    path is ignored. (Sharded checkpoints have their own pruner with
    completeness checks — sharded_checkpoint.prune_old_sharded.)"""
    d = Path(ckpt_dir)
    entries = sorted(p for p in d.glob("step_*.npz")
                     if re.match(r"step_\d+\.npz$", p.name))
    for p in entries[:-keep_last]:
        try:
            p.unlink()
        except FileNotFoundError:
            pass


def checkpoint_steps(ckpt_dir: str) -> List[int]:
    """All on-disk step numbers, ascending (no integrity claim — a
    listed step may still fail verification at restore)."""
    d = Path(ckpt_dir)
    if not d.exists():
        return []
    return sorted(int(m.group(1)) for p in d.glob("step_*.npz")
                  if (m := re.match(r"step_(\d+)\.npz$", p.name)))


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = checkpoint_steps(ckpt_dir)
    return steps[-1] if steps else None


def verify_checkpoint(ckpt_dir: str, step: int) -> Dict[str, np.ndarray]:
    """Load + integrity-check one checkpoint; returns the flat
    ``{key: array}`` dict (manifest entry stripped). Raises
    :class:`CheckpointCorrupt` when the npz is torn or disagrees with
    its embedded manifest, ``FileNotFoundError`` when the step doesn't
    exist. Manifest-less checkpoints (pre-manifest saves) pass on a
    clean unzip alone."""
    path = Path(ckpt_dir) / f"step_{step:08d}.npz"
    if not path.exists():
        raise FileNotFoundError(f"no checkpoint for step {step} in "
                                f"{ckpt_dir}")
    with obs.span("checkpoint.verify", step=step):
        try:
            with np.load(path) as z:
                flat = {k: z[k] for k in z.files}
        except Exception as e:  # torn zip / truncated entry / bad header
            raise CheckpointCorrupt(
                f"{path.name}: unreadable "
                f"({type(e).__name__}: {e})") from e
        if MANIFEST_KEY not in flat:
            return flat
        try:
            leaves = json.loads(str(flat.pop(MANIFEST_KEY)))["leaves"]
        except Exception as e:
            raise CheckpointCorrupt(
                f"{path.name}: unreadable embedded manifest "
                f"({type(e).__name__}: {e})") from e
        missing = set(leaves) - set(flat)
        extra = set(flat) - set(leaves)
        if missing or extra:
            raise CheckpointCorrupt(
                f"{path.name}: leaf set disagrees with manifest "
                f"(missing {sorted(missing)}, extra {sorted(extra)})")
        for key, meta in leaves.items():
            if _leaf_crc(flat[key]) != meta["crc32"]:
                raise CheckpointCorrupt(
                    f"{path.name}: CRC32 mismatch for leaf {key!r}")
        return flat


def restore_checkpoint(ckpt_dir: str, template: Any, step: Optional[int] = None) -> Tuple[Any, int]:
    """Restore into the structure of ``template`` (a freshly-init'd
    state), verifying integrity first (:func:`verify_checkpoint`)."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    return _unflatten(template, verify_checkpoint(ckpt_dir, step)), step


def try_restore(ckpt_dir: str, template: Any) -> Tuple[Optional[Any], int]:
    """Crash-resume entry: the newest INTACT checkpoint, or ``(None, 0)``
    when none verifies. A corrupt head (torn write from a mid-save kill)
    falls back to the previous step instead of raising — each rejected
    step is logged and counted (``checkpoint.corrupt_total``)."""
    for step in reversed(checkpoint_steps(ckpt_dir)):
        try:
            return (_unflatten(template, verify_checkpoint(ckpt_dir, step)),
                    step)
        except CheckpointCorrupt as e:
            obs.counter("checkpoint.corrupt_total").inc()
            _log.warning("skipping corrupt checkpoint at step %d: %s",
                         step, e)
        except FileNotFoundError:
            # A concurrent pruner (multi-host) deleted it between the
            # listing and the open — not corruption, just keep walking.
            _log.warning("checkpoint for step %d vanished (concurrent "
                         "prune?); falling back", step)
    return None, 0
