"""Sharding-aware checkpointing: save per-shard, restore per-shard.

``train.checkpoint`` snapshots via ``jax.device_get(state)`` — a full gather
of every array to one host. Fine at toy scale; wrong for sharded state (the
whole point of ZeRO-1/GSPMD is that no host ever holds the full optimizer
state). This module writes each *addressable shard* separately and restores
through ``jax.make_array_from_callback`` against the template's live
sharding, so data moves host<->device per-shard and the full array is never
materialized on any single host.

Layout of ``step_<N>.sharded/``:

- ``shards_p<proc>.npz``  — this process's shard data (replica 0 only)
- ``meta_p<proc>.json``   — shard key -> leaf path, global index, shape/dtype
- ``COMPLETE_p<proc>``    — commit marker (written last; a dir without all
  markers it names is a torn save and is ignored by ``latest_step``)

Restore tolerates a *different* sharding layout than the save: the callback
assembles each requested slice from every stored shard that overlaps it, so
a ZeRO-1 dp=8 save restores onto dp=4, a GSPMD save onto a different mesh,
or either onto a single device (at the cost of materializing whatever the
target layout asks for — no more).

Multi-host note: processes see each other's files via a shared filesystem
(the standard TPU-pod setup); each process writes only its own shards and
replica-0 copies, so the bytes on disk are exactly one copy of the state.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import warnings
from pathlib import Path
from typing import Any, Callable, List, Optional, Tuple

import jax
import numpy as np

from .checkpoint import _fsync_dir, _path_str


def _leaf_key(path) -> str:
    return "/".join(_path_str(p) for p in path)


def _norm_index(index, shape) -> List[List[int]]:
    """Normalize a shard index (tuple of slices) to [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        assert step == 1, "strided shard indices are not produced by jax"
        out.append([start, stop])
    return out


def save_sharded(ckpt_dir: str, state: Any, step: int,
                 keep_last: Optional[int] = None) -> str:
    """Write this process's shards of ``state`` under ``step_<N>.sharded``.

    ``keep_last=N`` prunes all but the N newest FULLY-COMPLETE checkpoints
    afterwards (torn dirs and the one just written are never counted or
    touched by the count — a crash mid-save can't cost the fallback)."""
    host_state = jax.tree_util.tree_map(_host_shards, state)
    out = _write_prefetched(ckpt_dir, host_state, step)
    if keep_last is not None and keep_last > 0:
        prune_old_sharded(ckpt_dir, keep_last)
    return out


def _is_complete(d: Path) -> bool:
    try:
        metas = list(d.glob("meta_p*.json"))
        if not metas:
            return False
        world = json.loads(metas[0].read_text()).get("world", 1)
        return all((d / f"COMPLETE_p{i}").exists() for i in range(world))
    except (OSError, ValueError):
        # A concurrent pruner may delete the dir between glob and read —
        # treat vanished/torn as not-complete, never raise from cleanup.
        return False


def prune_old_sharded(ckpt_dir: str, keep_last: int) -> None:
    """Delete all but the ``keep_last`` newest fully-complete sharded
    checkpoints. Best-effort cleanup: concurrent pruners (every rank after
    its own save) race benignly, and NO failure here may escape — a
    durably-written checkpoint must never be reported failed over a
    cleanup hiccup."""
    import shutil

    try:
        d = Path(ckpt_dir)
        complete = sorted(p for p in d.glob("step_*.sharded")
                          if re.match(r"step_\d+\.sharded$", p.name)
                          and _is_complete(p))
        for p in complete[:-keep_last]:
            shutil.rmtree(p, ignore_errors=True)
    except OSError as e:  # pragma: no cover - depends on races/filesystems
        warnings.warn(f"checkpoint retention pruning failed (save itself "
                      f"succeeded): {e}")


def latest_step(ckpt_dir: str) -> Optional[int]:
    d = Path(ckpt_dir)
    if not d.exists():
        return None
    steps, torn = [], []
    for p in d.glob("step_*.sharded"):
        m = re.match(r"step_(\d+)\.sharded$", p.name)
        if not m:
            continue
        metas = list(p.glob("meta_p*.json"))
        if not metas:
            continue
        world = json.loads(metas[0].read_text()).get("world", 1)
        if all((p / f"COMPLETE_p{i}").exists() for i in range(world)):
            steps.append(int(m.group(1)))
        else:
            torn.append((int(m.group(1)), p))
    chosen = max(steps) if steps else None
    # Loud only when it matters: a torn save NEWER than the chosen step
    # (crash mid-write, or a failure-path rescue whose dead rank never
    # committed — unusable by construction; we fall back to the last
    # complete cadence save). Older torn dirs were already reported once.
    for step, p in torn:
        if chosen is None or step > chosen:
            import warnings
            warnings.warn(f"ignoring torn sharded checkpoint {p} "
                          f"(missing COMPLETE markers)")
    return chosen


def _byte_view(a: np.ndarray) -> np.ndarray:
    """np.savez stores extension dtypes (bfloat16 etc., kind 'V') as raw
    void and load-side casts then fail — store a uint view instead."""
    if a.dtype.kind == "V":
        return a.view(np.dtype(f"u{a.dtype.itemsize}"))
    return a


def _unview(a: np.ndarray, dtype: np.dtype) -> np.ndarray:
    if dtype.kind == "V" and a.dtype.kind != "V":
        return a.view(dtype)
    return a


class _ShardStore:
    """All stored shards of one checkpoint, loaded lazily from the npz files."""

    def __init__(self, step_dir: Path):
        self.leaves: dict = {}
        self._files = []
        self._cache: dict = {}
        for meta_path in sorted(step_dir.glob("meta_p*.json")):
            proc = re.search(r"meta_p(\d+)\.json$", meta_path.name).group(1)
            z = np.load(step_dir / f"shards_p{proc}.npz")
            self._files.append(z)
            meta = json.loads(meta_path.read_text())
            for key, info in meta["leaves"].items():
                entry = self.leaves.setdefault(
                    key, {"shape": tuple(info["shape"]),
                          "dtype": np.dtype(info["dtype"]), "shards": []})
                for sh in info["shards"]:
                    entry["shards"].append((sh["index"], z, sh["key"]))

    def read(self, key: str, index: Tuple[slice, ...]) -> np.ndarray:
        """Assemble the requested global slice from overlapping shards.

        Memoized per (key, slice): make_array_from_callback asks once per
        device, so a leaf replicated over N devices would otherwise be
        assembled N times."""
        entry = self.leaves[key]
        gshape = entry["shape"]
        want = tuple(sl.indices(dim)[:2] for sl, dim in zip(index, gshape))
        ckey = (key, want)
        if ckey in self._cache:
            return self._cache[ckey]
        if not want:  # scalar
            _, z, skey = entry["shards"][0]
            out = _unview(z[skey], entry["dtype"]).astype(entry["dtype"])
            self._cache[ckey] = out
            return out
        out_shape = [stop - start for start, stop in want]
        out = np.empty(out_shape, entry["dtype"])
        filled = 0
        for sidx, z, skey in entry["shards"]:
            # Overlap of stored [s0,s1) with wanted [w0,w1) per dim.
            src_sl, dst_sl = [], []
            ok = True
            for (s0, s1), (w0, w1) in zip(sidx, want):
                lo, hi = max(s0, w0), min(s1, w1)
                if lo >= hi:
                    ok = False
                    break
                src_sl.append(slice(lo - s0, hi - s0))
                dst_sl.append(slice(lo - w0, hi - w0))
            if not ok:
                continue
            block = _unview(z[skey], entry["dtype"])[tuple(src_sl)]
            out[tuple(dst_sl)] = block
            filled += block.size
        if filled < int(np.prod(out_shape)):
            raise ValueError(
                f"stored shards do not cover requested slice of {key!r} "
                f"(missing process files?)")
        self._cache[ckey] = out
        return out

    def close(self):
        for z in self._files:
            z.close()
        self._cache.clear()


def restore_sharded(ckpt_dir: str, template: Any,
                    step: Optional[int] = None) -> Tuple[Any, int]:
    """Restore into ``template``'s structure AND sharding layout.

    Template leaves that are sharded ``jax.Array``s are rebuilt shard-by-
    shard via ``make_array_from_callback`` (each device reads only its own
    slice); plain leaves are assembled on host.
    """
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no sharded checkpoints in {ckpt_dir}")
    d = Path(ckpt_dir) / f"step_{step:08d}.sharded"
    store = _ShardStore(d)
    try:
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
            template)
        new_leaves = []
        for path, leaf in leaves_with_path:
            key = _leaf_key(path)
            if key not in store.leaves:
                raise KeyError(f"checkpoint missing leaf {key!r}")
            gshape = store.leaves[key]["shape"]
            if tuple(getattr(leaf, "shape", ())) != gshape:
                raise ValueError(
                    f"shape mismatch for {key!r}: template "
                    f"{tuple(getattr(leaf, 'shape', ()))} vs saved {gshape}")
            dtype = getattr(leaf, "dtype", store.leaves[key]["dtype"])
            if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
                arr = jax.make_array_from_callback(
                    gshape, leaf.sharding,
                    lambda idx, k=key, dt=dtype: store.read(k, idx).astype(dt))
                # One device-side copy so XLA is the SOLE owner of the
                # bytes: on CPU, make_array_from_callback may zero-copy
                # ALIAS the callback's host buffer, and the first
                # DONATING train step after resume then has XLA free
                # memory numpy still owns — glibc aborts with
                # "corrupted double-linked list" (reproduced on jax
                # 0.4.37 by the gspmd resume composition in
                # tests/test_cli.py).
                arr = arr.copy()
            else:
                full = (slice(None),) * len(gshape)
                arr = store.read(key, full).astype(dtype)
            new_leaves.append(arr)
        return jax.tree_util.tree_unflatten(treedef, new_leaves), step
    finally:
        store.close()


def try_restore_sharded(ckpt_dir: str, template: Any) -> Tuple[Optional[Any], int]:
    step = latest_step(ckpt_dir)
    if step is None:
        return None, 0
    state, step = restore_sharded(ckpt_dir, template, step)
    return state, step


class AsyncCheckpointer:
    """Background-thread sharded saves: the step path only pays the
    device->host shard copies; file IO happens off-thread.

    One save in flight at a time (a second ``save`` waits for the first —
    checkpoint cadence should outpace disk, and ordering stays simple).
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, ckpt_dir: str, state: Any, step: int,
             keep_last: Optional[int] = None) -> None:
        self.wait()
        # Snapshot device shards to host NOW (so the caller may donate/mutate
        # state immediately), write files in the background.
        host_state = jax.tree_util.tree_map(_host_shards, state)

        def work():
            try:
                _write_prefetched(ckpt_dir, host_state, step)
                if keep_last is not None and keep_last > 0:
                    prune_old_sharded(ckpt_dir, keep_last)
            except BaseException as e:  # surfaced on next wait()/save()
                self._error = e

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


class _HostShards:
    """A leaf snapshotted as (global shape/dtype, replica-0 host shards)."""

    def __init__(self, leaf):
        self.shape = tuple(getattr(leaf, "shape", ()))
        self.dtype = (np.dtype(leaf.dtype) if hasattr(leaf, "dtype")
                      else np.dtype(np.float32))
        self.shards: List[Tuple[tuple, np.ndarray]] = []
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            seen = set()
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                idx = tuple(tuple(se)
                            for se in _norm_index(shard.index, self.shape))
                if idx in seen:
                    continue
                seen.add(idx)
                self.shards.append((idx, np.asarray(shard.data)))
        else:
            self.shards.append(
                (tuple((0, n) for n in self.shape), np.asarray(leaf)))


def _host_shards(leaf) -> _HostShards:
    return _HostShards(leaf)


def _write_prefetched(ckpt_dir: str, host_state: Any, step: int) -> str:
    """save_sharded over already-host-resident shards."""
    proc = jax.process_index()
    d = Path(ckpt_dir) / f"step_{step:08d}.sharded"
    d.mkdir(parents=True, exist_ok=True)
    arrays = {}
    meta = {"leaves": {}, "world": jax.process_count()}
    for path, hs in jax.tree_util.tree_flatten_with_path(
            host_state, is_leaf=lambda x: isinstance(x, _HostShards))[0]:
        key = _leaf_key(path)
        meta["leaves"][key] = {"shape": list(hs.shape),
                               "dtype": str(hs.dtype), "shards": []}
        for i, (idx, data) in enumerate(hs.shards):
            skey = f"{key}::{i}"
            arrays[skey] = _byte_view(data)
            meta["leaves"][key]["shards"].append(
                {"key": skey, "index": [list(se) for se in idx]})
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, d / f"shards_p{proc}.npz")
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    mtmp = d / f"meta_p{proc}.json.tmp"
    with open(mtmp, "w") as f:
        f.write(json.dumps(meta))
        f.flush()
        os.fsync(f.fileno())
    os.replace(mtmp, d / f"meta_p{proc}.json")
    # The COMPLETE marker is only meaningful if the data it vouches for
    # is durable FIRST: fsync the dir (making both renames durable)
    # before touching the marker, then again after, so a power loss can
    # leave a torn dir without its marker — which latest_step skips —
    # but never a marker vouching for missing bytes.
    _fsync_dir(d)
    (d / f"COMPLETE_p{proc}").touch()
    _fsync_dir(d)
    return str(d)
