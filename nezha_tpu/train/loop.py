"""Training step construction and the host-side training loop.

The reference's hot loop (SURVEY.md §3: forward over op graph -> loss ->
backward -> collective -> optimizer update) becomes ONE jit'd function here:
XLA sees forward+backward+update as a single program, fuses it, and overlaps
the DP gradient collective with backward compute. Buffer donation makes the
parameter/optimizer-state update in-place in HBM (the TPU analogue of the
reference's in-place CUDA optimizer kernels).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np
import jax.numpy as jnp

from nezha_tpu import obs
from nezha_tpu.nn.module import Module, Variables
from nezha_tpu.obs.metrics import StepTimer
from nezha_tpu.optim.optimizers import Optimizer, apply_updates

TrainState = Dict[str, Any]  # {"variables": Variables, "opt_state": Any, "rng": key}


def merge_state(old: Any, new: Any) -> Any:
    """Overlay partial state updates (e.g. BatchNorm stats) onto old state."""
    if not isinstance(new, dict) or not isinstance(old, dict):
        return new if new is not None else old
    out = dict(old)
    for k, v in new.items():
        out[k] = merge_state(old.get(k), v) if k in old else v
    return out


def init_train_state(model: Module, optimizer: Optimizer, rng: jax.Array) -> TrainState:
    variables = model.init(rng)
    return {
        "variables": variables,
        "opt_state": optimizer.init(variables["params"]),
        "rng": rng,
    }


def make_train_step(model: Module, optimizer: Optimizer,
                    loss_fn: Callable[[Any, dict, Variables], Any],
                    jit: bool = True, donate: bool = True):
    """Build the fused train step.

    ``loss_fn(model_out, batch)`` -> scalar loss. The model is called as
    ``model.apply(variables, batch, training=True, rng=...)`` — models take the
    whole batch dict or its main tensor; see each model's ``apply``.

    Returns ``step(state, batch) -> (state, metrics)``.
    """

    def step(state: TrainState, batch: dict):
        variables, opt_state = state["variables"], state["opt_state"]
        rng, step_rng = jax.random.split(state["rng"])

        def compute_loss(params):
            out, new_state = model.apply(
                {"params": params, "state": variables["state"]},
                batch, training=True, rng=step_rng)
            loss = loss_fn(out, batch)
            return jnp.asarray(loss, jnp.float32), (new_state, out)

        (loss, (new_state, _)), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(variables["params"])
        updates, opt_state = optimizer.update(grads, opt_state, variables["params"])
        params = apply_updates(variables["params"], updates)
        new_variables = {"params": params,
                         "state": merge_state(variables["state"], new_state)}
        metrics = {"loss": loss}
        return ({"variables": new_variables, "opt_state": opt_state, "rng": rng},
                metrics)

    if jit:
        step = jax.jit(step, donate_argnums=(0,) if donate else ())
    return step


class Trainer:
    """Host-side loop: pulls batches, dispatches jit'd steps (async — JAX
    queues steps ahead while the host prepares the next batch), logs metrics,
    periodically checkpoints."""

    def __init__(self, model: Module, optimizer: Optimizer, loss_fn,
                 rng: Optional[jax.Array] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 0,
                 log_every: int = 10,
                 metric_logger: Optional[Callable[[int, dict], None]] = None,
                 tracer=None,
                 process_group=None,
                 failure_check_every: int = 0,
                 on_failure: Optional[Callable[[list], None]] = None,
                 failure_mode: str = "stop",
                 rejoin_timeout_s: float = 300.0,
                 recover_fn: Optional[Callable[[], None]] = None,
                 step_fn=None,
                 shard_fn: Optional[Callable[[dict], dict]] = None,
                 save_fn: Optional[Callable[[str, Any, int], Any]] = None,
                 save_wait: Optional[Callable[[], None]] = None,
                 checkpoint_keep: Optional[int] = None,
                 examples_per_step: int = 0,
                 tokens_per_step: int = 0):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.log_every = log_every
        self.metric_logger = metric_logger
        # Optional aux subsystems: a utils.Tracer to capture an XLA profile
        # over a step window, and a dist.ProcessGroup polled for dead peers
        # (reference coordinator heartbeat role, SURVEY.md §1) so a healthy
        # rank can checkpoint-and-stop instead of hanging in a collective.
        self.tracer = tracer
        self.process_group = process_group
        self.failure_check_every = failure_check_every
        self.on_failure = on_failure
        # Elastic recovery (SURVEY.md §5): "stop" checkpoints then raises
        # (supervisor restarts the world); "rejoin" additionally waits for
        # the dead rank's replacement to re-rendezvous (the coordinator
        # frees crashed rank slots, csrc/coordinator.cpp rejoin), reloads
        # the rescue checkpoint, and CONTINUES in-process. recover_fn, when
        # set, replaces the default reload (initialize) for states that
        # need mode-specific re-layout after restore.
        if failure_mode not in ("stop", "rejoin"):
            raise ValueError(f"failure_mode must be stop|rejoin, got "
                             f"{failure_mode!r}")
        if failure_mode == "rejoin":
            # Reject the combos whose semantics would otherwise silently
            # degrade: recovery NEEDS a checkpoint to reload, and an
            # on_failure callback would never fire on the heal path.
            if not checkpoint_dir:
                raise ValueError("failure_mode='rejoin' needs a "
                                 "checkpoint_dir: recovery reloads the "
                                 "rescue checkpoint")
            if on_failure is not None:
                raise ValueError("failure_mode='rejoin' and on_failure are "
                                 "mutually exclusive (rejoin continues "
                                 "in-process; the callback would never "
                                 "fire)")
        self.failure_mode = failure_mode
        self.rejoin_timeout_s = rejoin_timeout_s
        self.recover_fn = recover_fn
        # Injection points so one loop serves every parallelism mode: a
        # prebuilt sharded step (DP/ZeRO-1/GSPMD), a host-side batch-placement
        # fn, and a checkpoint writer (e.g. sharded_checkpoint.save_sharded).
        self.step_fn = step_fn if step_fn is not None else make_train_step(
            model, optimizer, loss_fn)
        self.shard_fn = shard_fn
        self._save_fn = save_fn
        # For async save_fns (AsyncCheckpointer.save): blocks until the
        # in-flight write commits. Called before raising on peer failure —
        # a rescue checkpoint whose files are still being written when the
        # process dies is a torn save.
        self._save_wait = save_wait
        # Retention: keep only the N newest checkpoints (None = keep all).
        # Custom save_fns handle their own pruning (the CLI wraps them).
        self.checkpoint_keep = checkpoint_keep
        self.examples_per_step = examples_per_step
        # Tokens consumed per optimizer step (LM configs: batch x seq) —
        # feeds the tokens/sec-per-chip metric of record (PAPER.md §0).
        self.tokens_per_step = tokens_per_step
        # Rate windows close on the loop's own log boundaries (a resume
        # can land mid-window), so the timer runs in explicit-lap mode.
        self._timer = StepTimer(window=max(log_every, 1))
        self._first_step = True  # next dispatch pays trace+compile
        self.state: Optional[TrainState] = None
        self.global_step = 0

    def _save(self, step: int) -> None:
        with obs.span("checkpoint.save", step=step):
            self._save_checkpoint(step)

    def _save_checkpoint(self, step: int) -> None:
        if self._save_fn is not None:
            if self.checkpoint_keep:
                # Every built-in save_fn (save_checkpoint, save_sharded,
                # AsyncCheckpointer.save) takes keep_last; only pass it when
                # retention is on so bare custom save_fns keep working.
                self._save_fn(self.checkpoint_dir, self.state, step,
                              keep_last=self.checkpoint_keep)
            else:
                self._save_fn(self.checkpoint_dir, self.state, step)
        else:
            from nezha_tpu.train import checkpoint as ckpt
            ckpt.save_checkpoint(self.checkpoint_dir, self.state, step,
                                 keep_last=self.checkpoint_keep)

    def _rejoin_and_reload(self, failed: list) -> None:
        """The healthy-rank half of elastic recovery: the rescue checkpoint
        is already committed (fit saves before calling this); poll until the
        coordinator reports no failed ranks (the replacement's HELLO clears
        the mark), then reload the rescue checkpoint so survivor and
        replacement resume from the same step with identical state. Raises
        if no replacement rejoins within ``rejoin_timeout_s``."""
        import sys

        print(f"peer rank(s) {failed} failed at step {self.global_step}; "
              f"checkpoint committed; waiting for rejoin "
              f"(timeout {self.rejoin_timeout_s:.0f}s)", file=sys.stderr)
        deadline = time.monotonic() + self.rejoin_timeout_s
        while True:
            still = self.process_group.failed_ranks()
            if not still:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"peer rank(s) {still} failed at step "
                    f"{self.global_step}; no replacement rejoined within "
                    f"{self.rejoin_timeout_s:.0f}s")
            time.sleep(0.2)
        if self.recover_fn is not None:
            self.recover_fn()
        else:
            self.initialize(resume=True)
        print(f"world healed; resumed from step {self.global_step}",
              file=sys.stderr)

    def initialize(self, resume: bool = True):
        from nezha_tpu.train import checkpoint as ckpt
        state = init_train_state(self.model, self.optimizer, self.rng)
        if resume and self.checkpoint_dir:
            if self._save_fn is not None:
                # A custom save_fn means a custom on-disk format; the only
                # shipped one is the per-shard layout, so pair its restore.
                from nezha_tpu.train import sharded_checkpoint as sck
                restored, step = sck.try_restore_sharded(
                    self.checkpoint_dir, state)
            else:
                restored, step = ckpt.try_restore(self.checkpoint_dir, state)
            if restored is not None:
                # One device-side copy so XLA is the SOLE owner of the
                # bytes: the dense restore returns numpy leaves, and on
                # CPU the implicit (or explicit) device transfer may
                # zero-copy ALIAS the host buffer — the next DONATING
                # train step then has XLA free memory numpy still owns
                # (NaN state, then a glibc heap abort; reproduced on
                # jax 0.4.37 by the elastic-rejoin reload in
                # tests/test_cli.py). jnp.asarray may alias; .copy()
                # allocates an XLA-owned buffer the alias is read from.
                # numpy leaves only: the sharded restore already hands
                # back XLA-owned copies (sharded_checkpoint.py), and a
                # second whole-state copy would transiently double
                # restore memory.
                restored = jax.tree_util.tree_map(
                    lambda a: jnp.asarray(a).copy()
                    if isinstance(a, np.ndarray) else a,
                    restored)
                state, self.global_step = restored, step
        self.state = state
        return state

    def fit(self, batches: Iterator[dict], steps: int) -> Dict[str, float]:
        if self.state is None:
            self.initialize()
        last_metrics: Dict[str, float] = {}
        n_chips = max(jax.device_count(), 1)
        self._timer.start()
        window_steps = 0  # actual steps this logging window (a resume can
        # land mid-window, so log_every would overstate the first rate)
        for _ in range(steps):
            batch = next(batches)
            if self.shard_fn is not None:
                batch = self.shard_fn(batch)
            if self._first_step:
                # The first dispatch carries trace+compile; as a span it
                # is the run's compile-time record (jit compiles
                # synchronously, so the call returns after the build).
                self._first_step = False
                if (not self.tokens_per_step and isinstance(batch, dict)
                        and hasattr(batch.get("tokens"), "size")):
                    # LM batches: global tokens consumed per step, for the
                    # tokens/sec-per-chip metric (shape is static, so one
                    # read here covers the run).
                    self.tokens_per_step = int(batch["tokens"].size)
                with obs.span("train.first_step",
                              step=self.global_step + 1):
                    self.state, metrics = self.step_fn(self.state, batch)
            else:
                self.state, metrics = self.step_fn(self.state, batch)
            self.global_step += 1
            window_steps += 1
            if self.tracer is not None:
                self.tracer.maybe_trace(self.global_step)
            if (self.failure_check_every and self.process_group is not None
                    and self.global_step % self.failure_check_every == 0):
                failed = self.process_group.failed_ranks()
                if failed:
                    if self.checkpoint_dir:  # preserve progress first
                        self._save(self.global_step)
                        if self._save_wait is not None:
                            self._save_wait()  # commit before raising
                    if self.failure_mode == "rejoin":  # ckpt_dir guaranteed
                        with obs.span("train.rejoin", failed=failed):
                            self._rejoin_and_reload(failed)
                        # Rate windows must not count the heal wait.
                        self._timer.start()
                        window_steps = 0
                        continue
                    if self.on_failure is not None:
                        self.on_failure(failed)
                    else:
                        raise RuntimeError(
                            f"peer rank(s) {failed} failed at step "
                            f"{self.global_step}")
            if self.log_every and self.global_step % self.log_every == 0:
                # The float() fetches are the window's device barrier (the
                # StepTimer contract): every dispatched step has finished
                # before the lap closes.
                last_metrics = {k: float(v) for k, v in metrics.items()}
                rate = self._timer.lap(last_metrics.get("loss", 0.0),
                                       window_steps)
                last_metrics["steps_per_sec"] = rate if rate is not None \
                    else 0.0
                if self.examples_per_step:
                    eps = last_metrics["steps_per_sec"] \
                        * self.examples_per_step
                    last_metrics["examples_per_sec"] = eps
                    last_metrics["examples_per_sec_per_chip"] = \
                        eps / n_chips
                if self.tokens_per_step:
                    tps = last_metrics["steps_per_sec"] \
                        * self.tokens_per_step
                    last_metrics["tokens_per_sec"] = tps
                    last_metrics["tokens_per_sec_per_chip"] = tps / n_chips
                last_metrics["step"] = self.global_step
                obs.counter("train.steps").inc(window_steps)
                obs.record_metrics(self.global_step, last_metrics)
                window_steps = 0
                if self.metric_logger:
                    self.metric_logger(self.global_step, last_metrics)
            if (self.checkpoint_every and self.checkpoint_dir
                    and self.global_step % self.checkpoint_every == 0):
                self._save(self.global_step)
        if not last_metrics and steps:
            last_metrics = {k: float(v) for k, v in metrics.items()}
            last_metrics["step"] = self.global_step
        return last_metrics
