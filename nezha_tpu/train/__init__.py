"""Training runtime: step builders, the Trainer loop, mixed precision,
checkpointing — the TPU-native counterpart of the reference's per-rank
training loop (SURVEY.md §3 call stack 2)."""

from nezha_tpu.train.loop import TrainState, make_train_step, merge_state, Trainer

__all__ = ["TrainState", "make_train_step", "merge_state", "Trainer"]


def __getattr__(name):
    if name in ("save_checkpoint", "restore_checkpoint", "latest_step"):
        from nezha_tpu.train import checkpoint
        return getattr(checkpoint, name)
    if name in ("save_sharded", "restore_sharded", "try_restore_sharded",
                "AsyncCheckpointer"):
        from nezha_tpu.train import sharded_checkpoint
        return getattr(sharded_checkpoint, name)
    if name in ("DynamicLossScale", "NoOpLossScale"):
        from nezha_tpu.train import mixed_precision
        return getattr(mixed_precision, name)
    raise AttributeError(name)
