"""Loss scaling for mixed-precision training.

bf16 (the TPU default, `nezha_tpu.tensor.bf16_policy`) needs NO loss scaling
— its exponent range matches fp32 — so the standard path uses `NoOpLossScale`.
`DynamicLossScale` exists for fp16-style parity with the reference's mixed
bf16/fp32 configs (SURVEY.md §2 "mixed precision") and for any future dtype
with a narrow exponent: scale the loss up, unscale grads, skip the step and
halve the scale on inf/nan, double it after a clean streak.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp


def _all_finite(tree: Any) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    ok = jnp.array(True)
    for leaf in leaves:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


@dataclasses.dataclass(frozen=True)
class NoOpLossScale:
    """bf16/fp32 path: identity. Keeps train-step code uniform."""

    def scale(self, loss):
        return loss

    def unscale(self, grads):
        return grads

    def adjust(self, grads) -> Tuple[Any, "NoOpLossScale", jnp.ndarray]:
        """Returns (grads, new_self, grads_are_finite)."""
        return grads, self, _all_finite(grads)


@dataclasses.dataclass(frozen=True)
class DynamicLossScale:
    """Classic dynamic loss scaling (a pure value — thread it through the
    jit'd step like optimizer state)."""

    scale_value: jnp.ndarray = dataclasses.field(
        default_factory=lambda: jnp.float32(2.0 ** 15))
    growth_interval: int = 2000
    counter: jnp.ndarray = dataclasses.field(
        default_factory=lambda: jnp.int32(0))

    def scale(self, loss):
        return loss * self.scale_value.astype(loss.dtype)

    def unscale(self, grads):
        inv = 1.0 / self.scale_value
        return jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads)

    def adjust(self, grads) -> Tuple[Any, "DynamicLossScale", jnp.ndarray]:
        """Unscale grads; on overflow halve the scale (caller should skip the
        update when ``finite`` is False), else grow after the interval."""
        grads = self.unscale(grads)
        finite = _all_finite(grads)
        new_counter = jnp.where(finite, self.counter + 1, 0)
        grow = new_counter >= self.growth_interval
        new_scale = jnp.where(
            finite,
            jnp.where(grow, self.scale_value * 2.0, self.scale_value),
            jnp.maximum(self.scale_value * 0.5, 1.0))
        new_counter = jnp.where(grow, 0, new_counter)
        new_self = DynamicLossScale(new_scale, self.growth_interval, new_counter)
        return grads, new_self, finite


jax.tree_util.register_pytree_node(
    DynamicLossScale,
    lambda ls: ((ls.scale_value, ls.counter), ls.growth_interval),
    lambda interval, children: DynamicLossScale(children[0], interval, children[1]),
)
jax.tree_util.register_pytree_node(
    NoOpLossScale, lambda ls: ((), None), lambda _, __: NoOpLossScale())
