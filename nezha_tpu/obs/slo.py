"""Declarative SLOs over window views: compliance + error-budget burn.

An SLO is one sentence about a windowed statistic::

    serve.ttft_s p99 < 0.5 over 60s
    serve.queue_depth max < 8 over 10s objective 0.99
    serve.admitted_total rate > 0.5 over 60s

:func:`parse_slo` turns the sentence into an :class:`SLOConfig`;
:func:`evaluate_slo` checks one config against a
``Registry.windows(duration)`` view (histogram quantiles, gauge
last/min/max, counter rate/delta — the stat picks the instrument kind);
:class:`SLOTracker` accumulates per-window verdicts into compliance and
**error-budget burn rate**: with objective ``o`` the budget is ``1-o``
bad windows, and burn = observed bad fraction / budget — burn 1.0 spends
the budget exactly at the objective boundary, burn 2.0 exhausts it in
half the period (the classic multi-window burn-rate alert input,
consumed by obs/watchdog.py).

The serving CLI wires specs from ``nezha-serve --slo`` (repeatable /
``;``-separated); every evaluation is also recorded as a typed
``slo.eval`` event so ``nezha-telemetry RUN_DIR --slo`` can render
compliance/burn offline from ``events.jsonl`` alone.
"""

from __future__ import annotations

import re
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

#: Window stats an SLO may reference, and the instrument section each
#: resolves against (histograms win on name collision — percentiles are
#: the common case).
_HIST_STATS = ("p50", "p90", "p99", "mean", "count")
_GAUGE_STATS = ("last", "min", "max")
_COUNTER_STATS = ("rate", "delta")
VALID_STATS = _HIST_STATS + _GAUGE_STATS + _COUNTER_STATS

_OPS = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}

_SPEC_RE = re.compile(
    r"^\s*(?P<metric>[A-Za-z0-9_.\-]+)\s+(?P<stat>[a-z0-9_]+)\s+"
    r"(?P<op><=|>=|<|>)\s+(?P<threshold>[0-9.eE+\-]+)\s+"
    r"over\s+(?P<window>[0-9.]+)\s*s"
    r"(?:\s+objective\s+(?P<objective>[0-9.]+))?\s*$")


@dataclass(frozen=True)
class SLOConfig:
    """One service-level objective over a rolling window."""

    metric: str          # instrument name, e.g. "serve.ttft_s"
    stat: str            # p99 / max / rate / ... (VALID_STATS)
    op: str              # "<" | "<=" | ">" | ">="
    threshold: float
    window_s: float      # evaluation window duration
    objective: float = 0.999   # target fraction of compliant windows

    @property
    def name(self) -> str:
        """Stable display/grouping key: ``serve.ttft_s:p99<0.5/60s``."""
        w = int(self.window_s) if float(self.window_s).is_integer() \
            else self.window_s
        return f"{self.metric}:{self.stat}{self.op}{self.threshold}/{w}s"

    def spec(self) -> str:
        """Round-trippable spec string (``parse_slo(cfg.spec())``)."""
        out = (f"{self.metric} {self.stat} {self.op} {self.threshold} "
               f"over {self.window_s}s")
        if self.objective != 0.999:
            out += f" objective {self.objective}"
        return out


def parse_slo(spec: str) -> SLOConfig:
    """``"serve.ttft_s p99 < 0.5 over 60s [objective 0.99]"`` ->
    :class:`SLOConfig`. Raises ``ValueError`` with the offending spec on
    any grammar violation (the CLI surfaces it as an argument error)."""
    m = _SPEC_RE.match(spec)
    if not m:
        raise ValueError(
            f"bad SLO spec {spec!r} (want: '<metric> <stat> <op> "
            f"<threshold> over <N>s [objective <frac>]')")
    stat = m.group("stat")
    if stat not in VALID_STATS:
        raise ValueError(
            f"bad SLO stat {stat!r} in {spec!r} (one of "
            f"{', '.join(VALID_STATS)})")
    objective = float(m.group("objective") or 0.999)
    if not 0.0 < objective < 1.0:
        raise ValueError(
            f"SLO objective must be in (0, 1), got {objective} "
            f"in {spec!r}")
    window_s = float(m.group("window"))
    if window_s <= 0:
        raise ValueError(f"SLO window must be > 0s in {spec!r}")
    return SLOConfig(metric=m.group("metric"), stat=stat,
                     op=m.group("op"),
                     threshold=float(m.group("threshold")),
                     window_s=window_s, objective=objective)


def parse_slo_args(values) -> List[SLOConfig]:
    """CLI form: repeatable ``--slo`` flags, each possibly
    ``;``-separated. Empty segments are skipped."""
    out: List[SLOConfig] = []
    for value in values or []:
        for part in str(value).split(";"):
            part = part.strip()
            if part:
                out.append(parse_slo(part))
    return out


def window_stat(view: dict, metric: str, stat: str) -> Optional[float]:
    """Resolve ``metric``'s ``stat`` in a window view, or ``None`` when
    the window saw no such instrument (no data is NOT a violation)."""
    if stat in _HIST_STATS:
        h = (view.get("histograms") or {}).get(metric)
        if h is not None:
            return float(h.get(stat, 0.0))
        return None
    if stat in _GAUGE_STATS:
        g = (view.get("gauges") or {}).get(metric)
        if g is not None:
            return float(g.get(stat, 0.0))
        return None
    c = (view.get("counters") or {}).get(metric)
    if c is not None:
        return float(c.get(stat, 0.0))
    return None


def evaluate_slo(cfg: SLOConfig, view: dict) -> dict:
    """One windowed evaluation -> the ``slo.eval`` event detail shape:
    ``{"slo", "metric", "stat", "op", "threshold", "window_s",
    "value", "ok", "no_data"}``. A window with no observations
    evaluates ``ok`` (vacuous) with ``no_data`` set, and trackers skip
    it — an idle service doesn't burn budget."""
    value = window_stat(view, cfg.metric, cfg.stat)
    if value is None:
        ok, no_data = True, True
    else:
        ok, no_data = _OPS[cfg.op](value, cfg.threshold), False
    return {"slo": cfg.name, "metric": cfg.metric, "stat": cfg.stat,
            "op": cfg.op, "threshold": cfg.threshold,
            "window_s": cfg.window_s, "objective": cfg.objective,
            "value": value, "ok": ok, "no_data": no_data}


class SLOTracker:
    """Per-SLO budget accounting over a trailing run of evaluations.

    ``observe(ok)`` feeds one window verdict; ``compliance`` is the
    lifetime good fraction, ``burn_rate()`` the trailing bad fraction
    divided by the error budget ``1 - objective``. Pinned by a
    hand-computed-trace unit test (objective 0.9, 8 good + 2 bad ->
    compliance 0.8, burn 2.0). Single-consumer (the watchdog thread);
    not locked."""

    def __init__(self, cfg: SLOConfig, horizon: int = 100):
        self.cfg = cfg
        self.good = 0
        self.bad = 0
        self._recent: deque = deque(maxlen=max(1, horizon))

    def observe(self, ok: bool) -> None:
        if ok:
            self.good += 1
        else:
            self.bad += 1
        self._recent.append(bool(ok))

    @property
    def total(self) -> int:
        return self.good + self.bad

    @property
    def compliance(self) -> float:
        t = self.total
        return self.good / t if t else 1.0

    def bad_fraction(self) -> float:
        if not self._recent:
            return 0.0
        return sum(1 for ok in self._recent if not ok) / len(self._recent)

    def burn_rate(self) -> float:
        """Error-budget burn over the trailing horizon: 0.0 = no burn,
        1.0 = burning exactly the budget, >1 = on track to exhaust it
        early."""
        budget = 1.0 - self.cfg.objective
        return self.bad_fraction() / budget

    def status(self) -> dict:
        return {"slo": self.cfg.name, "objective": self.cfg.objective,
                "evaluations": self.total, "good": self.good,
                "bad": self.bad, "compliance": self.compliance,
                "burn_rate": self.burn_rate()}


def summarize_slo_events(events: List[dict]) -> Dict[str, dict]:
    """Rebuild per-SLO compliance/burn from a run dir's ``slo.eval``
    event records (the ``nezha-telemetry --slo`` offline path). Events
    with no matching data windows (``no_data``) are excluded, mirroring
    the live tracker."""
    rows: Dict[str, dict] = {}
    for rec in events:
        if rec.get("kind") != "slo.eval":
            continue
        d = rec.get("detail") or {}
        name = d.get("slo")
        if not isinstance(name, str) or d.get("no_data"):
            continue
        row = rows.setdefault(
            name, {"slo": name, "good": 0, "bad": 0,
                   "objective": float(d.get("objective", 0.999)),
                   "last_value": None, "threshold": d.get("threshold"),
                   "window_s": d.get("window_s")})
        if d.get("ok"):
            row["good"] += 1
        else:
            row["bad"] += 1
        row["last_value"] = d.get("value")
    for row in rows.values():
        total = row["good"] + row["bad"]
        row["evaluations"] = total
        row["compliance"] = row["good"] / total if total else 1.0
        budget = 1.0 - row["objective"]
        bad_frac = row["bad"] / total if total else 0.0
        row["burn_rate"] = bad_frac / budget if budget > 0 else 0.0
    return rows
