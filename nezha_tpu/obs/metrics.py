"""Metrics recording: JSONL sink + async-dispatch-aware step timing.

Absorbed from ``utils/metrics.py`` into the telemetry subsystem (the
public names stay importable from ``nezha_tpu.utils`` as thin
re-exports). JAX dispatch is asynchronous — ``step()`` returns before the
device finishes — so naive per-step wall timing measures Python overhead,
not the step. ``StepTimer`` measures over windows and closes each window
with a host fetch of a device scalar (the only reliable barrier on the
tunneled TPU platform; see bench.py's note), giving true steps/sec.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, IO, Optional


class MetricsLogger:
    """Append-only JSONL metrics: one object per line with ``step`` and a
    wall-clock ``ts``. Cheap enough to call every logged step; safe to use
    as the Trainer's ``metric_logger``."""

    def __init__(self, path: str, flush_every: int = 1, mode: str = "a"):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f: Optional[IO[str]] = open(path, mode)
        self._flush_every = max(flush_every, 1)
        self._since_flush = 0
        self.path = path

    def __call__(self, step: int, metrics: Dict[str, Any]) -> None:
        self.log(step, metrics)

    def log(self, step: int, metrics: Dict[str, Any]) -> None:
        if self._f is None:
            raise ValueError("logger is closed")
        rec = {"step": int(step), "ts": time.time()}
        for k, v in metrics.items():
            # Ints stay ints (a metrics-dict "step" must not demote the
            # canonical int field to float); device/numpy scalars coerce.
            if isinstance(v, bool) or isinstance(v, int):
                rec[k] = v
            else:
                rec[k] = float(v) if hasattr(v, "__float__") else v
        self._f.write(json.dumps(rec) + "\n")
        self._since_flush += 1
        if self._since_flush >= self._flush_every:
            self._f.flush()
            self._since_flush = 0

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_metrics(path: str) -> list:
    """Read a JSONL metrics file back as a list of dicts."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


class StepTimer:
    """Windowed steps/sec with a true device barrier per window.

    Usage::

        timer = StepTimer(window=10)
        for batch in batches:
            state, metrics = step(state, batch)
            rate = timer.tick(metrics["loss"])   # None inside a window
            if rate is not None: ...             # steps/sec for the window

    ``tick`` fetches the scalar to host only at window edges, so the
    dispatch pipeline stays full in between. For loops that pick their own
    window boundaries (the Trainer logs on global-step multiples, which a
    mid-window resume can desynchronize from a fixed tick count), use the
    explicit form: ``start()`` once, then ``lap(scalar, n)`` at each
    boundary to close a window of exactly ``n`` steps.
    """

    def __init__(self, window: int = 10):
        self.window = max(window, 1)
        self._count = 0
        self._t0: Optional[float] = None

    def tick(self, device_scalar) -> Optional[float]:
        if self._t0 is None:  # first call: sync, then open the window
            float(device_scalar)
            self._t0 = time.perf_counter()
            self._count = 0
            return None
        self._count += 1
        if self._count < self.window:
            return None
        float(device_scalar)  # barrier: all window steps actually finished
        now = time.perf_counter()
        rate = self._count / max(now - self._t0, 1e-9)
        self._t0 = now
        self._count = 0
        return rate

    # -- explicit-window form ----------------------------------------------
    def start(self) -> None:
        """Open a window now (no barrier: pair with a ``lap`` whose scalar
        sync defines the closing edge)."""
        self._t0 = time.perf_counter()
        self._count = 0

    def lap(self, device_scalar, steps: int) -> Optional[float]:
        """Close an explicit window of ``steps`` steps: barrier on the
        scalar, return steps/sec since ``start()``/the previous lap.
        Returns None when no window is open or it covered zero steps."""
        float(device_scalar)  # barrier: the window's steps actually finished
        now = time.perf_counter()
        if self._t0 is None or steps <= 0:
            self._t0 = now
            return None
        rate = steps / max(now - self._t0, 1e-9)
        self._t0 = now
        return rate

    def reset(self) -> None:
        """Forget the open window (e.g. after an elastic-recovery stall —
        the heal wait must not count against the next window's rate)."""
        self._t0 = None
        self._count = 0
