"""Telemetry subsystem: process-wide registry + run-scoped sinks.

The stack self-reports its metrics of record (PAPER.md §0: steps/sec,
examples- and tokens-per-sec-per-chip, per-collective payload bytes and
bus bandwidth, compile-cache behavior) instead of leaving them to ad-hoc
computation in bench.py. Three pieces:

- ``registry``: counters / gauges / histograms / wall-clock spans with
  branch-only no-op fast paths while disabled (see registry.py docstring
  for the exact contract).
- ``sink``: ``start_run(run_dir)`` streams ``metrics.jsonl`` +
  ``spans.jsonl`` and writes a final ``summary.json`` —
  ``nezha-train --run-dir`` wires it up; ``nezha-telemetry <run-dir>``
  renders the report (obs/report.py).
- ``metrics`` / ``trace``: the JSONL logger, async-dispatch-aware
  StepTimer, and jax.profiler wrappers absorbed from ``utils/metrics.py``
  and ``utils/profiling.py`` (those modules remain as thin re-exports).
- ``timeseries`` / ``slo`` / ``watchdog``: the rolling-window layer —
  fixed-interval bucket rings with mergeable log-bucket sketches
  (``Registry.windows(duration)``, the Prometheus-style ``/metrics``
  exposition and its fleet merge), declarative SLOs with error-budget
  burn rate, and the anomaly watchdog streaming typed events to
  ``events.jsonl``.
"""

from nezha_tpu.obs.metrics import MetricsLogger, StepTimer, read_metrics
from nezha_tpu.obs.registry import (
    NULL_SPAN,
    Counter,
    Gauge,
    Histogram,
    REGISTRY,
    Registry,
    Span,
    TRACE_HEADER,
    adopt_trace_header,
    counter,
    current_trace,
    disable,
    emit_span,
    enable,
    enabled,
    gauge,
    histogram,
    mint_trace_id,
    new_span_id,
    record_collective,
    record_event,
    record_metrics,
    set_trace_sample,
    span,
    stats_snapshot,
    trace_context,
    trace_sample,
    traced_span,
    windows,
)
from nezha_tpu.obs.sink import (
    EVENTS_FILE,
    METRICS_FILE,
    SPANS_FILE,
    SUMMARY_FILE,
    RunSink,
    current_sink,
    end_run,
    start_run,
)
from nezha_tpu.obs.slo import (
    SLOConfig,
    SLOTracker,
    evaluate_slo,
    parse_slo,
    parse_slo_args,
    summarize_slo_events,
)
from nezha_tpu.obs.timeseries import (
    LogSketch,
    WINDOW_DURATIONS,
    WindowStore,
    current_windows,
    install_windows,
    merge_window_payloads,
    parse_prometheus,
    render_prometheus,
    uninstall_windows,
    windows_payload,
)
from nezha_tpu.obs.trace import Tracer, annotate, profile_trace
from nezha_tpu.obs.watchdog import Watchdog, WatchdogConfig, WatchdogThread

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Span", "REGISTRY",
    "NULL_SPAN", "counter", "gauge", "histogram", "span", "enabled",
    "enable", "disable", "record_metrics", "record_collective",
    "trace_context", "current_trace", "mint_trace_id", "new_span_id",
    "set_trace_sample", "trace_sample", "traced_span", "emit_span",
    "stats_snapshot", "TRACE_HEADER", "adopt_trace_header",
    "RunSink", "start_run", "end_run", "current_sink",
    "METRICS_FILE", "SPANS_FILE", "EVENTS_FILE", "SUMMARY_FILE",
    "MetricsLogger", "StepTimer", "read_metrics",
    "Tracer", "annotate", "profile_trace",
    "record_event", "windows",
    "LogSketch", "WindowStore", "WINDOW_DURATIONS",
    "install_windows", "uninstall_windows", "current_windows",
    "windows_payload", "merge_window_payloads",
    "render_prometheus", "parse_prometheus",
    "SLOConfig", "SLOTracker", "parse_slo", "parse_slo_args",
    "evaluate_slo", "summarize_slo_events",
    "Watchdog", "WatchdogConfig", "WatchdogThread",
]
