"""Telemetry subsystem: process-wide registry + run-scoped sinks.

The stack self-reports its metrics of record (PAPER.md §0: steps/sec,
examples- and tokens-per-sec-per-chip, per-collective payload bytes and
bus bandwidth, compile-cache behavior) instead of leaving them to ad-hoc
computation in bench.py. Three pieces:

- ``registry``: counters / gauges / histograms / wall-clock spans with
  branch-only no-op fast paths while disabled (see registry.py docstring
  for the exact contract).
- ``sink``: ``start_run(run_dir)`` streams ``metrics.jsonl`` +
  ``spans.jsonl`` and writes a final ``summary.json`` —
  ``nezha-train --run-dir`` wires it up; ``nezha-telemetry <run-dir>``
  renders the report (obs/report.py).
- ``metrics`` / ``trace``: the JSONL logger, async-dispatch-aware
  StepTimer, and jax.profiler wrappers absorbed from ``utils/metrics.py``
  and ``utils/profiling.py`` (those modules remain as thin re-exports).
"""

from nezha_tpu.obs.metrics import MetricsLogger, StepTimer, read_metrics
from nezha_tpu.obs.registry import (
    NULL_SPAN,
    Counter,
    Gauge,
    Histogram,
    REGISTRY,
    Registry,
    Span,
    TRACE_HEADER,
    adopt_trace_header,
    counter,
    current_trace,
    disable,
    emit_span,
    enable,
    enabled,
    gauge,
    histogram,
    mint_trace_id,
    new_span_id,
    record_collective,
    record_metrics,
    set_trace_sample,
    span,
    stats_snapshot,
    trace_context,
    trace_sample,
    traced_span,
)
from nezha_tpu.obs.sink import (
    METRICS_FILE,
    SPANS_FILE,
    SUMMARY_FILE,
    RunSink,
    current_sink,
    end_run,
    start_run,
)
from nezha_tpu.obs.trace import Tracer, annotate, profile_trace

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Span", "REGISTRY",
    "NULL_SPAN", "counter", "gauge", "histogram", "span", "enabled",
    "enable", "disable", "record_metrics", "record_collective",
    "trace_context", "current_trace", "mint_trace_id", "new_span_id",
    "set_trace_sample", "trace_sample", "traced_span", "emit_span",
    "stats_snapshot", "TRACE_HEADER", "adopt_trace_header",
    "RunSink", "start_run", "end_run", "current_sink",
    "METRICS_FILE", "SPANS_FILE", "SUMMARY_FILE",
    "MetricsLogger", "StepTimer", "read_metrics",
    "Tracer", "annotate", "profile_trace",
]
