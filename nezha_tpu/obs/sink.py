"""Run-scoped telemetry sink: ``--run-dir`` -> metrics.jsonl + spans.jsonl
+ summary.json.

``start_run(run_dir)`` enables the process-wide registry and attaches a
``RunSink`` that streams per-step metrics and completed spans as JSONL;
``end_run()`` (or ``sink.close()``) writes the final ``summary.json`` from
the registry snapshot and disables telemetry again. One run at a time per
process — the run IS the process-wide enable switch, which is what keeps
the disabled fast paths branch-only.

File contract (frozen; tools/check_telemetry_schema.py validates it):

    metrics.jsonl   one object per line: {"step": int, "ts": float, ...}
    spans.jsonl     one object per line: {"name", "t0", "t1", "dur_s",
                    "attrs"}
    events.jsonl    one object per line: {"event_schema_version", "ts",
                    "kind", "severity", "source", "detail"} — the typed
                    watchdog/SLO event stream (PR 16; validated by the
                    schema checker when present, so pre-PR16 captures
                    stay valid)
    summary.json    the Registry.snapshot() shape (schema_version 1) plus
                    a "run" block of caller-provided metadata
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from nezha_tpu.obs import registry as _registry
from nezha_tpu.obs.metrics import MetricsLogger

METRICS_FILE = "metrics.jsonl"
SPANS_FILE = "spans.jsonl"
EVENTS_FILE = "events.jsonl"
SUMMARY_FILE = "summary.json"


class RunSink:
    """Writer for one run directory. Create via :func:`start_run`."""

    def __init__(self, run_dir: str,
                 registry: Optional[_registry.Registry] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self.run_dir = run_dir
        self.registry = registry if registry is not None \
            else _registry.REGISTRY
        self.meta = dict(meta or {})
        os.makedirs(run_dir, exist_ok=True)
        # A run dir holds exactly ONE run: truncate the streams and drop any
        # stale summary, so retrying with the same --run-dir never mixes a
        # previous capture's records into this run's report.
        try:
            os.remove(os.path.join(run_dir, SUMMARY_FILE))
        except FileNotFoundError:
            pass
        self._metrics = MetricsLogger(os.path.join(run_dir, METRICS_FILE),
                                      mode="w")
        self._spans = open(os.path.join(run_dir, SPANS_FILE), "w")
        self._events = open(os.path.join(run_dir, EVENTS_FILE), "w")
        self._t_start = time.time()
        self._closed = False

    def write_metrics(self, step: int, metrics: Dict[str, Any]) -> None:
        if not self._closed:
            self._metrics.log(step, metrics)

    def write_span(self, rec: dict) -> None:
        if not self._closed:
            self._spans.write(json.dumps(rec) + "\n")
            self._spans.flush()

    def write_event(self, rec: dict) -> None:
        if not self._closed:
            self._events.write(json.dumps(rec) + "\n")
            self._events.flush()

    def summary(self) -> dict:
        out = self.registry.snapshot()
        out["run"] = {**self.meta,
                      "run_dir": os.path.abspath(self.run_dir),
                      "started_at": self._t_start,
                      "wall_seconds": time.time() - self._t_start}
        return out

    def close(self) -> None:
        """Flush streams and write ``summary.json``. Idempotent."""
        if self._closed:
            return
        summary = self.summary()
        self._closed = True
        self._metrics.close()
        self._spans.close()
        self._events.close()
        path = os.path.join(self.run_dir, SUMMARY_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
        os.replace(tmp, path)  # readers never see a torn summary

    def __enter__(self) -> "RunSink":
        return self

    def __exit__(self, *exc) -> None:
        end_run()


_current: Optional[RunSink] = None


def current_sink() -> Optional[RunSink]:
    return _current


def start_run(run_dir: str, meta: Optional[Dict[str, Any]] = None,
              reset: bool = True, windows: bool = True,
              window_interval_s: float = 10.0,
              window_retention_s: float = 300.0) -> RunSink:
    """Open a telemetry run: enable the registry, attach the sink.

    ``reset`` clears instruments accumulated before the run started so the
    summary is genuinely run-scoped (pass False to keep process history).
    ``windows`` installs the rolling-window tap (obs/timeseries) so
    ``Registry.windows(duration)`` and the ``/metrics`` exposition carry
    live 10s/60s/300s views; pass False for a capture-only run (the
    bench scrape-overhead baseline measures exactly this delta).
    Starting a new run closes any previous one first.
    """
    global _current
    if _current is not None:
        end_run()
    if reset:
        _registry.REGISTRY.reset()
    # Pre-register the standard collective rows so every summary carries
    # the per-collective payload table — a single-device run reports
    # zeros rather than omitting the section (stable schema for readers).
    for op in ("all_reduce", "reduce_scatter", "all_gather"):
        _registry.REGISTRY.counter(f"collective.{op}.calls")
        _registry.REGISTRY.counter(f"collective.{op}.payload_bytes")
    if windows:
        from nezha_tpu.obs.timeseries import install_windows
        install_windows(interval_s=window_interval_s,
                        retention_s=window_retention_s)
    sink = RunSink(run_dir, meta=meta)
    _current = sink
    _registry.REGISTRY._sink = sink
    _registry.enable()
    return sink


def end_run() -> None:
    """Write summary.json, detach the sink and the window store,
    disable telemetry."""
    global _current
    sink = _current
    _current = None
    _registry.REGISTRY._sink = None
    if sink is not None:
        sink.close()
    _registry._state.windows = None
    _registry.disable()
