"""Rolling time-series telemetry: window buckets, mergeable sketches,
and the Prometheus-style ``/metrics`` exposition.

The registry (obs/registry.py) answers "what happened since the run
started"; this module answers "what happened over the LAST 10/60/300
seconds" — the sensing layer SLO evaluation (obs/slo.py), the anomaly
watchdog (obs/watchdog.py), and fleet autoscaling consume. Design:

- **window buckets** — a :class:`WindowStore` holds a bounded ring of
  fixed-interval buckets (default 10s x 30 = 300s of history, O(buckets)
  memory regardless of traffic). Every instrument write while a store is
  installed also lands in the CURRENT bucket: counters accumulate a
  per-bucket delta (windows render them as RATES), gauges keep
  last/min/max, histogram observations stream into a per-bucket
  :class:`LogSketch`.
- **mergeable sketches** — histograms use log-spaced buckets (DDSketch
  style, arXiv:1908.10693): a value lands in bucket
  ``ceil(log_gamma(v))``, so merging two sketches is bucket-wise count
  addition and the merged quantile BOUNDS are byte-identical to one
  sketch fed the union stream. That is what makes both roll-ups exact:
  windows merge across TIME (10s buckets -> a 60s view) and replicas
  merge across SPACE (the router's fleet ``/metrics``) without the
  summed-percentile lie.
- **exposition** — :func:`render_prometheus` renders a registry's
  cumulative stats plus its window views in the Prometheus text format
  (names sanitized under the pinned ``nezha_`` prefix, window-labeled
  samples like ``nezha_serve_ttft_s{window="60s",quantile="p99"}``);
  :func:`parse_prometheus` reads it back (``nezha-top``, tests).

Install with :func:`~nezha_tpu.obs.registry.install_windows` (done by
``start_run`` by default); ``Registry.windows(duration)`` returns the
rolled-up view. The disabled-telemetry fast path is untouched: window
taps sit INSIDE the ``_state.enabled`` branch, so a disabled process
still pays a single attribute check per instrument call.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from nezha_tpu.obs import registry as _registry
from nezha_tpu.obs.registry import percentile_of  # noqa: F401  (shared convention)

#: Relative-accuracy knob: bucket i covers (gamma^(i-1), gamma^i], so a
#: reported quantile bound is within a factor gamma of the true value
#: (~5% at the default). Sketches only merge at equal gamma.
DEFAULT_GAMMA = 1.05

#: The canonical roll-up durations (seconds) every exposition surface
#: labels its windows with — ``window="10s" | "60s" | "300s"``.
WINDOW_DURATIONS = (10, 60, 300)

_HIST_SUMMARY_ZERO = {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                      "mean": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0}


class LogSketch:
    """Mergeable log-bucket value sketch (count/sum/min/max exact).

    Not thread-safe on its own — the owning :class:`WindowStore`
    serializes writes under its lock."""

    __slots__ = ("gamma", "count", "total", "min", "max",
                 "zero", "buckets", "_ln_gamma")

    def __init__(self, gamma: float = DEFAULT_GAMMA):
        if gamma <= 1.0:
            raise ValueError(f"gamma must be > 1, got {gamma}")
        self.gamma = float(gamma)
        self._ln_gamma = math.log(self.gamma)
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.zero = 0                      # count of values <= 0
        self.buckets: Dict[int, int] = {}  # log-bucket index -> count

    def _index(self, v: float) -> int:
        # Bucket i covers (gamma^(i-1), gamma^i]; the index depends only
        # on (v, gamma), so any split of one stream across sketches
        # lands every value in the same bucket — merge exactness.
        return math.ceil(math.log(v) / self._ln_gamma - 1e-12)

    def observe(self, v) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        if v <= 0.0:
            # Telemetry values are durations/sizes; <= 0 collapses into
            # one underflow bucket rather than a log() domain error.
            self.zero += 1
        else:
            i = self._index(v)
            self.buckets[i] = self.buckets.get(i, 0) + 1

    def merge(self, other: "LogSketch") -> None:
        """Fold ``other`` in: bucket-wise count addition — the merged
        sketch reports the same quantile bounds as one sketch fed the
        union stream (pinned by tests)."""
        if abs(other.gamma - self.gamma) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with gamma {self.gamma} and "
                f"{other.gamma}")
        self.count += other.count
        self.total += other.total
        if other.min is not None and (self.min is None
                                      or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None
                                      or other.max > self.max):
            self.max = other.max
        self.zero += other.zero
        for i, n in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + n

    def quantile(self, q: float) -> float:
        """Upper quantile BOUND at percentile ``q`` (index-percentile
        rank, the one convention every telemetry surface shares),
        clamped into the exact [min, max] envelope."""
        if self.count == 0:
            return 0.0
        rank = min(int(q / 100.0 * self.count), self.count - 1)
        out: Optional[float] = None
        seen = self.zero
        if rank < seen:
            out = min(self.min if self.min is not None else 0.0, 0.0)
        else:
            for i in sorted(self.buckets):
                seen += self.buckets[i]
                if rank < seen:
                    out = self.gamma ** i     # bucket upper bound
                    break
        if out is None:
            out = self.max if self.max is not None else 0.0
        # Clamp with the EXACT extrema: a bound can overshoot max by a
        # factor <= gamma, and clamping keeps merge exactness (merged
        # and union sketches share identical exact min/max).
        if self.max is not None:
            out = min(out, self.max)
        if self.min is not None:
            out = max(out, self.min)
        return out

    def summary(self) -> dict:
        """The ``Histogram.summary()`` shape (count/sum exact, min/max
        exact, percentiles = sketch bounds)."""
        if self.count == 0:
            return dict(_HIST_SUMMARY_ZERO)
        return {"count": self.count, "sum": self.total,
                "min": self.min if self.min is not None else 0.0,
                "max": self.max if self.max is not None else 0.0,
                "mean": self.total / self.count,
                "p50": self.quantile(50), "p90": self.quantile(90),
                "p99": self.quantile(99)}

    def to_dict(self) -> dict:
        return {"gamma": self.gamma, "count": self.count,
                "sum": self.total,
                "min": self.min, "max": self.max, "zero": self.zero,
                "buckets": {str(i): n for i, n in self.buckets.items()}}

    @classmethod
    def from_dict(cls, obj: dict) -> "LogSketch":
        sk = cls(gamma=float(obj.get("gamma", DEFAULT_GAMMA)))
        sk.count = int(obj.get("count", 0))
        sk.total = float(obj.get("sum", 0.0))
        sk.min = obj.get("min")
        sk.min = float(sk.min) if sk.min is not None else None
        sk.max = obj.get("max")
        sk.max = float(sk.max) if sk.max is not None else None
        sk.zero = int(obj.get("zero", 0))
        sk.buckets = {int(i): int(n)
                      for i, n in (obj.get("buckets") or {}).items()}
        return sk


class _Bucket:
    """One fixed-interval window: per-instrument counter deltas, gauge
    last/min/max triples, and histogram sketches."""

    __slots__ = ("index", "counters", "gauges", "sketches")

    def __init__(self, index: int):
        self.index = index
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, List[float]] = {}   # [last, min, max]
        self.sketches: Dict[str, LogSketch] = {}


class WindowStore:
    """Bounded ring of fixed-interval window buckets.

    One lock serializes the hot recording path AND bucket rotation, so
    a writer can never land an observation in a bucket the rotation is
    simultaneously dropping (pinned by the concurrent-writer test).
    Memory is O(num_buckets x live instruments) — independent of
    traffic volume."""

    # Every recorder thread mutates the ring and the per-bucket maps —
    # declared for nezha-lint's lock-discipline rule.
    _LOCK_GUARDED = {"_buckets": "_lock"}

    def __init__(self, interval_s: float = 10.0,
                 retention_s: float = 300.0,
                 clock: Callable[[], float] = time.time,
                 gamma: float = DEFAULT_GAMMA):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = float(interval_s)
        self.num_buckets = max(1, math.ceil(retention_s / interval_s))
        self.gamma = float(gamma)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: deque = deque(maxlen=self.num_buckets)

    def _bucket(self) -> _Bucket:
        """The CURRENT bucket, rotating the ring if the interval grid
        advanced. Caller holds ``_lock``.

        [holds: _lock]"""
        idx = int(self._clock() / self.interval_s)
        if self._buckets and self._buckets[-1].index >= idx:
            # Same interval — or a clock stumble backwards; recording
            # into the newest bucket keeps the ring monotone either way.
            return self._buckets[-1]
        b = _Bucket(idx)
        self._buckets.append(b)      # maxlen drops the oldest bucket
        return b

    # -------------------------------------------------- recording taps
    def record_counter(self, name: str, n: float) -> None:
        with self._lock:
            b = self._bucket()
            b.counters[name] = b.counters.get(name, 0) + n

    def record_gauge(self, name: str, v: float) -> None:
        with self._lock:
            b = self._bucket()
            cur = b.gauges.get(name)
            if cur is None:
                b.gauges[name] = [v, v, v]
            else:
                cur[0] = v
                if v < cur[1]:
                    cur[1] = v
                if v > cur[2]:
                    cur[2] = v

    def record_histogram(self, name: str, v: float) -> None:
        with self._lock:
            b = self._bucket()
            sk = b.sketches.get(name)
            if sk is None:
                sk = b.sketches[name] = LogSketch(gamma=self.gamma)
            sk.observe(v)

    # ----------------------------------------------------- rolled views
    def view(self, duration_s: float, skip: int = 0) -> dict:
        """Roll the last ``ceil(duration/interval)`` buckets up into one
        window view (the ``Registry.windows(duration)`` shape).
        ``skip`` drops that many NEWEST grid intervals first — the
        watchdog's trailing-baseline view excludes the window it
        compares against.
        """
        n = max(1, math.ceil(float(duration_s) / self.interval_s))
        with self._lock:
            ring = list(self._buckets)
        # Anchor the window to the CLOCK's interval grid, not to
        # whichever buckets happen to exist: on a sparse workload the
        # newest retained bucket can be far in the past, and "the last
        # 60s" must then be empty rather than resurrect it. ``skip``
        # therefore excludes the newest ``skip`` grid INTERVALS (not
        # buckets) — idle gaps count against the baseline too.
        hi = int(self._clock() / self.interval_s) - max(0, int(skip))
        lo = hi - n + 1
        picked = [b for b in ring if lo <= b.index <= hi]
        counters: Dict[str, float] = {}
        gauges: Dict[str, List[float]] = {}
        sketches: Dict[str, LogSketch] = {}
        for b in picked:
            for k, v in b.counters.items():
                counters[k] = counters.get(k, 0) + v
            for k, (last, mn, mx) in b.gauges.items():
                cur = gauges.get(k)
                if cur is None:
                    gauges[k] = [last, mn, mx]
                else:
                    cur[0] = last       # later bucket wins "last"
                    if mn < cur[1]:
                        cur[1] = mn
                    if mx > cur[2]:
                        cur[2] = mx
            for k, sk in b.sketches.items():
                merged = sketches.get(k)
                if merged is None:
                    merged = sketches[k] = LogSketch(gamma=self.gamma)
                merged.merge(sk)
        covered = min(max(len(picked), 1) * self.interval_s,
                      max(float(duration_s), self.interval_s))
        out_h = {}
        for k, sk in sketches.items():
            h = sk.summary()
            h["sketch"] = sk.to_dict()
            out_h[k] = h
        return {
            "window_schema_version": 1,
            "duration_s": float(duration_s),
            "interval_s": self.interval_s,
            "ts": self._clock(),
            "buckets": len(picked),
            "counters": {k: {"delta": v, "rate": v / covered}
                         for k, v in counters.items()},
            "gauges": {k: {"last": t[0], "min": t[1], "max": t[2]}
                       for k, t in gauges.items()},
            "histograms": out_h,
        }


def empty_view(duration_s: float) -> dict:
    """The ``view()`` shape with no window store installed — callers
    render zeros instead of branching on None."""
    return {"window_schema_version": 1, "duration_s": float(duration_s),
            "interval_s": 0.0, "ts": time.time(), "buckets": 0,
            "counters": {}, "gauges": {}, "histograms": {}}


# ------------------------------------------------------ fleet merging
def merge_window_payloads(payloads: Iterable[Optional[dict]]) -> dict:
    """Merge member ``windows_payload()`` dicts into one fleet view —
    sketches merge bucket-wise (exact), counter deltas/rates and gauge
    lasts sum, gauge min/max envelope. Members sharing a
    ``registry_id`` (the thread replica backend: N members, ONE process
    registry) are deduplicated — each distinct registry contributes
    once, so thread and process backends report the same fleet totals.
    """
    merged_windows: Dict[str, dict] = {}
    seen: set = set()
    members = deduped = 0
    for p in payloads:
        if not isinstance(p, dict):
            continue
        members += 1
        reg = p.get("registry_id")
        if isinstance(reg, str) and reg:
            if reg in seen:
                deduped += 1
                continue
            seen.add(reg)
        for label, view in (p.get("windows") or {}).items():
            if not isinstance(view, dict):
                continue
            tgt = merged_windows.get(label)
            if tgt is None:
                tgt = merged_windows[label] = {
                    "window_schema_version": 1,
                    "duration_s": view.get("duration_s", 0.0),
                    "interval_s": view.get("interval_s", 0.0),
                    "ts": view.get("ts", 0.0),
                    "buckets": view.get("buckets", 0),
                    "counters": {}, "gauges": {}, "_sketches": {}}
            tgt["buckets"] = max(tgt["buckets"], view.get("buckets", 0))
            tgt["ts"] = max(tgt["ts"], view.get("ts", 0.0))
            for k, row in (view.get("counters") or {}).items():
                cur = tgt["counters"].setdefault(
                    k, {"delta": 0.0, "rate": 0.0})
                cur["delta"] += row.get("delta", 0.0)
                cur["rate"] += row.get("rate", 0.0)
            for k, row in (view.get("gauges") or {}).items():
                cur = tgt["gauges"].get(k)
                if cur is None:
                    tgt["gauges"][k] = dict(row)
                else:
                    # Fleet gauge semantics: "last" SUMS (fleet queue
                    # depth = every member's), min/max envelope.
                    cur["last"] = cur.get("last", 0.0) + row.get(
                        "last", 0.0)
                    cur["min"] = min(cur.get("min", 0.0),
                                     row.get("min", 0.0))
                    cur["max"] = max(cur.get("max", 0.0),
                                     row.get("max", 0.0))
            for k, h in (view.get("histograms") or {}).items():
                sk_obj = h.get("sketch") if isinstance(h, dict) else None
                if not isinstance(sk_obj, dict):
                    continue
                sk = LogSketch.from_dict(sk_obj)
                cur = tgt["_sketches"].get(k)
                if cur is None:
                    tgt["_sketches"][k] = sk
                else:
                    cur.merge(sk)
    for view in merged_windows.values():
        hists = {}
        for k, sk in view.pop("_sketches").items():
            h = sk.summary()
            h["sketch"] = sk.to_dict()
            hists[k] = h
        view["histograms"] = hists
    return {"window_schema_version": 1, "ts": time.time(),
            "members": members, "deduped": deduped,
            "windows": merged_windows}


# ------------------------------------------- Prometheus-text exposition
#: Pinned exposition conventions (analysis/telemetry_schema.py
#: re-exports and validates them): every sample name carries the
#: prefix; windowed samples are labeled with one of WINDOW_LABELS.
EXPOSITION_PREFIX = "nezha_"
WINDOW_LABELS = tuple(f"{d}s" for d in WINDOW_DURATIONS)
QUANTILE_LABELS = ("p50", "p90", "p99")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(-?[0-9.eE+]+"
    r"|[+-]?Inf|NaN)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def prom_name(name: str) -> str:
    """Instrument name -> exposition sample name (``serve.ttft_s`` ->
    ``nezha_serve_ttft_s``)."""
    return EXPOSITION_PREFIX + _NAME_RE.sub("_", name)


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(stats: Optional[dict],
                      windows: Optional[dict] = None,
                      extra_labels: Optional[Dict[str, str]] = None
                      ) -> str:
    """Render one registry's cumulative stats (the ``/stats`` shape —
    or the router's deduped fleet section) plus its window views
    (``windows_payload()`` / a fleet merge) as Prometheus text.

    Cumulative counters/gauges render unlabeled; window views render
    window-labeled rates (``<name>_rate{window="60s"}``), gauge
    last/min/max, and sketch quantiles
    (``<name>{window="60s",quantile="p99"}``)."""
    base = dict(extra_labels or {})

    def labels(**kw) -> str:
        merged = {**base, **kw}
        if not merged:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in sorted(merged.items()))
        return "{" + inner + "}"

    lines: List[str] = []
    if stats:
        ctr = stats.get("counters") or {}
        # Fleet KV reuse at-a-glance (PR 17): a comment line — both
        # parse_prometheus and the schema validator skip '#' lines, so
        # this is scrape-invisible but human-greppable on /metrics.
        if "serve.kv.fleet_hits_total" in ctr:
            lines.append(
                "# fleet kv: "
                f"{_fmt(ctr['serve.kv.fleet_hits_total'])} hits "
                f"(device {_fmt(ctr.get('serve.kv.fleet_hits_device_total', 0))}"
                f" / host {_fmt(ctr.get('serve.kv.fleet_hits_host_total', 0))}"
                f" / peer {_fmt(ctr.get('serve.kv.fleet_hits_peer_total', 0))}), "
                f"{_fmt(ctr.get('serve.kv.pull_bytes', 0))} bytes pulled")
        for k in sorted(stats.get("counters") or {}):
            n = prom_name(k)
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n}{labels()} "
                         f"{_fmt(stats['counters'][k])}")
        for k in sorted(stats.get("gauges") or {}):
            n = prom_name(k)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n}{labels()} {_fmt(stats['gauges'][k])}")
    for label in sorted((windows or {}).get("windows") or {},
                        key=lambda s: (len(s), s)):
        view = windows["windows"][label]
        if not isinstance(view, dict):
            continue
        for k in sorted(view.get("counters") or {}):
            row = view["counters"][k]
            n = prom_name(k)
            lines.append(f"{n}_rate{labels(window=label)} "
                         f"{_fmt(row.get('rate', 0.0))}")
        for k in sorted(view.get("gauges") or {}):
            row = view["gauges"][k]
            n = prom_name(k)
            for stat in ("last", "min", "max"):
                lines.append(f"{n}_{stat}{labels(window=label)} "
                             f"{_fmt(row.get(stat, 0.0))}")
        for k in sorted(view.get("histograms") or {}):
            h = view["histograms"][k]
            n = prom_name(k)
            for q in QUANTILE_LABELS:
                lines.append(
                    f"{n}{labels(window=label, quantile=q)} "
                    f"{_fmt(h.get(q, 0.0))}")
            lines.append(f"{n}_count{labels(window=label)} "
                         f"{_fmt(h.get('count', 0))}")
            lines.append(f"{n}_sum{labels(window=label)} "
                         f"{_fmt(h.get('sum', 0.0))}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str
                     ) -> List[Tuple[str, Dict[str, str], float]]:
    """Prometheus text -> ``[(name, labels, value), ...]`` — the
    ``nezha-top`` / test-side reader (comments skipped, malformed lines
    dropped; the schema validator is the strict reader)."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, raw_labels, value = m.group(1), m.group(2), m.group(3)
        labels = dict(_LABEL_RE.findall(raw_labels)) if raw_labels else {}
        try:
            out.append((name, labels, float(value)))
        except ValueError:
            continue
    return out


def metric_value(samples: List[Tuple[str, Dict[str, str], float]],
                 name: str, **want: str) -> Optional[float]:
    """First sample matching ``name`` whose labels contain ``want``."""
    for n, labels, v in samples:
        if n == name and all(labels.get(k) == w for k, w in want.items()):
            return v
    return None


# ---------------------------------------------- process-wide installation
def install_windows(interval_s: float = 10.0,
                    retention_s: float = 300.0,
                    clock: Callable[[], float] = time.time,
                    gamma: float = DEFAULT_GAMMA) -> WindowStore:
    """Install a :class:`WindowStore` as the process-wide window tap:
    every instrument write while telemetry is enabled also records into
    the store's current bucket. ``start_run`` installs one by default;
    the capture-only baseline (bench) and tests pass knobs explicitly.
    Replaces any previously installed store."""
    store = WindowStore(interval_s=interval_s, retention_s=retention_s,
                        clock=clock, gamma=gamma)
    _registry._state.windows = store
    return store


def uninstall_windows() -> None:
    _registry._state.windows = None


def current_windows() -> Optional[WindowStore]:
    return _registry._state.windows


def windows_payload(registry: Optional["_registry.Registry"] = None,
                    durations: Iterable[float] = WINDOW_DURATIONS
                    ) -> dict:
    """The JSON window views a front end serves at ``GET /windows`` —
    the mergeable form (sketch bucket counts ride along) the router
    scrapes to build the fleet ``/metrics`` roll-up. ``registry_id``
    lets the fleet merge dedupe thread-backend members that share one
    process registry."""
    reg = registry if registry is not None else _registry.REGISTRY
    return {"window_schema_version": 1, "ts": time.time(),
            "registry_id": reg.registry_id,
            "windows": {f"{int(d)}s": reg.windows(d) for d in durations}}
