"""Anomaly watchdog: window views -> typed ``events.jsonl`` records.

A :class:`Watchdog` runs one ``check()`` per interval (a daemon thread
in ``nezha-serve``, wired by ``--watchdog-interval``/``--slo``) and
turns raw windows into the typed event stream later scheduling /
autoscaling PRs consume (ROADMAP open item 2). Rules, each pinned as an
event kind in analysis/telemetry_schema.py:

==============================  =======================================
``watchdog.queue_depth_sustained``  ``serve.queue_depth`` min over the
                                    window >= limit — the queue never
                                    drained for a full window.
``watchdog.ttft_regression``        windowed ``serve.ttft_s`` p99 vs
                                    the TRAILING baseline (the older
                                    300s view, current window excluded)
                                    exceeds the regression factor.
``watchdog.replica_flap``           ``router.replica_restarts_total``
                                    delta over the window >= limit.
``watchdog.slo_burn``               an :class:`~nezha_tpu.obs.slo.
                                    SLOTracker` burn rate >= the alert
                                    threshold.
``slo.eval``                        one info record per SLO evaluation
                                    (the offline compliance stream
                                    ``nezha-telemetry --slo`` renders).
==============================  =======================================

Alert-kind rules fire on the RISING EDGE (condition false -> true) and
re-arm only after the condition clears, so a sustained incident is one
event, not one per check. Every check also maintains the pinned
``watchdog.*``/``slo.*`` instruments (checks/events counters, max burn
rate gauge) so the watchdog's own behavior is visible in ``/metrics``.
"""

from __future__ import annotations

import threading
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Sequence

from nezha_tpu.obs import registry as _registry
from nezha_tpu.obs.slo import SLOConfig, SLOTracker, evaluate_slo


@dataclass(frozen=True)
class WatchdogConfig:
    """Threshold/trend knobs (see RUNBOOK "Monitoring & SLOs")."""

    interval_s: float = 10.0          # check cadence
    window_s: float = 60.0            # rule evaluation window
    baseline_window_s: float = 300.0  # trailing-baseline span
    queue_depth_limit: float = 16.0   # sustained-queue threshold
    ttft_regression_factor: float = 2.0   # current p99 vs baseline p99
    min_samples: int = 8              # TTFT counts below this: no verdict
    flap_limit: float = 3.0           # replica restarts per window
    burn_alert: float = 2.0           # SLOTracker.burn_rate() threshold


class Watchdog:
    """Evaluates the rule set against one registry. ``check()`` is
    called from a single timer thread; state (edge triggers, SLO
    trackers) is unlocked single-consumer."""

    def __init__(self, registry: Optional[_registry.Registry] = None,
                 slos: Sequence[SLOConfig] = (),
                 config: Optional[WatchdogConfig] = None):
        self.registry = registry if registry is not None \
            else _registry.REGISTRY
        self.config = config or WatchdogConfig()
        self.trackers: Dict[str, SLOTracker] = {
            cfg.name: SLOTracker(cfg) for cfg in slos}
        self._firing: Dict[str, bool] = {}   # rule key -> edge state

    # ------------------------------------------------------------ rules
    def _edge(self, key: str, condition: bool) -> bool:
        """True exactly when ``condition`` newly holds (rising edge)."""
        was = self._firing.get(key, False)
        self._firing[key] = condition
        return condition and not was

    def _emit(self, events: List[dict], kind: str, severity: str,
              source: str, **detail) -> None:
        rec = self.registry.record_event(kind, severity=severity,
                                         source=source, **detail)
        self.registry.counter("watchdog.events_total").inc()
        if rec is not None:
            events.append(rec)

    def check(self) -> List[dict]:
        """Run every rule once; returns the events emitted by THIS
        check (they are already recorded/streamed)."""
        cfg = self.config
        reg = self.registry
        reg.counter("watchdog.checks_total").inc()
        events: List[dict] = []
        view = reg.windows(cfg.window_s)

        # Sustained queue depth: min over the window never dipped below
        # the limit — admission is outrunning service for a full window.
        g = (view.get("gauges") or {}).get("serve.queue_depth")
        sustained = (g is not None
                     and g.get("min", 0.0) >= cfg.queue_depth_limit)
        if self._edge("queue_depth", sustained):
            self._emit(events, "watchdog.queue_depth_sustained",
                       "warning", "watchdog",
                       window_s=cfg.window_s,
                       min_depth=g.get("min"), max_depth=g.get("max"),
                       limit=cfg.queue_depth_limit)

        # TTFT regression vs trailing baseline: compare the current
        # window's p99 against the older history with the current
        # window EXCLUDED, so the regression can't dilute its own
        # baseline.
        interval = view.get("interval_s") or 0.0
        skip = int(cfg.window_s / interval + 0.999) if interval > 0 else 0
        baseline = reg.windows(cfg.baseline_window_s, skip=skip)
        cur = (view.get("histograms") or {}).get("serve.ttft_s")
        base = (baseline.get("histograms") or {}).get("serve.ttft_s")
        regressed = False
        if (cur is not None and base is not None
                and cur.get("count", 0) >= cfg.min_samples
                and base.get("count", 0) >= cfg.min_samples
                and base.get("p99", 0.0) > 0.0):
            regressed = (cur["p99"]
                         >= cfg.ttft_regression_factor * base["p99"])
        if self._edge("ttft_regression", regressed):
            self._emit(events, "watchdog.ttft_regression", "critical",
                       "watchdog", window_s=cfg.window_s,
                       current_p99=cur.get("p99"),
                       baseline_p99=base.get("p99"),
                       factor=cfg.ttft_regression_factor)

        # Replica flap: restarts within one window (router registries
        # only — elsewhere the counter simply never appears).
        c = (view.get("counters") or {}).get(
            "router.replica_restarts_total")
        flapping = (c is not None
                    and c.get("delta", 0.0) >= cfg.flap_limit)
        if self._edge("replica_flap", flapping):
            self._emit(events, "watchdog.replica_flap", "critical",
                       "watchdog", window_s=cfg.window_s,
                       restarts=c.get("delta"), limit=cfg.flap_limit)

        # SLO evaluations + burn-rate alerts.
        burn_max = 0.0
        for tracker in self.trackers.values():
            scfg = tracker.cfg
            verdict = evaluate_slo(scfg, reg.windows(scfg.window_s))
            reg.counter("slo.evaluations_total").inc()
            if not verdict["no_data"]:
                tracker.observe(verdict["ok"])
                if not verdict["ok"]:
                    reg.counter("slo.violations_total").inc()
            burn = tracker.burn_rate()
            burn_max = max(burn_max, burn)
            self._emit(events, "slo.eval",
                       "info" if verdict["ok"] else "warning", "slo",
                       burn_rate=burn, compliance=tracker.compliance,
                       **verdict)
            if self._edge(f"burn:{scfg.name}",
                          tracker.total > 0 and burn >= cfg.burn_alert):
                self._emit(events, "watchdog.slo_burn", "critical",
                           "slo", slo=scfg.name, burn_rate=burn,
                           compliance=tracker.compliance,
                           objective=scfg.objective,
                           limit=cfg.burn_alert)
        if self.trackers:
            reg.gauge("slo.burn_rate_max").set(burn_max)
        return events

    def status(self) -> dict:
        return {"config": asdict(self.config),
                "slos": [t.status() for t in self.trackers.values()],
                "firing": sorted(k for k, v in self._firing.items()
                                 if v)}


class WatchdogThread:
    """Daemon timer driving ``Watchdog.check()`` every interval — the
    serve-side wiring (``nezha-serve --watchdog-interval``). ``stop()``
    is idempotent and joins the thread."""

    def __init__(self, watchdog: Watchdog,
                 interval_s: Optional[float] = None):
        self.watchdog = watchdog
        self.interval_s = float(interval_s
                                if interval_s is not None
                                else watchdog.config.interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="nezha-watchdog", daemon=True)

    def start(self) -> "WatchdogThread":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.watchdog.check()
            except Exception:
                # A watchdog bug must never take the serving loop down;
                # the failed check is skipped and the next tick retries.
                self.watchdog.registry.counter(
                    "watchdog.check_errors_total").inc()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
