"""Run-report rendering for ``nezha-telemetry``.

Reads the three run-dir artifacts the sink writes (metrics.jsonl,
spans.jsonl, summary.json — any subset may be missing for a crashed run)
and renders the operator's first-read view: step-rate percentiles,
per-chip throughput, the per-collective payload/bandwidth table, compile-
cache behavior, and the slowest spans. Pure stdlib + the JSONL reader, so
the report works on any machine the run dir is copied to.

This module also owns DISTRIBUTED-TRACE stitching (``--trace``): walk a
run dir plus the per-replica subdirectories a multi-replica serve run
writes, group every replica's span fragments by their ``trace_id``, and
rebuild each request's cross-fleet timeline — the TTFT decomposition
over :data:`TRACE_SEGMENTS` whose pieces tile the measured TTFT exactly,
plus partial-trace accounting for requests whose fragments a killed
replica took with it.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from nezha_tpu.obs.metrics import read_metrics
from nezha_tpu.obs.registry import (UNFOLDED_METRIC_KEYS, percentile_of,
                                    values_summary)
from nezha_tpu.obs.sink import (EVENTS_FILE, METRICS_FILE, SPANS_FILE,
                                SUMMARY_FILE)


def load_run(run_dir: str) -> dict:
    """-> {"metrics": [...], "spans": [...], "summary": dict|None}."""
    out: Dict[str, Any] = {"metrics": [], "spans": [], "summary": None}
    mpath = os.path.join(run_dir, METRICS_FILE)
    if os.path.isfile(mpath):
        out["metrics"] = read_metrics(mpath)
    spath = os.path.join(run_dir, SPANS_FILE)
    if os.path.isfile(spath):
        out["spans"] = read_metrics(spath)  # same JSONL shape
    jpath = os.path.join(run_dir, SUMMARY_FILE)
    if os.path.isfile(jpath):
        with open(jpath) as f:
            out["summary"] = json.load(f)
    return out


def summarize_streams(metrics: List[dict], spans: List[dict]) -> dict:
    """Best-effort summary for a run that died before ``end_run()`` wrote
    summary.json: numeric metric histograms and span aggregates recomputed
    from the JSONL streams. Counter-backed sections (collectives, compile
    cache) lived only in the process registry and cannot be recovered, so
    they are absent; ``recomputed`` marks the dict as this partial form."""
    series: Dict[str, List[float]] = {}
    for m in metrics:
        for k, v in m.items():
            if (k not in UNFOLDED_METRIC_KEYS
                    and isinstance(v, (int, float))
                    and not isinstance(v, bool)):
                series.setdefault(f"metric.{k}", []).append(float(v))

    slowest = sorted(spans, key=lambda sp: -sp.get("dur_s", 0.0))[:10]
    return {"schema_version": 1, "recomputed": True,
            "histograms": {k: values_summary(v)
                           for k, v in series.items()},
            "num_spans": len(spans), "slowest_spans": slowest}


def _percentiles(values: List[float]) -> Optional[dict]:
    if not values:
        return None
    s = sorted(values)
    return {"n": len(s), "mean": sum(s) / len(s), "min": s[0],
            "p10": percentile_of(s, 10), "p50": percentile_of(s, 50),
            "p90": percentile_of(s, 90), "max": s[-1]}


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def render_serving_section(summary: Optional[dict]) -> List[str]:
    """The serving block (present only for serve/benchmark runs —
    detected by the pre-registered ``serve.*`` instruments): request
    counters, TTFT/TPOT percentiles, throughput, batch occupancy."""
    if not summary:
        return []
    counters = summary.get("counters", {})
    if "serve.admitted_total" not in counters:
        return []
    gauges = summary.get("gauges", {})
    hists = summary.get("histograms", {})
    lines = ["serving:"]
    lines.append(
        "  requests: "
        f"{counters.get('serve.admitted_total', 0)} admitted  "
        f"{counters.get('serve.rejected_total', 0)} rejected  "
        f"{counters.get('serve.expired_total', 0)} expired  "
        f"{counters.get('serve.retired_total', 0)} retired")
    if "serve.errors_total" in counters:
        # Resilience accounting (absent only in pre-PR-4 captures):
        # errored requests, bounded step retries, and how many faults
        # the chaos plan injected (0 on a clean run).
        lines.append(
            "  errors: "
            f"{counters.get('serve.errors_total', 0):.0f} errored  "
            f"{counters.get('serve.step_retries_total', 0):.0f} "
            f"step retries  "
            f"{counters.get('faults.injected_total', 0):.0f} "
            f"faults injected")
    for key, label in (("serve.ttft_s", "ttft"), ("serve.tpot_s", "tpot")):
        h = hists.get(key)
        if h and h.get("count"):
            lines.append(
                f"  {label}: p50 {h['p50'] * 1e3:.1f} ms  "
                f"p90 {h['p90'] * 1e3:.1f} ms  "
                f"p99 {h['p99'] * 1e3:.1f} ms  (n={h['count']})")
    # Per-priority-class TTFT split (PR 19): rendered only for classes
    # that saw traffic, and only when MORE than one class did — a
    # single-class run (the default wire) collapses to the line above.
    split = [(p, hists.get(f"serve.ttft_s.{p}"))
             for p in ("interactive", "batch", "background")]
    split = [(p, h) for p, h in split if h and h.get("count")]
    if len(split) > 1:
        for p, h in split:
            lines.append(
                f"    ttft[{p}]: p50 {h['p50'] * 1e3:.1f} ms  "
                f"p90 {h['p90'] * 1e3:.1f} ms  "
                f"p99 {h['p99'] * 1e3:.1f} ms  (n={h['count']})")
    if counters.get("serve.preemptions_total") or counters.get(
            "serve.tenant_over_limit_total"):
        # Multi-tenant scheduling view (PR 19): suspends/resumes and
        # typed per-tenant sheds — all 0 (line absent) on FIFO runs.
        lines.append(
            "  preemption: "
            f"{counters.get('serve.preemptions_total', 0):.0f} "
            f"preempted  "
            f"{counters.get('serve.resumes_total', 0):.0f} resumed  "
            f"{counters.get('serve.tenant_over_limit_total', 0):.0f} "
            f"tenant-capped")
    hg = hists.get("serve.host_gap_s")
    if hg and hg.get("count"):
        # The decode-horizon view: host time between consecutive step
        # dispatches (the overhead a horizon > 1 amortizes over H
        # tokens) and the tokens-per-dispatch ceiling the blocks ran at
        # (absent in pre-horizon captures).
        dh = hists.get("serve.decode.horizon") or {}
        hz = (f"  horizon p50 {dh['p50']:.0f}"
              if dh.get("count") else "")
        lines.append(
            f"  host gap: p50 {hg['p50'] * 1e3:.2f} ms  "
            f"p90 {hg['p90'] * 1e3:.2f} ms  "
            f"p99 {hg['p99'] * 1e3:.2f} ms  (n={hg['count']}){hz}")
    if "serve.kv.prefix_hits_total" in counters:
        # Paged-KV view (absent only in pre-paged captures): the KV
        # storage dtype (from the quant_bits gauge; absent in
        # pre-quantization captures), blocks + bytes resident at run
        # end, prefix-cache hits (requests that took block references
        # instead of re-prefilling), copy-on-write block copies, and —
        # on int8 runs — the sampled per-block dequant error p99.
        bits = gauges.get("serve.kv.quant_bits")
        dtype = {8: "int8", 16: "bf16", 32: "f32"}.get(
            int(bits) if bits else 0)
        parts = ["  kv: "]
        if dtype:
            parts.append(f"dtype {dtype}  ")
        parts.append(
            f"{gauges.get('serve.kv.blocks_used', 0):.0f} blocks "
            f"resident")
        if "serve.kv.bytes_resident" in gauges:
            parts.append(
                f" ({gauges['serve.kv.bytes_resident'] / 1024:.1f} "
                f"KiB)")
        parts.append(
            f"  "
            f"{counters.get('serve.kv.prefix_hits_total', 0):.0f} "
            f"prefix hits  "
            f"{counters.get('serve.kv.cow_copies_total', 0):.0f} "
            f"cow copies")
        qe = hists.get("serve.kv.quant_error")
        if qe and qe.get("count"):
            parts.append(f"  quant err p99 {qe['p99']:.2e}")
        lines.append("".join(parts))
        demoted = counters.get("serve.kv.demotions_total", 0)
        promoted = counters.get("serve.kv.promotions_total", 0)
        host_used = gauges.get("serve.kv.host_blocks_used", 0)
        if demoted or promoted or host_used:
            # Host spill tier (absent when kv_host_blocks is 0 or the
            # run never churned): blocks currently parked in host RAM,
            # and the demote/promote traffic — a healthy churn load
            # shows promotions tracking demotions (returning users hit
            # the tier) rather than demotions alone (a write-only
            # spill buys nothing).
            lines.append(
                f"  kv host tier: {host_used:.0f} blocks resident "
                f"({gauges.get('serve.kv.host_bytes_resident', 0) / 1024:.1f} "
                f"KiB)  {demoted:.0f} demoted  {promoted:.0f} promoted")
        fleet = counters.get("serve.kv.fleet_hits_total", 0)
        pulled = counters.get("serve.kv.pull_bytes", 0)
        if fleet or pulled:
            # Fleet-wide KV reuse (PR 17; absent on single-replica /
            # affinity-off runs which report 0s): the three-tier hit
            # split — a healthy affinity fleet shows device hits
            # dominating (the scorer landed revisits on their owner)
            # with peer hits covering owner churn/saturation.
            lines.append(
                f"  fleet kv: {fleet:.0f} hits (device "
                f"{counters.get('serve.kv.fleet_hits_device_total', 0):.0f}"
                f" / host "
                f"{counters.get('serve.kv.fleet_hits_host_total', 0):.0f}"
                f" / peer "
                f"{counters.get('serve.kv.fleet_hits_peer_total', 0):.0f})"
                f"  {pulled / 1024:.1f} KiB pulled")
    mesh = gauges.get("serve.mesh.devices", 0)
    if mesh and mesh >= 2:
        # Tensor-sharded serving (absent on single-device runs): mesh
        # size, the per-shard share of resident KV, and the trace-shape
        # collective-payload estimate the mesh moved.
        parts = [f"  mesh: {mesh:.0f} devices (head-sharded KV)"]
        if "serve.kv.bytes_resident" in gauges:
            per_shard = gauges["serve.kv.bytes_resident"] / mesh / 1024
            parts.append(f"  {per_shard:.1f} KiB/shard resident")
        cb = counters.get("serve.mesh.collective_bytes", 0)
        if cb:
            parts.append(f"  collectives ~{cb / 2**20:.2f} MiB "
                         f"(trace-shape est.)")
        lines.append("".join(parts))
    al = hists.get("serve.spec.accepted_len")
    if al and al.get("count"):
        # Speculative decoding (absent when the knob is off — the
        # histogram only fills on speculative runs): accepted-prefix
        # length percentiles per verify window, the realized accept
        # rate (accepted / proposed draft tokens), and the headline
        # tokens-per-verify (accepted-len p50 + 1 for the t0 column).
        drafted = counters.get("serve.spec.draft_tokens_total", 0)
        accepted = counters.get("serve.spec.accepted_total", 0)
        rate = accepted / drafted if drafted else 0.0
        lines.append(
            f"  speculation: accept-rate p50 {al['p50']:.0f}"
            f"/{drafted / al['count']:.0f} drafts  "
            f"({rate:.0%} of {drafted:.0f} proposed)  "
            f"tokens/verify {al['mean'] + 1:.2f}")
    ph = hists.get("serve.prefill.bucket_len")
    if ph and ph.get("count"):
        # Bucket occupancy: how wide the static prefill programs
        # actually ran (p50/max widths + chunk count — a max stuck at
        # the top bucket under short-prompt traffic means the bucket set
        # is too coarse).
        chunks = counters.get("serve.prefill.chunks_total", ph["count"])
        # Active prefill impl (PR 18): the engine pins the gauge to 1
        # when chunks dispatch through the Pallas flash-prefill kernel;
        # an int8 pool additionally counts the per-layer block writes
        # the kernel epilogue fused in place of the gather/requant
        # round-trip.
        impl = ("kernel"
                if gauges.get("serve.prefill.kernel_active") else "xla")
        # Sequence-sharded prefill (PR 20): the seq_shards gauge is M
        # when chunks shard over the mesh's sequence axis, 0 in
        # replicated mode — the report labels the line's parallelism
        # mode from it alone (ring hops additionally show the
        # ppermute-variant traffic).
        shards = gauges.get("serve.prefill.seq_shards", 0)
        mode = f"seq x{shards:.0f}" if shards else "replicated"
        fused = counters.get("serve.prefill.fused_writes_total", 0)
        fused_part = f"  fused writes {fused:.0f}" if fused else ""
        hops = counters.get("serve.prefill.ring_hops_total", 0)
        hops_part = f"  ring hops {hops:.0f}" if hops else ""
        lines.append(
            f"  prefill[{impl}, {mode}]: {chunks:.0f} chunk(s)  "
            f"bucket len p50 {ph['p50']:.0f}  p90 {ph['p90']:.0f}  "
            f"max {ph['max']:.0f}{fused_part}{hops_part}")
    tokens = counters.get("serve.tokens_total", 0)
    wall = (summary.get("run") or {}).get("wall_seconds")
    if tokens and wall:
        lines.append(f"  throughput: {tokens} tokens in {wall:.1f}s "
                     f"({tokens / wall:.1f} tok/s)")
    elif tokens:
        lines.append(f"  throughput: {tokens} tokens")
    occ = gauges.get("serve.batch_occupancy")
    occ_h = hists.get("metric.batch_occupancy")
    if occ_h and occ_h.get("count"):
        lines.append(f"  batch occupancy: mean {occ_h['mean']:.2f}  "
                     f"p50 {occ_h['p50']:.2f}  max {occ_h['max']:.2f}")
    elif occ is not None:
        lines.append(f"  batch occupancy: {occ:.2f} (final)  "
                     f"queue depth: {gauges.get('serve.queue_depth', 0):.0f}")
    return lines


def render_replicas_section(summary: Optional[dict]) -> List[str]:
    """The multi-replica block (present only for router runs —
    detected by the pre-registered ``router.*`` instruments): live
    replica count, restart/failover/retry ledger, and route-latency
    percentiles."""
    if not summary:
        return []
    counters = summary.get("counters", {})
    if "router.retries_total" not in counters:
        return []
    gauges = summary.get("gauges", {})
    hists = summary.get("histograms", {})
    lines = ["replicas:"]
    lines.append(
        f"  live: {gauges.get('router.replicas_live', 0):.0f} (final)  "
        f"{counters.get('router.replica_restarts_total', 0):.0f} "
        f"restarts  "
        f"{counters.get('router.failovers_total', 0):.0f} failovers  "
        f"{counters.get('router.retries_total', 0):.0f} retries")
    h = hists.get("router.route_s")
    if h and h.get("count"):
        lines.append(
            f"  route: p50 {h['p50'] * 1e3:.1f} ms  "
            f"p90 {h['p90'] * 1e3:.1f} ms  "
            f"p99 {h['p99'] * 1e3:.1f} ms  (n={h['count']})")
    # Fleet-wide KV reuse (PR 17): affinity overrides of the least-
    # loaded pick (present only when the scorer actually won any).
    aff = counters.get("router.affinity_wins_total", 0)
    if aff:
        lines.append(f"  affinity: {aff:.0f} wins over least-loaded")
    # Disaggregated tiers: migration volume and the per-tier queueing
    # split (present only when the run actually migrated / split).
    mig = counters.get("serve.kv.migrations_total", 0)
    if mig:
        lines.append(
            f"  migration: {mig:.0f} pulls  "
            f"{counters.get('serve.kv.migration_bytes', 0) / 2**20:.2f} "
            f"MiB moved  "
            f"{counters.get('router.migrate_fallbacks_total', 0):.0f} "
            f"fallbacks")
    pw, dw = (hists.get("router.prefill_wait_s"),
              hists.get("router.decode_wait_s"))
    if pw and pw.get("count") and dw and dw.get("count"):
        lines.append(
            f"  queue split: prefill wait p50 {pw['p50'] * 1e3:.1f} ms  "
            f"decode wait p50 {dw['p50'] * 1e3:.1f} ms")
    return lines


# ------------------------------------------------- distributed traces
# The stitched-timeline segments of the TTFT decomposition, in wall
# order. Each is the interval between two consecutive milestones of a
# request's cross-replica lifecycle, so for a complete trace they TILE
# [router arrival, first token] exactly — the segment sum IS the
# end-to-end TTFT (tests pin this).
TRACE_SEGMENTS = ("router_queue", "prefill_wait", "prefill_compute",
                  "migration_transfer", "decode_wait", "first_token")


def load_fleet_spans(run_dir: str) -> List[dict]:
    """Every span record reachable from ``run_dir`` — its own
    spans.jsonl plus any immediate subdirectory's (the per-replica
    ``replica<N>/`` layout ``nezha-serve --replicas --run-dir`` writes,
    and the per-horizon ``h<N>/`` layout of bench sweeps) — each tagged
    with its source directory under ``_src`` so stitched timelines can
    say which replica a fragment came from."""
    sources = [(".", run_dir)]
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        names = []
    for name in names:
        sub = os.path.join(run_dir, name)
        if os.path.isdir(sub):
            sources.append((name, sub))
    out: List[dict] = []
    for src, d in sources:
        path = os.path.join(d, SPANS_FILE)
        if not os.path.isfile(path):
            continue
        for rec in read_metrics(path):
            if isinstance(rec, dict):
                rec = dict(rec)
                rec["_src"] = src
                out.append(rec)
    return out


def stitch_traces(spans: List[dict]) -> Dict[str, List[dict]]:
    """Group span fragments by ``trace_id`` (records without one are
    not part of any request timeline), each trace's fragments sorted by
    start time — all fragments carry epoch wall clocks, so one host's
    replicas order correctly across processes."""
    traces: Dict[str, List[dict]] = {}
    for rec in spans:
        tid = rec.get("trace_id")
        if isinstance(tid, str) and tid:
            traces.setdefault(tid, []).append(rec)
    for frags in traces.values():
        frags.sort(key=lambda r: (r.get("t0", 0.0), r.get("t1", 0.0)))
    return traces


def trace_timeline(trace_id: str, frags: List[dict]) -> dict:
    """One stitched per-request timeline: the TTFT decomposition
    (:data:`TRACE_SEGMENTS`) computed from the trace's milestone
    boundaries. Milestones are clamped monotone, so for a ``complete``
    timeline ``sum(segments) == ttft_s`` EXACTLY — no gap hides between
    segments. A trace missing milestones (killed replica mid-migration,
    request still in flight at capture end, expired in queue) comes
    back ``complete=False`` with the absent pieces named in
    ``missing`` — partial timelines render, they just don't decompose.
    """
    by_name: Dict[str, List[dict]] = {}
    for f in frags:
        by_name.setdefault(str(f.get("name")), []).append(f)

    def attrs_of(f) -> dict:
        a = f.get("attrs")
        return a if isinstance(a, dict) else {}

    root = (by_name.get("router.request") or [None])[0]
    qws = by_name.get("serve.queue_wait", [])
    prefills = by_name.get("serve.prefill", [])
    # Only SUCCESSFUL installs count as a migration: a failed pull
    # (source lost mid-transfer, kv blocks exhausted) records its
    # serve.kv_install fragment with an ``error`` attr and the router
    # degrades — retry on another replica or local decode on the
    # source. Counting it would report migrated=true with a positive
    # transfer segment for a migration that never delivered, masking
    # exactly the degradation this report exists to surface.
    pulls = [p for p in by_name.get("serve.kv_install", [])
             if "error" not in attrs_of(p)]
    # The LAST decode fragment wins: a resumed (local-decode fallback)
    # request parks one aborted residency behind the real one.
    decodes = by_name.get("serve.decode", [])
    decode = decodes[-1] if decodes else None

    request_id = None
    for f in frags:
        rid = attrs_of(f).get("request_id")
        if rid:
            request_id = rid
            break

    qw0 = qws[0] if qws else None
    pull_t0 = pulls[0].get("t0") if pulls else None
    pre = [p for p in prefills
           if pull_t0 is None or p.get("t0", 0.0) <= pull_t0]
    first_token = attrs_of(decode).get("first_token") if decode else None

    milestones = [
        ("router.request", root.get("t0") if root else
         (qw0.get("t0") if qw0 else None)),
        ("serve.queue_wait", qw0.get("t0") if qw0 else None),
        ("admitted", qw0.get("t1") if qw0 else None),
        ("prefill done", max((p.get("t1", 0.0) for p in pre),
                             default=None) if pre else None),
        ("migration done", max((p.get("t1", 0.0) for p in pulls),
                               default=None) if pulls
         else (max((p.get("t1", 0.0) for p in pre), default=None)
               if pre else None)),
        ("serve.decode", decode.get("t0") if decode else None),
        ("first token", float(first_token)
         if first_token is not None else None),
    ]
    missing = [name for name, t in milestones if t is None]
    out = {
        "trace_id": trace_id,
        "request_id": request_id,
        "fragments": len(frags),
        "span_names": sorted(by_name),
        "replicas": sorted({str(f.get("_src", ".")) for f in frags}),
        "complete": not missing,
        "missing": missing,
        "migrated": bool(pulls),
        "t0": milestones[0][1],
    }
    if decode is not None:
        a = attrs_of(decode)
        out["finish_reason"] = a.get("finish_reason")
        out["tokens"] = a.get("tokens")
    if missing:
        return out
    # Clamp monotone, then difference: consecutive intervals tile
    # [arrival, first token], so the segment sum equals ttft_s exactly.
    times = []
    run = None
    for _, t in milestones:
        run = t if run is None else max(run, t)
        times.append(run)
    out["segments"] = {seg: times[i + 1] - times[i]
                       for i, seg in enumerate(TRACE_SEGMENTS)}
    out["ttft_s"] = times[-1] - times[0]
    return out


def stitch_run_dir(run_dir: str) -> List[dict]:
    """-> every stitched timeline of a (possibly multi-replica) run
    dir, slowest-complete first, partial timelines at the tail."""
    traces = stitch_traces(load_fleet_spans(run_dir))
    timelines = [trace_timeline(tid, frags)
                 for tid, frags in traces.items()]
    timelines.sort(key=lambda t: (not t["complete"],
                                  -(t.get("ttft_s") or 0.0)))
    return timelines


def trace_summary(run_dir: str) -> Optional[dict]:
    """The per-segment percentile record of a run's stitched traces —
    what ``benchmarks/serving.py`` embeds as the record's ``trace``
    block so ``nezha-bench`` can gate each piece of the TTFT
    decomposition, not just the total. None when the run produced no
    traces at all."""
    timelines = stitch_run_dir(run_dir)
    if not timelines:
        return None
    complete = [t for t in timelines if t["complete"]]
    out = {"count": len(timelines), "complete": len(complete),
           "partial": len(timelines) - len(complete)}

    def pcts(vals: List[float]) -> dict:
        s = sorted(vals)
        return {"n": len(s), "p50": percentile_of(s, 50),
                "p90": percentile_of(s, 90),
                "p99": percentile_of(s, 99)}

    if complete:
        out["ttft_s"] = pcts([t["ttft_s"] for t in complete])
        out["segments"] = {
            seg: pcts([t["segments"][seg] for t in complete])
            for seg in TRACE_SEGMENTS}
    return out


def _critical_path(timeline: dict) -> str:
    segs = timeline.get("segments") or {}
    if not segs:
        return "-"
    seg, dur = max(segs.items(), key=lambda kv: kv[1])
    total = sum(segs.values())
    share = dur / total if total else 0.0
    return f"{seg} {share:.0%}"


def render_trace_report(run_dir: str, top: int = 10) -> str:
    """The ``nezha-telemetry RUN_DIR --trace`` view: the fleet's
    stitched per-request timelines — TTFT decomposition percentiles per
    segment, the slowest requests with critical-path attribution, and
    the partial traces (a killed replica mid-migration leaves exactly
    this shape) listed rather than silently dropped."""
    timelines = stitch_run_dir(run_dir)
    lines = [f"trace report: {os.path.abspath(run_dir)}"]
    if not timelines:
        lines.append("(no trace fragments found — was the run captured "
                     "with --run-dir and tracing not sampled out?)")
        return "\n".join(lines)
    complete = [t for t in timelines if t["complete"]]
    partial = [t for t in timelines if not t["complete"]]
    lines.append(f"traces: {len(timelines)} stitched "
                 f"({len(complete)} complete, {len(partial)} partial)")
    if complete:
        lines.append("")
        lines.append(f"ttft decomposition over {len(complete)} "
                     f"complete request(s):")
        lines.append(f"  {'segment':<20}{'p50 ms':>10}{'p90 ms':>10}"
                     f"{'p99 ms':>10}")
        seg_series = {seg: sorted(t["segments"][seg] for t in complete)
                      for seg in TRACE_SEGMENTS}
        for seg in TRACE_SEGMENTS:
            s = seg_series[seg]
            lines.append(
                f"  {seg:<20}"
                f"{percentile_of(s, 50) * 1e3:>10.1f}"
                f"{percentile_of(s, 90) * 1e3:>10.1f}"
                f"{percentile_of(s, 99) * 1e3:>10.1f}")
        totals = sorted(t["ttft_s"] for t in complete)
        lines.append(
            f"  {'total (ttft)':<20}"
            f"{percentile_of(totals, 50) * 1e3:>10.1f}"
            f"{percentile_of(totals, 90) * 1e3:>10.1f}"
            f"{percentile_of(totals, 99) * 1e3:>10.1f}")
        lines.append("")
        lines.append(f"slowest requests (top {min(top, len(complete))}):")
        lines.append(f"  {'ttft ms':>10}  {'request':<20}"
                     f"{'replicas':<20}  critical path")
        for t in complete[:top]:
            lines.append(
                f"  {t['ttft_s'] * 1e3:>10.1f}  "
                f"{str(t.get('request_id') or t['trace_id']):<20}"
                f"{','.join(t['replicas']):<20}  "
                f"{_critical_path(t)}")
    if partial:
        lines.append("")
        lines.append(f"partial traces ({len(partial)} — request still "
                     f"in flight at capture end, expired unadmitted, "
                     f"or a replica died holding its fragments):")
        for t in partial[:top]:
            lines.append(
                f"  {str(t.get('request_id') or t['trace_id']):<22}"
                f"{t['fragments']} fragment(s) from "
                f"{','.join(t['replicas'])}; missing "
                f"{', '.join(t['missing'])}")
    return "\n".join(lines)


def render_report(run_dir: str) -> str:
    """The full plain-text report for a run directory."""
    run = load_run(run_dir)
    metrics, spans, summary = run["metrics"], run["spans"], run["summary"]
    lines: List[str] = [f"telemetry report: {os.path.abspath(run_dir)}"]

    if summary and "run" in summary:
        meta = summary["run"]
        parts = [f"{k}={meta[k]}" for k in sorted(meta)
                 if k not in ("run_dir", "started_at")]
        if parts:
            lines.append("run: " + " ".join(parts))
    if not (metrics or spans or summary):
        lines.append("(no telemetry artifacts found — was the run started "
                     "with --run-dir?)")
        return "\n".join(lines)

    # ------------------------------------------------------- step rates
    rates = [m["steps_per_sec"] for m in metrics
             if isinstance(m.get("steps_per_sec"), (int, float))]
    p = _percentiles(rates)
    lines.append("")
    if p is not None:
        lines.append(f"step rate (steps/sec over {p['n']} windows): "
                     f"mean {p['mean']:.3f}  p10 {p['p10']:.3f}  "
                     f"p50 {p['p50']:.3f}  p90 {p['p90']:.3f}")
    else:
        lines.append("step rate: no steps_per_sec records")
    for key in ("examples_per_sec_per_chip", "tokens_per_sec_per_chip"):
        vals = [m[key] for m in metrics
                if isinstance(m.get(key), (int, float))]
        pk = _percentiles(vals)
        if pk is not None:
            lines.append(f"{key}: mean {pk['mean']:.1f}  "
                         f"p50 {pk['p50']:.1f}  p90 {pk['p90']:.1f}")
    losses = [m["loss"] for m in metrics
              if isinstance(m.get("loss"), (int, float))]
    if losses:
        lines.append(f"loss: first {losses[0]:.4f} -> last {losses[-1]:.4f} "
                     f"({len(losses)} records)")

    # ------------------------------------------------------ collectives
    coll = (summary or {}).get("collectives", {})
    lines.append("")
    if coll:
        lines.append("collectives:")
        lines.append(f"  {'op':<22}{'calls':>8}{'payload':>12}"
                     f"{'bus GB/s (p50)':>16}")
        for op in sorted(coll):
            row = coll[op]
            bw = row.get("bus_gbps")
            bw_s = f"{bw['p50']:.2f}" if isinstance(bw, dict) else "-"
            lines.append(f"  {op:<22}{row.get('calls', 0):>8}"
                         f"{_fmt_bytes(row.get('payload_bytes', 0)):>12}"
                         f"{bw_s:>16}")
    else:
        lines.append("collectives: none recorded")

    # ---------------------------------------------------------- serving
    serving = render_serving_section(summary)
    if serving:
        lines.append("")
        lines.extend(serving)

    # --------------------------------------------------------- replicas
    replicas = render_replicas_section(summary)
    if replicas:
        lines.append("")
        lines.extend(replicas)

    # ---------------------------------------------------- compile cache
    cc = (summary or {}).get("compile_cache")
    if cc is not None:
        hits, misses = cc.get("hits", 0), cc.get("misses", 0)
        total = hits + misses
        ratio = f"{hits / total:.1%}" if total else "n/a"
        secs = cc.get("compile_seconds", {})
        lines.append(f"compile cache: {hits} hits / {misses} misses "
                     f"(hit ratio {ratio}; "
                     f"{secs.get('sum', 0.0):.2f}s compiling)")

    # ------------------------------------------------------------ spans
    slowest = (summary or {}).get("slowest_spans")
    if slowest is None:
        slowest = sorted(spans, key=lambda s: -s.get("dur_s", 0.0))[:10]
    lines.append("")
    if slowest:
        lines.append("slowest spans:")
        for s in slowest[:10]:
            attrs = s.get("attrs") or {}
            a = (" " + " ".join(f"{k}={v}" for k, v in sorted(
                attrs.items()))) if attrs else ""
            lines.append(f"  {s.get('dur_s', 0.0):>9.4f}s  "
                         f"{s.get('name', '?')}{a}")
    else:
        lines.append("spans: none recorded")
    return "\n".join(lines)


# ------------------------------------------------------------------ SLO view


def load_fleet_events(run_dir: str) -> List[dict]:
    """Every typed event record reachable from ``run_dir`` — its own
    events.jsonl plus any immediate subdirectory's (the per-replica
    ``replica<N>/`` layout), each tagged with its source directory under
    ``_src``, sorted by timestamp so the fleet event log interleaves
    correctly across replicas."""
    sources = [(".", run_dir)]
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        names = []
    for name in names:
        sub = os.path.join(run_dir, name)
        if os.path.isdir(sub):
            sources.append((name, sub))
    out: List[dict] = []
    for src, d in sources:
        path = os.path.join(d, EVENTS_FILE)
        if not os.path.isfile(path):
            continue
        for rec in read_metrics(path):  # same JSONL shape
            if isinstance(rec, dict):
                rec = dict(rec)
                rec["_src"] = src
                out.append(rec)
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out


def slo_rows(events: List[dict]) -> List[dict]:
    """Per-SLO compliance/burn rows recomputed from ``slo.eval`` event
    records (the offline twin of the live tracker — see
    :func:`nezha_tpu.obs.slo.summarize_slo_events`)."""
    from nezha_tpu.obs.slo import summarize_slo_events
    rows = summarize_slo_events(events)
    return [rows[name] for name in sorted(rows)]


def render_slo_report(run_dir: str) -> str:
    """Plain-text SLO/watchdog view for a run directory: the per-SLO
    compliance + error-budget burn table recomputed from the run's
    ``slo.eval`` events, then the watchdog alert log."""
    events = load_fleet_events(run_dir)
    lines: List[str] = [f"SLO report: {os.path.abspath(run_dir)}"]
    if not events:
        lines.append("(no events.jsonl captured — was the run started with "
                     "--run-dir and --slo/--watchdog-interval?)")
        return "\n".join(lines)

    rows = slo_rows(events)
    lines.append("")
    if rows:
        lines.append("SLOs:")
        lines.append(f"  {'slo':<40}{'evals':>7}{'good':>7}{'bad':>6}"
                     f"{'compliance':>12}{'burn':>8}")
        for row in rows:
            comp = row.get("compliance")
            burn = row.get("burn_rate")
            comp_s = f"{comp:.1%}" if isinstance(comp, float) else "-"
            burn_s = f"{burn:.2f}" if isinstance(burn, float) else "-"
            lines.append(f"  {row['slo']:<40}"
                         f"{row.get('evaluations', 0):>7}"
                         f"{row.get('good', 0):>7}{row.get('bad', 0):>6}"
                         f"{comp_s:>12}{burn_s:>8}")
    else:
        lines.append("SLOs: no slo.eval records (run without --slo?)")

    alerts = [e for e in events
              if isinstance(e.get("kind"), str)
              and e["kind"].startswith("watchdog.")]
    lines.append("")
    if alerts:
        lines.append(f"watchdog events ({len(alerts)}):")
        for e in alerts[-20:]:
            detail = e.get("detail") or {}
            d = (" " + " ".join(f"{k}={v}" for k, v in sorted(
                detail.items()))) if detail else ""
            lines.append(f"  [{e.get('severity', '?'):<8}] "
                         f"{e.get('_src', '.')}: {e.get('kind', '?')}{d}")
    else:
        lines.append("watchdog events: none")
    return "\n".join(lines)
