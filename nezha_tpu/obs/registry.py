"""Process-wide telemetry registry: counters, gauges, histograms, spans.

The observability layer the metrics of record hang off (PAPER.md §0:
images/sec/chip, tokens/sec/chip, all-reduce bus bandwidth): call sites
across train/, parallel/, runtime/, and dist/ stay permanently
instrumented, and the whole layer collapses to near-zero cost when no run
is active. The fast-path contract is explicit: with telemetry disabled,
``counter().inc()`` / ``gauge().set()`` / ``histogram().observe()`` are a
single attribute check and ``span()`` returns one shared no-op singleton —
no per-call host allocation, no I/O (pinned by tests/test_obs.py).

Instruments are process-wide and keyed by name (get-or-create), so
independent subsystems accumulate into one snapshot without plumbing a
registry handle through every constructor. A run-scoped sink
(``obs.sink.start_run``) enables the registry and streams spans/metrics to
a ``--run-dir``; ``snapshot()`` renders everything into the
``summary.json`` schema (tools/check_telemetry_schema.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import random
import threading
import time
import uuid
import zlib
from typing import Any, Dict, List, Optional, Tuple

# One mutable cell shared by every instrument: ``enabled`` is THE fast-path
# check. Instruments cache a reference to this object, so toggling it flips
# every existing counter/gauge/span site at once. ``windows`` is the
# optional rolling-window tap (obs/timeseries.WindowStore): it lives
# INSIDE the enabled branch, so the disabled fast path stays a single
# attribute check whether or not windows were ever installed.


class _State:
    __slots__ = ("enabled", "windows")

    def __init__(self):
        self.enabled = False
        self.windows = None


_state = _State()


def enabled() -> bool:
    return _state.enabled


# --------------------------------------------------------- trace context
# Distributed request tracing: a request admitted anywhere in the fleet
# carries one ``trace_id`` across processes (router -> prefill replica ->
# migration -> decode replica), and every span recorded while the ambient
# trace context is set adopts it, so per-replica spans.jsonl fragments can
# be stitched back into one per-request timeline (obs/report.py). The
# context is a contextvar — it follows the handler thread that owns the
# request, never leaks across threads, and costs nothing while telemetry
# is disabled (``Registry.span`` short-circuits to NULL_SPAN before ever
# reading it).
_TRACE: contextvars.ContextVar = contextvars.ContextVar(
    "nezha_trace", default=None)          # (trace_id, parent_span_id)

# Sampling knob for load (``nezha-serve --trace-sample P``): minting rolls
# a seeded RNG once per request; a sampled-out request gets NO trace id,
# so none of its per-request spans are emitted — tracing cost scales with
# P, not with traffic.
_trace_lock = threading.Lock()
_trace_sample = 1.0
_trace_rng = random.Random(0x7ace)


def set_trace_sample(p: float) -> None:
    """Set the fraction of minted traces kept (0.0 disables minting
    entirely, 1.0 traces every request)."""
    global _trace_sample
    p = float(p)
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"trace sample must be in [0, 1], got {p}")
    with _trace_lock:
        _trace_sample = p


def trace_sample() -> float:
    return _trace_sample


def mint_trace_id() -> Optional[str]:
    """A fresh trace id for one request — or ``None`` when telemetry is
    disabled (the branch-only no-op contract: no run, no tracing) or the
    request lost the ``set_trace_sample`` coin flip. The minting site is
    the fleet's admission edge (the router; a router-less scheduler mints
    for itself at submit)."""
    if not _state.enabled:
        return None
    with _trace_lock:
        if _trace_sample <= 0.0:
            return None
        if _trace_sample < 1.0 and _trace_rng.random() >= _trace_sample:
            return None
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


#: The HTTP twin of the ``trace_id`` payload field — every serving
#: front end (replica, thread worker, router) honors the same pair.
TRACE_HEADER = "X-Nezha-Trace"


def adopt_trace_header(headers, payload) -> None:
    """Merge a ``TRACE_HEADER`` value into ``payload["trace_id"]`` —
    THE header-adoption rule, shared by every HTTP front end so the
    header/field precedence can never diverge between them. The header
    fills ``trace_id`` only when the payload doesn't already carry a
    non-empty one (the router sends both; either carries the trace).
    Non-dict payloads are left for the caller's validation to reject.
    """
    if not isinstance(payload, dict):
        return
    hdr = headers.get(TRACE_HEADER)
    if hdr and not payload.get("trace_id"):
        payload["trace_id"] = hdr


def current_trace() -> Tuple[Optional[str], Optional[str]]:
    """-> ``(trace_id, parent_span_id)`` of the ambient trace context
    (``(None, None)`` outside any)."""
    cur = _TRACE.get()
    return cur if cur is not None else (None, None)


@contextlib.contextmanager
def trace_context(trace_id: Optional[str],
                  parent_id: Optional[str] = None):
    """Run the enclosed block under ``trace_id``: every span opened (or
    ``emit_span``-recorded) inside adopts it. ``trace_id=None`` is a
    cheap no-op, so call sites can pass an unconditionally-threaded
    (possibly absent) id without branching."""
    if not trace_id:
        yield
        return
    token = _TRACE.set((trace_id, parent_id))
    try:
        yield
    finally:
        _TRACE.reset(token)


def percentile_of(sorted_values: List[float], q: float) -> float:
    """Index percentile over an ascending list (0.0 when empty) — the one
    percentile convention every telemetry surface shares (Histogram
    summaries, the report renderer, recomputed-stream summaries)."""
    if not sorted_values:
        return 0.0
    idx = min(int(q / 100.0 * len(sorted_values)), len(sorted_values) - 1)
    return sorted_values[idx]


def values_summary(values: List[float]) -> dict:
    """``Histogram.summary()``-shaped dict computed exactly from a full
    list of values (the recomputed-from-stream path, where no reservoir
    decimation is involved)."""
    s = sorted(values)
    total = sum(s)
    return {"count": len(s), "sum": total,
            "min": s[0] if s else 0.0, "max": s[-1] if s else 0.0,
            "mean": total / len(s) if s else 0.0,
            "p50": percentile_of(s, 50), "p90": percentile_of(s, 90),
            "p99": percentile_of(s, 99)}


class Counter:
    """Monotonic counter. ``inc`` is a no-op while telemetry is disabled."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if _state.enabled:
            self.value += n
            w = _state.windows
            if w is not None:
                w.record_counter(self.name, n)


class Gauge:
    """Last-value-wins instrument (queue depths, cache sizes)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v) -> None:
        if _state.enabled:
            self.value = float(v)
            w = _state.windows
            if w is not None:
                w.record_gauge(self.name, self.value)


class Histogram:
    """Value distribution with streaming min/max/sum and a bounded sample
    RESERVOIR for percentiles (Vitter's Algorithm R): once the reservoir
    is full, observation ``n`` replaces a random slot with probability
    ``cap/n``, so at any point the samples are a uniform draw over the
    WHOLE stream so far — long-run percentiles are unbiased, unlike the
    old stride decimation whose kept set was anchored to the startup
    prefix of the stream. The replacement RNG is seeded from the
    instrument name, so a given observation stream always yields the same
    summary (reproducible captures)."""

    __slots__ = ("name", "count", "total", "min", "max", "_samples",
                 "_rng", "_cap", "_lock")

    # observe() is a multi-field read-modify-write hit from concurrent
    # recorder threads (the reservoir RNG's stream advance included) —
    # declared for nezha-lint's lock-discipline rule.
    _LOCK_GUARDED = {"count": "_lock", "total": "_lock", "min": "_lock",
                     "max": "_lock", "_samples": "_lock",
                     "_rng": "_lock"}

    def __init__(self, name: str, cap: int = 4096):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._samples: List[float] = []
        # crc32, not hash(): hash() is salted per process, and the
        # reservoir must decimate identically across runs of the same
        # stream for captures to be reproducible.
        self._rng = random.Random(zlib.crc32(name.encode("utf-8")))
        self._cap = cap
        # Per-instrument lock: observe() is a multi-field read-modify-write
        # (count/total/reservoir replacement) that concurrent recorders
        # (e.g. two Executor threads timing compiles) would corrupt.
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        if not _state.enabled:
            return
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._samples) < self._cap:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self._samples[j] = v
        # Window tap outside the reservoir lock: the store has its own
        # lock, and nesting them would couple every histogram's hot path
        # to the rotation critical section.
        w = _state.windows
        if w is not None:
            w.record_histogram(self.name, v)

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            s = sorted(self._samples)
        return percentile_of(s, q) if s else None

    def summary(self) -> dict:
        # count/sum/min/max are exact streaming stats; only the percentiles
        # come from the (possibly decimated) reservoir.
        with self._lock:
            count, total = self.count, self.total
            mn, mx = self.min, self.max
            s = sorted(self._samples)
        return {
            "count": count,
            "sum": total,
            "min": mn if mn is not None else 0.0,
            "max": mx if mx is not None else 0.0,
            "mean": total / count if count else 0.0,
            "p50": percentile_of(s, 50),
            "p90": percentile_of(s, 90),
            "p99": percentile_of(s, 99),
        }


class _NullSpan:
    """The disabled-mode span: one shared instance, every method a no-op —
    ``with obs.span("x"):`` costs a dict-free call and two no-op methods."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self


NULL_SPAN = _NullSpan()

# Bookkeeping fields whose distributions mean nothing (the step counter,
# the logger's wall-clock stamp): streamed to metrics.jsonl as-is but never
# folded into metric.<key> histograms. Shared with the recomputed-stream
# path (obs/report.py summarize_streams).
UNFOLDED_METRIC_KEYS = frozenset({"step", "ts"})


class Span:
    """Live wall-clock span; records itself into the registry on exit.

    A span opened inside a ``trace_context`` adopts the ambient trace:
    it carries ``trace_id`` / a fresh ``span_id`` / the ambient
    ``parent_id``, and while entered it IS the ambient parent, so nested
    spans chain. The trace fields ride in the span record only when a
    trace is present — untraced captures are byte-identical to the
    pre-tracing schema."""

    __slots__ = ("name", "attrs", "t0", "t1", "_registry",
                 "trace_id", "span_id", "parent_id", "_token")

    def __init__(self, name: str, registry: "Registry", attrs: dict,
                 trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None):
        self.name = name
        self.attrs = attrs
        self.t0 = time.time()
        self.t1: Optional[float] = None
        self._registry = registry
        self.trace_id = trace_id
        self.span_id = new_span_id() if trace_id else None
        self.parent_id = parent_id
        self._token = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        if self.trace_id:
            self._token = _TRACE.set((self.trace_id, self.span_id))
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        if self._token is not None:
            _TRACE.reset(self._token)
            self._token = None
        self.t1 = time.time()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._registry.record_span(self.to_record())
        return False

    def to_record(self) -> dict:
        t1 = self.t1 if self.t1 is not None else time.time()
        rec = {"name": self.name, "t0": self.t0, "t1": t1,
               "dur_s": t1 - self.t0, "attrs": self.attrs}
        if self.trace_id:
            rec["trace_id"] = self.trace_id
            rec["span_id"] = self.span_id
            if self.parent_id:
                rec["parent_id"] = self.parent_id
        return rec


class Registry:
    """Named-instrument store + bounded span log. Thread-safe for
    get-or-create (instrument mutation itself is GIL-atomic enough for
    counters/gauges; histograms carry their own lock, spans take the
    registry's)."""

    # Get-or-create maps and the span/event logs, shared by every
    # recording thread — declared for nezha-lint's lock-discipline rule.
    _LOCK_GUARDED = {"_counters": "_lock", "_gauges": "_lock",
                     "_histograms": "_lock", "spans": "_lock",
                     "events": "_lock"}

    def __init__(self, max_spans: int = 10000, max_events: int = 1000):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self.spans: List[dict] = []
        self.events: List[dict] = []
        self._max_spans = max_spans
        self._max_events = max_events
        self._sink = None  # RunSink streaming spans/metrics, when attached
        # Stable identity for fleet roll-up dedupe: thread-backend
        # replicas all answer /stats from THIS one process-wide
        # registry, and the router must sum each distinct registry once
        # — not once per member — for thread and process backends to
        # report the same fleet totals.
        self.registry_id = uuid.uuid4().hex[:16]

    # -------------------------------------------------- instrument access
    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name))
        return h

    def span(self, name: str, **attrs):
        if not _state.enabled:
            return NULL_SPAN
        tid, parent = current_trace()
        return Span(name, self, attrs, trace_id=tid, parent_id=parent)

    def traced_span(self, name: str, **attrs):
        """A span recorded ONLY inside an ambient trace context — the
        per-request instrumentation form: a sampled-out (or untraced)
        request pays a single contextvar read and records nothing, so
        trace volume scales with the sample rate, not with traffic."""
        if not _state.enabled:
            return NULL_SPAN
        tid, parent = current_trace()
        if tid is None:
            return NULL_SPAN
        return Span(name, self, attrs, trace_id=tid, parent_id=parent)

    def emit_span(self, name: str, t0: float, t1: float,
                  trace_id: Optional[str] = None,
                  parent_id: Optional[str] = None, **attrs) -> None:
        """Record an already-measured interval as a span — the
        retroactive form lifecycle call sites use when the boundary
        times are only known after the fact (queue wait is measured at
        admission, a park's span at its release). No-op while telemetry
        is disabled."""
        if not _state.enabled:
            return
        rec = {"name": name, "t0": float(t0), "t1": float(t1),
               "dur_s": float(t1) - float(t0), "attrs": attrs}
        if trace_id:
            rec["trace_id"] = trace_id
            rec["span_id"] = new_span_id()
            if parent_id:
                rec["parent_id"] = parent_id
        self.record_span(rec)

    def record_span(self, rec: dict) -> None:
        if not _state.enabled:
            return
        with self._lock:
            if len(self.spans) < self._max_spans:
                self.spans.append(rec)
            sink = self._sink
        if sink is not None:
            sink.write_span(rec)

    def record_event(self, kind: str, severity: str = "info",
                     source: str = "watchdog", **detail) -> Optional[dict]:
        """Record a typed telemetry event (the watchdog/SLO stream):
        kept in a bounded in-process log and streamed to the run dir's
        ``events.jsonl`` when a sink is attached. Event kinds under the
        ``watchdog.``/``slo.`` namespaces are pinned by
        analysis/telemetry_schema.py (EVENT_KINDS). No-op while
        telemetry is disabled."""
        if not _state.enabled:
            return None
        rec = {"event_schema_version": 1, "ts": time.time(),
               "kind": kind, "severity": severity, "source": source,
               "detail": detail}
        with self._lock:
            if len(self.events) < self._max_events:
                self.events.append(rec)
            sink = self._sink
        if sink is not None:
            sink.write_event(rec)
        return rec

    def windows(self, duration_s: float, skip: int = 0) -> dict:
        """The rolled-up window view over the trailing ``duration_s``
        seconds (obs/timeseries.WindowStore.view shape). With no window
        store installed the empty view renders — zero buckets, no
        instruments — so exposition callers never branch on None.
        ``skip`` drops that many newest buckets (trailing baselines)."""
        w = _state.windows
        if w is None:
            from nezha_tpu.obs.timeseries import empty_view
            return empty_view(duration_s)
        return w.view(duration_s, skip=skip)

    # --------------------------------------------------------- aggregates
    def record_metrics(self, step: int, metrics: Dict[str, Any]) -> None:
        """Route a per-step metrics dict to the attached sink and fold
        every numeric value into a ``metric.<key>`` histogram, so the
        summary carries percentiles (step-rate p50/p90/...) for free."""
        if not _state.enabled:
            return
        for k, v in metrics.items():
            if (k not in UNFOLDED_METRIC_KEYS
                    and isinstance(v, (int, float))
                    and not isinstance(v, bool)):
                self.histogram(f"metric.{k}").observe(v)
        sink = self._sink
        if sink is not None:
            sink.write_metrics(step, metrics)

    def record_collective(self, op: str, payload_bytes: int,
                          seconds: Optional[float] = None,
                          bus_bytes: Optional[float] = None) -> None:
        """Per-collective accounting (EQuARX's first-class metric): call
        count + payload bytes always; achieved bus bandwidth when the
        caller timed the op (benchmarks). Trace-time call sites (the
        collectives emitted inside jit) count bytes per traced program —
        the payload a compiled step moves per execution."""
        if not _state.enabled:
            return
        self.counter(f"collective.{op}.calls").inc()
        self.counter(f"collective.{op}.payload_bytes").inc(
            int(payload_bytes))
        if seconds is not None and seconds > 0 and bus_bytes is not None:
            self.histogram(f"collective.{op}.bus_gbps").observe(
                bus_bytes / seconds / 1e9)

    def snapshot(self) -> dict:
        """Everything, in the frozen summary.json shape (schema v1)."""
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = {k: g.value for k, g in self._gauges.items()}
            hists = {k: h.summary() for k, h in self._histograms.items()}
            spans = list(self.spans)
        collectives: Dict[str, dict] = {}
        for name, value in counters.items():
            if not name.startswith("collective."):
                continue
            _, op, field = name.split(".", 2)
            collectives.setdefault(op, {})[field] = value
        for name, h in hists.items():
            if name.startswith("collective.") and name.endswith(".bus_gbps"):
                op = name.split(".", 2)[1]
                collectives.setdefault(op, {})["bus_gbps"] = h
        slowest = sorted(spans, key=lambda s: -s["dur_s"])[:10]
        return {
            "schema_version": 1,
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
            "collectives": collectives,
            "compile_cache": {
                "hits": counters.get("compile_cache.hits", 0),
                "misses": counters.get("compile_cache.misses", 0),
                "compile_seconds": hists.get(
                    "compile_cache.compile_seconds",
                    {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                     "mean": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0}),
            },
            "num_spans": len(spans),
            "slowest_spans": slowest,
        }

    def stats(self) -> dict:
        """The live ``/stats`` payload (stats schema v1, pinned by
        analysis/telemetry_schema.check_stats_payload): the registry's
        counters/gauges/histogram summaries RIGHT NOW, without touching
        (or requiring) a run dir — what a replica front end answers so
        an operator can curl the fleet mid-run. Spans are excluded: the
        live view is the aggregate state, traces are the run-dir
        artifact."""
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = {k: g.value for k, g in self._gauges.items()}
            hists = list(self._histograms.values())
        return {"stats_schema_version": 1,
                "kind": "replica",
                "ts": time.time(),
                "enabled": _state.enabled,
                "registry_id": self.registry_id,
                "counters": counters,
                "gauges": gauges,
                "histograms": {h.name: h.summary() for h in hists}}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self.spans.clear()
            self.events.clear()


# The process-wide default registry and its module-level shorthands: the
# form instrumented call sites use (``obs.counter("x").inc()``).
REGISTRY = Registry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return REGISTRY.histogram(name)


def span(name: str, **attrs):
    return REGISTRY.span(name, **attrs)


def traced_span(name: str, **attrs):
    return REGISTRY.traced_span(name, **attrs)


def emit_span(name: str, t0: float, t1: float,
              trace_id: Optional[str] = None,
              parent_id: Optional[str] = None, **attrs) -> None:
    REGISTRY.emit_span(name, t0, t1, trace_id=trace_id,
                       parent_id=parent_id, **attrs)


def stats_snapshot() -> dict:
    return REGISTRY.stats()


def record_metrics(step: int, metrics: Dict[str, Any]) -> None:
    REGISTRY.record_metrics(step, metrics)


def record_collective(op: str, payload_bytes: int,
                      seconds: Optional[float] = None,
                      bus_bytes: Optional[float] = None) -> None:
    REGISTRY.record_collective(op, payload_bytes, seconds, bus_bytes)


def record_event(kind: str, severity: str = "info",
                 source: str = "watchdog", **detail) -> Optional[dict]:
    return REGISTRY.record_event(kind, severity=severity, source=source,
                                 **detail)


def windows(duration_s: float, skip: int = 0) -> dict:
    return REGISTRY.windows(duration_s, skip=skip)


def enable() -> None:
    _state.enabled = True


def disable() -> None:
    _state.enabled = False
