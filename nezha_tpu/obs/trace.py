"""Tracing and profiling on top of jax.profiler — plus the distributed
request-trace context re-exports.

Two kinds of tracing meet here:

- **device tracing** (this module's own code): absorbed from
  ``utils/profiling.py`` (the public names stay importable from
  ``nezha_tpu.utils``). The reference had no attested profiler subsystem
  (SURVEY.md §5); on TPU the platform tool is the XLA profiler —
  ``jax.profiler`` captures device traces (MXU occupancy, HBM traffic,
  per-op timing) viewable in TensorBoard/XProf. The context managers are
  no-ops when disabled, so call sites can stay annotated permanently.
- **distributed request tracing** (re-exported from ``obs.registry``,
  where the Span machinery lives): ``trace_context(trace_id)`` sets the
  ambient trace a request carries across the serving fleet,
  ``mint_trace_id()`` mints one at the admission edge (sampled by
  ``set_trace_sample``), ``traced_span`` / ``emit_span`` record
  per-request lifecycle fragments that ``nezha-telemetry RUN_DIR
  --trace`` stitches back into per-request timelines (obs/report.py).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

import jax

from nezha_tpu.obs.registry import (  # noqa: F401 — re-exported API
    current_trace,
    emit_span,
    mint_trace_id,
    new_span_id,
    set_trace_sample,
    trace_context,
    trace_sample,
    traced_span,
)


@contextlib.contextmanager
def profile_trace(log_dir: str,
                  create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device trace for the enclosed block into ``log_dir``.

    Wrap a handful of steady-state steps (skip step 0 — it contains the
    compile). View with TensorBoard's profile plugin or Perfetto.
    """
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region in the trace timeline (host and device rows).

    Usable inside jit: becomes an XLA op annotation via TraceAnnotation.
    """
    with jax.profiler.TraceAnnotation(name):
        yield


class Tracer:
    """Start/stop trace control for long-running loops.

    A Trainer can hold one and call ``maybe_trace(step)``: the trace turns
    on at ``start_step`` and off after ``num_steps`` — the standard
    "profile steps 10..13" workflow without restructuring the loop.
    """

    def __init__(self, log_dir: Optional[str] = None, start_step: int = 10,
                 num_steps: int = 3):
        self.log_dir = log_dir
        self.start_step = start_step
        self.num_steps = num_steps
        self.stop_step = start_step + num_steps
        self._active = False
        self._done = False

    @property
    def enabled(self) -> bool:
        return self.log_dir is not None

    def maybe_trace(self, step: int) -> None:
        if not self.enabled:
            return
        # A resumed run's counter may start anywhere past start_step (e.g.
        # restored global_step=5000 with start_step=10): rebase the window
        # onto the first step actually observed at/after start_step, so a
        # full num_steps window is always captured exactly once.
        if not self._active and not self._done and step >= self.start_step:
            self.stop_step = step + self.num_steps
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and step >= self.stop_step:
            self.stop()

    def stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True  # one window per Tracer

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass
