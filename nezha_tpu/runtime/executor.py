"""Executor: compile-cached dispatch of graphs/functions.

The reference's worker pool kept the GPU busy by dispatching graph nodes to
streams; XLA's runtime already pipelines dispatch (async, ahead-of-device),
so the executor's job is executable lifetime: compile once per (graph,
shapes, shardings), reuse forever (SURVEY.md §7 item (c): per-step graph
capture + executable cache).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Hashable, Optional, Tuple

import jax

from nezha_tpu import obs
from nezha_tpu.graph.graph import Graph
from nezha_tpu.graph.lower import to_callable


def _graph_fingerprint(graph: Graph) -> Hashable:
    """Structural identity of a graph: ops, edges, and attrs — so distinct
    graphs never share a compiled executable even if same-named/sized."""
    import hashlib

    import numpy as np

    def attr_val(v):
        if isinstance(v, np.ndarray):
            # repr() truncates big arrays; hash the actual bytes instead.
            h = hashlib.sha256()
            h.update(str(v.dtype).encode())
            h.update(str(v.shape).encode())
            h.update(np.ascontiguousarray(v).tobytes())
            return ("ndarray", h.hexdigest())
        return repr(v)

    def attr_sig(attrs):
        return tuple(sorted((k, attr_val(v)) for k, v in attrs.items()))

    return (
        tuple((n.op, n.inputs, attr_sig(n.attrs)) for n in graph.nodes),
        tuple(graph.placeholders),
        tuple(graph.outputs),
    )


def _signature(args: Tuple, kwargs: Dict) -> Hashable:
    def leaf_sig(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return ("arr", tuple(x.shape), str(x.dtype))
        return ("lit", x)

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(leaf_sig(l) for l in leaves))


class CompileCache:
    """Thread-safe (signature -> compiled executable) cache with stats.

    Hit/miss/build-time telemetry flows to the process-wide registry
    (``compile_cache.*`` — the GC3-motivated compiler-cache view in a
    ``--run-dir`` summary) alongside the local attributes."""

    def __init__(self):
        self._cache: Dict[Hashable, Any] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get_or_build(self, key: Hashable, build: Callable[[], Any]) -> Any:
        value, _ = self.get_or_build2(key, build)
        return value

    def get_or_build2(self, key: Hashable,
                      build: Callable[[], Any]) -> "Tuple[Any, bool]":
        """-> ``(value, built)`` where ``built`` says whether THIS call
        populated the entry — a per-call miss signal (the shared ``misses``
        counter can move concurrently under other keys)."""
        with self._lock:
            if key in self._cache:
                self.hits += 1
                obs.counter("compile_cache.hits").inc()
                return self._cache[key], False
        built = build()  # compile outside the lock; dup compiles are benign
        with self._lock:
            self.misses += 1
            obs.counter("compile_cache.misses").inc()
            return self._cache.setdefault(key, built), True

    def __len__(self):
        return len(self._cache)


class Executor:
    """Runs functions or Graph IR programs with jit + compile caching.

    ``run`` is async like the device: it returns device arrays immediately;
    call ``jax.block_until_ready`` (or read values) to synchronize —
    mirroring how the reference's pool overlapped host work with kernels.
    """

    def __init__(self, donate_argnums: Tuple[int, ...] = ()):
        self.cache = CompileCache()
        self.donate_argnums = donate_argnums

    def run(self, fn_or_graph, *args, **kwargs):
        if isinstance(fn_or_graph, Graph):
            fn = to_callable(fn_or_graph)
            base_key = ("graph", _graph_fingerprint(fn_or_graph))
        else:
            fn = fn_or_graph
            # Key by the function object itself: hashable, and the cache
            # entry keeps it alive so ids can't be recycled.
            base_key = ("fn", fn_or_graph)
        key = (base_key, _signature(args, kwargs))
        jitted, built = self.cache.get_or_build2(
            key, lambda: jax.jit(fn, donate_argnums=self.donate_argnums))
        if built and obs.enabled():
            # jax.jit is lazy — the FIRST dispatch pays trace+compile, so
            # that call is the executable's compile-time record.
            with obs.span("executor.compile", kind=base_key[0]):
                t0 = time.perf_counter()
                out = jitted(*args, **kwargs)
                obs.histogram("compile_cache.compile_seconds").observe(
                    time.perf_counter() - t0)
            return out
        return jitted(*args, **kwargs)

    def stats(self) -> dict:
        return {"entries": len(self.cache), "hits": self.cache.hits,
                "misses": self.cache.misses}
