"""Host-side prefetching worker pool.

Python counterpart of the reference's goroutine worker pool for the input
path (SURVEY.md §1 "Execution runtime"): N worker threads pull batches from
the source iterator into a bounded queue and stage them onto device (with a
target sharding) while the previous step runs. For decode-heavy pipelines a
native C++ loader (under `csrc/`) can sit underneath as the source iterator;
numpy-producing iterators release the GIL during copies, so threads suffice
for staging.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterator, Optional

import jax

from nezha_tpu import obs


class Prefetcher:
    """Bounded-depth background prefetcher; iterate to get device batches.

    Telemetry (when a run is active): a ``prefetch.queue_depth`` gauge
    sampled at every consumer read, and a ``prefetch.stalls`` counter with
    ``prefetch.stall_seconds`` for reads that found the queue empty — the
    input-bound signal (a healthy pipeline keeps depth > 0, so the device
    never waits on the host)."""

    _DONE = object()

    def __init__(self, source: Iterator[Any], depth: int = 2,
                 sharding: Optional[jax.sharding.Sharding] = None,
                 num_workers: int = 1):
        self._source = source
        self._sharding = sharding
        # +num_workers slots so every worker can always enqueue its exit
        # sentinel without blocking, even with no consumer draining.
        self._q: "queue.Queue" = queue.Queue(
            maxsize=max(depth, 1) + max(num_workers, 1))
        self._src_lock = threading.Lock()
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._done_seen = 0
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"nezha-prefetch-{i}")
            for i in range(max(num_workers, 1))
        ]
        for t in self._threads:
            t.start()

    def _stage(self, batch):
        if self._sharding is None:
            return jax.device_put(batch)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._sharding), batch)

    def _work(self):
        # Every worker enqueues exactly one _DONE on exit; the consumer stops
        # only after collecting all of them, so one worker finishing early
        # can't truncate batches other workers are still staging.
        try:
            while not self._stop.is_set():
                try:
                    with self._src_lock:
                        batch = next(self._source)
                except StopIteration:
                    return
                except BaseException as e:  # surface in consumer
                    self._error = e
                    return
                self._q.put(self._stage(batch))
        finally:
            self._q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if obs.enabled():
                # Guarded so the disabled path stays exactly `q.get()`.
                obs.gauge("prefetch.queue_depth").set(self._q.qsize())
                if self._q.empty():
                    t0 = time.perf_counter()
                    item = self._q.get()
                    # A wait that yields a worker-exit sentinel is shutdown
                    # bookkeeping, not host-input starvation — don't let
                    # end-of-stream drains read as an input-bound signal.
                    if item is not self._DONE:
                        obs.counter("prefetch.stalls").inc()
                        obs.histogram("prefetch.stall_seconds").observe(
                            time.perf_counter() - t0)
                else:
                    item = self._q.get()
            else:
                item = self._q.get()
            if item is self._DONE:
                self._done_seen += 1
                if self._done_seen >= len(self._threads):
                    if self._error is not None:
                        raise self._error
                    raise StopIteration
                continue
            return item

    def close(self, timeout: float = 5.0):
        self._stop.set()
        # Keep draining until every worker has exited: a worker blocked in
        # put() needs space to wake up, check _stop, and enqueue its sentinel.
        deadline = time.monotonic() + timeout
        while (any(t.is_alive() for t in self._threads)
               and time.monotonic() < deadline):
            try:
                self._q.get(timeout=0.05)
            except queue.Empty:
                pass
        for t in self._threads:
            t.join(timeout=0.1)


def prefetch_to_device(source: Iterator[Any], depth: int = 2,
                       sharding: Optional[jax.sharding.Sharding] = None) -> Iterator[Any]:
    return Prefetcher(source, depth=depth, sharding=sharding)
