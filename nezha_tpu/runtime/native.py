"""ctypes bindings for the native runtime library (csrc/).

Builds ``libnezha_rt.so`` on first use with the in-tree Makefile (g++ is
part of the baked toolchain) and caches by source mtime. The library holds
the TPU-native counterparts of the reference's native runtime pieces
(SURVEY.md §2): the coordinator (gRPC coordinator role) and the threaded
batch loader (goroutine worker pool role on the input path).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_LIB_PATH = os.path.join(_CSRC, "build", "libnezha_rt.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


class NativeBuildError(RuntimeError):
    pass


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for name in os.listdir(_CSRC):
        if name.endswith((".cpp", ".h")):
            if os.path.getmtime(os.path.join(_CSRC, name)) > lib_mtime:
                return True
    return False


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.nz_last_error.restype = c.c_char_p
    lib.nz_coord_start.restype = c.c_void_p
    lib.nz_coord_start.argtypes = [c.c_int, c.c_int, c.c_int]
    lib.nz_coord_port.restype = c.c_int
    lib.nz_coord_port.argtypes = [c.c_void_p]
    lib.nz_coord_stop.argtypes = [c.c_void_p]
    lib.nz_client_connect.restype = c.c_void_p
    lib.nz_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int, c.c_int,
                                      c.c_int]
    lib.nz_client_rank.restype = c.c_int
    lib.nz_client_rank.argtypes = [c.c_void_p]
    lib.nz_client_world.restype = c.c_int
    lib.nz_client_world.argtypes = [c.c_void_p]
    lib.nz_client_put.restype = c.c_int
    lib.nz_client_put.argtypes = [c.c_void_p, c.c_char_p, c.c_void_p,
                                  c.c_long]
    lib.nz_client_get.restype = c.c_long
    lib.nz_client_get.argtypes = [c.c_void_p, c.c_char_p, c.c_void_p,
                                  c.c_long, c.c_long]
    lib.nz_client_incr.restype = c.c_long
    lib.nz_client_incr.argtypes = [c.c_void_p, c.c_char_p]
    lib.nz_client_barrier.restype = c.c_int
    lib.nz_client_barrier.argtypes = [c.c_void_p, c.c_long]
    lib.nz_client_failed.restype = c.c_long
    lib.nz_client_failed.argtypes = [c.c_void_p, c.POINTER(c.c_int32),
                                     c.c_long]
    lib.nz_client_leave.argtypes = [c.c_void_p]
    lib.nz_client_close.argtypes = [c.c_void_p]

    lib.nz_loader_error.restype = c.c_char_p
    lib.nz_mnist_open.restype = c.c_void_p
    lib.nz_mnist_open.argtypes = [c.c_char_p, c.c_char_p, c.c_int,
                                  c.c_uint64, c.c_int, c.c_int, c.c_int,
                                  c.POINTER(c.c_int), c.POINTER(c.c_int)]
    lib.nz_tokens_open.restype = c.c_void_p
    lib.nz_tokens_open.argtypes = [c.c_char_p, c.c_int, c.c_int, c.c_int,
                                   c.c_uint64, c.c_int, c.c_int, c.c_int,
                                   c.c_int, c.POINTER(c.c_long)]
    lib.nz_records_open.restype = c.c_void_p
    lib.nz_records_open.argtypes = [c.c_char_p, c.c_int, c.c_int, c.c_int,
                                    c.c_uint64, c.c_int, c.c_int, c.c_int,
                                    c.c_int, c.c_int, c.c_int,
                                    c.POINTER(c.c_int), c.POINTER(c.c_int),
                                    c.POINTER(c.c_int), c.POINTER(c.c_int)]
    lib.nz_loader_next.restype = c.c_int
    lib.nz_loader_next.argtypes = [c.c_void_p, c.POINTER(c.c_float),
                                   c.POINTER(c.c_int32)]
    lib.nz_loader_close.argtypes = [c.c_void_p]
    return lib


def load_library() -> ctypes.CDLL:
    """Build (if stale) and load the native runtime library.

    Thread-safe in-process, and cross-process safe: multi-process launches
    on one host all race here on a cold build, so the build runs under an
    exclusive flock and the Makefile moves the .so into place atomically —
    no rank can dlopen a half-written library.
    """
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            import fcntl
            os.makedirs(os.path.join(_CSRC, "build"), exist_ok=True)
            with open(os.path.join(_CSRC, "build", ".lock"), "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                try:
                    if _needs_build():  # may have been built while we waited
                        proc = subprocess.run(
                            ["make", "-s"], cwd=_CSRC,
                            capture_output=True, text=True)
                        if proc.returncode != 0:
                            raise NativeBuildError(
                                "native build failed:\n"
                                f"{proc.stdout}\n{proc.stderr}")
                finally:
                    fcntl.flock(lockf, fcntl.LOCK_UN)
        _lib = _declare(ctypes.CDLL(_LIB_PATH))
        return _lib


def native_available() -> bool:
    """True if the native library is (or can be) built on this host."""
    try:
        load_library()
        return True
    except (NativeBuildError, OSError):
        return False
