"""Execution runtime: compile cache, async dispatch, prefetching workers.

The reference's execution runtime is a goroutine worker pool dispatching
ready graph nodes to CUDA streams (SURVEY.md §1 "Execution runtime"). On
TPU, XLA fuses the graph into a handful of executables and the device runs
them asynchronously, so the runtime's real jobs become: executable lifetime
+ compile caching (`Executor`), keeping the device fed (host worker pool /
prefetcher — Python threads staging batches; a native C++ loader under
`csrc/` can feed it), and tracing/profiling hooks.
"""

from nezha_tpu.runtime.executor import Executor, CompileCache
from nezha_tpu.runtime.prefetch import Prefetcher, prefetch_to_device

__all__ = ["Executor", "CompileCache", "Prefetcher", "prefetch_to_device"]
