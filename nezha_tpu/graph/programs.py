"""Training programs authored in the Graph IR.

This makes the IR load-bearing for benchmark config 1 (`mlp_mnist` with
``--engine graph``): the MLP forward, the cross-entropy loss, and the
momentum update are all *built as graphs*, the backward comes from
``jax.grad`` over the interpreted IR (the documented autograd path,
`graph/lower.py:grad_callable`), and the whole step executes through the
runtime ``Executor``'s compile cache — Graph -> StableHLO -> XLA end to end
(the north star's "lower the internal op graph to StableHLO").
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import numpy as np

from nezha_tpu.graph.graph import Graph
from nezha_tpu.graph.lower import to_callable
from nezha_tpu.runtime.executor import Executor

# Parameter order for an L-layer MLP: w0, b0, w1, b1, ..., wH, bH (head last)
# — matches models.MLP's {"fc0": {"w","b"}, ..., "head": {"w","b"}} layout.


def mlp_param_names(n_layers: int) -> Sequence[str]:
    names = [f"fc{i}" for i in range(n_layers - 1)] + ["head"]
    return names


def mlp_loss_graph(dims: Sequence[int], batch: int) -> Graph:
    """IR graph: (w0, b0, ..., image[B, in], onehot[B, classes]) -> loss.

    The label one-hot is a placeholder (host-side data transform), keeping
    the graph free of integer gather ops.
    """
    g = Graph("mlp_loss")
    ws, bs = [], []
    for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
        ws.append(g.placeholder((din, dout), name=f"w{i}"))
        bs.append(g.placeholder((dout,), name=f"b{i}"))
    x = g.placeholder((batch, dims[0]), name="image")
    onehot = g.placeholder((batch, dims[-1]), name="onehot")

    h = x
    for i, (w, b) in enumerate(zip(ws, bs)):
        h = (h @ w) + b
        if i < len(ws) - 1:
            h = g.relu(h)
    logp = g.log_softmax(h, axis=-1)
    nll = -g.mean(g.sum(logp * onehot, axis=1))
    g.output(nll)
    return g


def momentum_update_graph(shape: Sequence[int], lr: float,
                          beta: float) -> Graph:
    """IR graph: (param, velocity, grad) -> (new_param, new_velocity)."""
    g = Graph("momentum_update")
    p = g.placeholder(shape, name="param")
    v = g.placeholder(shape, name="velocity")
    grad = g.placeholder(shape, name="grad")
    v_new = v * beta + grad
    p_new = p - v_new * lr
    g.output(p_new, v_new)
    return g


def make_mlp_graph_train_step(dims: Sequence[int], batch: int, lr: float,
                              beta: float = 0.9,
                              executor: Executor = None):
    """Trainer-compatible ``step(state, batch) -> (state, metrics)`` whose
    forward/loss/update are Graph IR programs.

    ``state`` = {"params": {fcN/head: {"w","b"}}, "vel": same-shaped}.
    ``batch`` = {"image": [B, in], "onehot": [B, classes]} (see
    :func:`onehot_shard_fn`).
    """
    executor = executor or Executor()
    loss_graph = mlp_loss_graph(dims, batch)
    loss_fn = to_callable(loss_graph)
    n_params = 2 * (len(dims) - 1)
    vg = jax.value_and_grad(loss_fn, argnums=tuple(range(n_params)))

    # One update graph per distinct parameter shape (placeholders are
    # shape-typed); the Executor dedupes compiles by graph fingerprint.
    shapes = [(din, dout) for din, dout in zip(dims[:-1], dims[1:])]
    shapes += [(dout,) for dout in dims[1:]]
    upd_fns: Dict[Tuple[int, ...], callable] = {}
    for s in {tuple(s) for s in shapes}:
        upd_fns[s] = to_callable(momentum_update_graph(s, lr, beta))

    names = mlp_param_names(len(dims) - 1)

    def flatten(tree) -> list:
        return [tree[n][k] for n in names for k in ("w", "b")]

    def unflatten(flat) -> dict:
        it = iter(flat)
        return {n: {"w": next(it), "b": next(it)} for n in names}

    def whole_step(*flat_and_batch):
        flat = flat_and_batch[:2 * n_params]
        params, vels = flat[:n_params], flat[n_params:]
        image, onehot = flat_and_batch[-2:]
        loss, grads = vg(*params, image, onehot)
        new_p, new_v = [], []
        for p, v, gr in zip(params, vels, grads):
            pn, vn = upd_fns[tuple(p.shape)](p, v, gr)
            new_p.append(pn)
            new_v.append(vn)
        return (loss, *new_p, *new_v)

    def step(state, b):
        flat_p = flatten(state["params"])
        flat_v = flatten(state["vel"])
        out = executor.run(whole_step, *flat_p, *flat_v,
                           b["image"], b["onehot"])
        loss, rest = out[0], out[1:]
        return ({"params": unflatten(rest[:n_params]),
                 "vel": unflatten(rest[n_params:])},
                {"loss": loss})

    step.loss_graph = loss_graph  # for introspection/tests
    step.executor = executor
    return step


def init_graph_mlp_state(dims: Sequence[int], rng) -> dict:
    """Initialize IR-engine state with the SAME values as models.MLP.init
    (so the two engines are numerically comparable)."""
    from nezha_tpu.models.mlp import MLP

    model = MLP(in_features=dims[0], hidden=tuple(dims[1:-1]),
                num_classes=dims[-1])
    params = model.init(rng)["params"]
    vel = jax.tree_util.tree_map(lambda p: np.zeros_like(np.asarray(p)),
                                 params)
    return {"params": params, "vel": vel}


def onehot_shard_fn(num_classes: int):
    """Host-side batch transform: integer labels -> one-hot floats."""
    eye = np.eye(num_classes, dtype=np.float32)

    def shard(b):
        img = np.asarray(b["image"], np.float32)
        return {"image": img.reshape(img.shape[0], -1),
                "onehot": eye[np.asarray(b["label"])]}

    return shard
