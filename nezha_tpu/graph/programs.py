"""Training programs authored in the Graph IR.

This makes the IR load-bearing for benchmark config 1 (`mlp_mnist` with
``--engine graph``): the MLP forward, the cross-entropy loss, and the
momentum update are all *built as graphs*, the backward comes from
``jax.grad`` over the interpreted IR (the documented autograd path,
`graph/lower.py:grad_callable`), and the whole step executes through the
runtime ``Executor``'s compile cache — Graph -> StableHLO -> XLA end to end
(the north star's "lower the internal op graph to StableHLO").
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import numpy as np

from nezha_tpu.graph.graph import Graph
from nezha_tpu.graph.lower import to_callable
from nezha_tpu.runtime.executor import Executor

# Parameter order for an L-layer MLP: w0, b0, w1, b1, ..., wH, bH (head last)
# — matches models.MLP's {"fc0": {"w","b"}, ..., "head": {"w","b"}} layout.


def _leaf_dtype(leaf) -> str:
    """Dtype string of a param leaf without np.asarray's device-to-host
    copy (a full-model transfer at graph-build time when leaves live on
    device)."""
    return str(leaf.dtype) if hasattr(leaf, "dtype") else str(
        np.asarray(leaf).dtype)


def mlp_param_names(n_layers: int) -> Sequence[str]:
    names = [f"fc{i}" for i in range(n_layers - 1)] + ["head"]
    return names


def _mlp_layout(dims: Sequence[int]):
    """Shared param-layout scaffolding for the MLP step builders (single and
    dp must agree exactly or their parity guarantee is meaningless):
    (param shapes, flatten tree->list, unflatten list->tree)."""
    names = mlp_param_names(len(dims) - 1)
    shapes = [(din, dout) for din, dout in zip(dims[:-1], dims[1:])]
    shapes += [(dout,) for dout in dims[1:]]

    def flatten(tree) -> list:
        return [tree[n][k] for n in names for k in ("w", "b")]

    def unflatten(flat) -> dict:
        it = iter(flat)
        return {n: {"w": next(it), "b": next(it)} for n in names}

    return shapes, flatten, unflatten


def mlp_loss_graph(dims: Sequence[int], batch: int) -> Graph:
    """IR graph: (w0, b0, ..., image[B, in], onehot[B, classes]) -> loss.

    The label one-hot is a placeholder (host-side data transform), keeping
    the graph free of integer gather ops.
    """
    g = Graph("mlp_loss")
    ws, bs = [], []
    for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
        ws.append(g.placeholder((din, dout), name=f"w{i}"))
        bs.append(g.placeholder((dout,), name=f"b{i}"))
    x = g.placeholder((batch, dims[0]), name="image")
    onehot = g.placeholder((batch, dims[-1]), name="onehot")

    h = x
    for i, (w, b) in enumerate(zip(ws, bs)):
        h = (h @ w) + b
        if i < len(ws) - 1:
            h = g.relu(h)
    logp = g.log_softmax(h, axis=-1)
    nll = -g.mean(g.sum(logp * onehot, axis=1))
    g.output(nll)
    return g


def momentum_update_graph(shape: Sequence[int], lr: float,
                          beta: float) -> Graph:
    """IR graph: (param, velocity, grad) -> (new_param, new_velocity)."""
    g = Graph("momentum_update")
    p = g.placeholder(shape, name="param")
    v = g.placeholder(shape, name="velocity")
    grad = g.placeholder(shape, name="grad")
    v_new = v * beta + grad
    p_new = p - v_new * lr
    g.output(p_new, v_new)
    return g


def clip_scale_graph(shapes: Sequence[Tuple[int, ...]],
                     clip_norm: float) -> Graph:
    """IR graph: (*flat_grads) -> clip scale = min(1, C / (||g|| + 1e-6)).

    ``optim.clip_by_global_norm``'s exact math (same eps) authored as IR
    nodes so `--clip-norm --engine graph` stays inside the op graph. The IR
    has no min op; min(1, r) = 1 - relu(1 - r), exact for every r down to
    ~2^-24 and for all r >= 1 — crucially including huge clip_norms, where
    the algebraically-equal r - relu(r - 1) collapses to 0 (r-1 rounds to
    r once r > 2^24, so the subtraction cancels and every gradient would
    silently zero). Below r ~ 2^-24 this form underflows to exactly 0
    where jnp.minimum keeps ~1e-8 — both freeze training identically."""
    g = Graph("clip_scale")
    total = None
    for i, s in enumerate(shapes):
        gr = g.placeholder(s, name=f"g{i}")
        sq = g.sum(gr * gr)
        total = sq if total is None else total + sq
    norm = total ** 0.5
    r = g.constant(np.float32(clip_norm)) / (norm + 1e-6)
    g.output(-g.relu(-r + 1.0) + 1.0)
    return g


def scale_grad_graph(shape: Sequence[int]) -> Graph:
    """IR graph: (grad, scale) -> grad * scale (scalar broadcast)."""
    g = Graph("scale_grad")
    gr = g.placeholder(shape, name="grad")
    sc = g.placeholder((), name="scale")
    g.output(gr * sc)
    return g


def _make_clip(ordered_shapes, clip_norm):
    """(clip_fn, per-shape scale_fns); both None when clipping is off.
    ``ordered_shapes`` must match the flat-gradient order the step passes
    to clip_fn. Shared by every IR step builder so the clip math cannot
    drift between configs."""
    if clip_norm is None:
        return None, None
    ordered_shapes = [tuple(s) for s in ordered_shapes]
    clip_fn = to_callable(clip_scale_graph(ordered_shapes, clip_norm))
    scale_fns = {s: to_callable(scale_grad_graph(s))
                 for s in set(ordered_shapes)}
    return clip_fn, scale_fns


def _apply_clip(clip_fn, scale_fns, grads):
    if clip_fn is None:
        return grads
    sc = clip_fn(*grads)
    return [scale_fns[tuple(np.shape(g_))](g_, sc) for g_ in grads]


def _dp_world(mesh, axis: str, global_batch: int) -> Tuple[int, int]:
    """(world, local_batch) for a dp graph engine; loud on ragged batch."""
    world = int(mesh.shape[axis])
    if global_batch % world:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"mesh axis {axis}={world}")
    return world, global_batch // world


def _dp_per_shard(vg, upd_fns, flatten_params, feed_keys, axis):
    """Shared dp per-shard body (MLP and ResNet engines must not drift):
    flatten -> loss+grads -> per-shape dp update graphs (the all_reduce is
    an IR node inside them) -> pmean'd loss metric.

    ``flatten_params(tree) -> (flat_list, unflatten_fn)``."""
    from jax import lax

    def per_shard(state, b):
        flat_p, unf = flatten_params(state["params"])
        flat_v, _ = flatten_params(state["vel"])
        loss, grads = vg(*flat_p, *[b[k] for k in feed_keys])
        new = [upd_fns[tuple(p_.shape)](p_, v_, gr)
               for p_, v_, gr in zip(flat_p, flat_v, grads)]
        new_p, new_v = zip(*new)
        # Metric only (program semantics live in the IR): each shard's
        # loss is its local-batch mean; the global mean is their pmean.
        loss = lax.pmean(loss, axis)
        return ({"params": unf(list(new_p)), "vel": unf(list(new_v))}, loss)

    return per_shard


def _dp_shard_map(mesh, axis, per_shard, state, b):
    """shard_map wiring shared by the dp graph engines: state replicated,
    batch leading-dim sharded over ``axis``."""
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    tmap = jax.tree_util.tree_map
    return shard_map(per_shard, mesh=mesh,
                     in_specs=(tmap(lambda _: P(), state),
                               tmap(lambda _: P(axis), b)),
                     out_specs=(tmap(lambda _: P(), state), P()))


def dp_momentum_update_graph(shape: Sequence[int], lr: float, beta: float,
                             axis_name: str, world: int) -> Graph:
    """IR graph: (param, velocity, LOCAL grad) -> (new_param, new_velocity)
    with the gradient all-reduce authored as an IR node.

    ``all_reduce(grad) * (1/world)`` is the mean over the ``axis_name`` mesh
    axis (the IR ships a sum collective; the static world size makes it a
    mean) — the reference's backward -> collective all-reduce -> optimizer
    call stack (SURVEY.md §3 call stack 2) expressed entirely inside the op
    graph, so lowering emits a real ``stablehlo.all_reduce`` between the
    gradient and the update math."""
    g = Graph("dp_momentum_update")
    p = g.placeholder(shape, name="param")
    v = g.placeholder(shape, name="velocity")
    grad_local = g.placeholder(shape, name="grad_local")
    grad = g.all_reduce(grad_local, axis_name=axis_name) * (1.0 / world)
    v_new = v * beta + grad
    p_new = p - v_new * lr
    g.output(p_new, v_new)
    return g


def make_mlp_graph_dp_train_step(dims: Sequence[int], global_batch: int,
                                 lr: float, mesh, beta: float = 0.9,
                                 axis: str = "dp",
                                 executor: Executor = None):
    """Data-parallel IR engine (VERDICT r3 missing #4): the per-shard step —
    IR loss graph -> ``jax.grad`` -> IR update graphs whose ``all_reduce``
    nodes lower to XLA collectives — runs inside ``shard_map`` over
    ``mesh[axis]`` with the batch leading-dim sharded and params/velocity
    replicated. Numerically identical to the single-device graph engine on
    the same global batch (mean-of-shard-mean gradients == global mean).

    ``state``/``batch`` layouts match :func:`make_mlp_graph_train_step`;
    place batches with ``parallel.shard_batch(mesh, b)`` (or feed host
    arrays and let jit shard them).
    """
    executor = executor or Executor()
    world, local_batch = _dp_world(mesh, axis, global_batch)
    loss_graph = mlp_loss_graph(dims, local_batch)
    loss_fn = to_callable(loss_graph)
    n_params = 2 * (len(dims) - 1)
    vg = jax.value_and_grad(loss_fn, argnums=tuple(range(n_params)))

    shapes, flatten, unflatten = _mlp_layout(dims)
    upd_fns = {s: to_callable(dp_momentum_update_graph(s, lr, beta, axis,
                                                       world))
               for s in {tuple(s) for s in shapes}}
    per_shard = _dp_per_shard(
        vg, upd_fns, lambda tree: (flatten(tree), unflatten),
        feed_keys=("image", "onehot"), axis=axis)

    mapped = None

    def step(state, b):
        nonlocal mapped
        if mapped is None:
            mapped = _dp_shard_map(mesh, axis, per_shard, state, b)
        new_state, loss = executor.run(mapped, state, b)
        return new_state, {"loss": loss}

    step.loss_graph = loss_graph
    step.update_graph = dp_momentum_update_graph(
        tuple(shapes[0]), lr, beta, axis, world)  # introspection/tests
    step.executor = executor
    return step


# ---------------------------------------------------------------------------
# ZeRO-1 authored in the IR (VERDICT r3 weak #3 / SURVEY §2: the
# reference's second attested parallelism mode — "grad reduce-scatter +
# weight all-gather" — expressed as graph nodes, not library calls). The
# optimizer state lives as ONE flat fp32 vector sharded over dp; each step
# is three IR programs composed per shard:
#
#   gather:  param_chunk --all_gather--> flat --slice/reshape--> tensors
#   flatten: grad tensors --reshape/concat(+zero pad)--> flat grads
#   update:  flat grads --reduce_scatter * 1/world--> local mean-grad
#            chunk -> momentum update on the LOCAL param/velocity chunk
#
# so both wire collectives (`all_gather`, `reduce_scatter`) lower from the
# op graph itself into stablehlo.


def zero1_flatten_grads_graph(shapes: Sequence[Tuple[int, ...]],
                              n_pad: int) -> Graph:
    """IR graph: (*grad tensors) -> flat [n_pad] (zero-padded)."""
    g = Graph("zero1_flatten")
    pieces = []
    total = 0
    for i, s in enumerate(shapes):
        size = int(np.prod(s))
        total += size
        p = g.placeholder(s, name=f"g{i}")
        pieces.append(g.reshape(p, (size,)))
    if n_pad > total:
        pieces.append(g.constant(np.zeros(n_pad - total, np.float32)))
    g.output(g.concat(pieces, axis=0))
    return g


def zero1_gather_params_graph(shapes: Sequence[Tuple[int, ...]],
                              chunk_size: int, axis_name: str) -> Graph:
    """IR graph: (param_chunk [chunk_size]) --all_gather--> per-tensor
    params (the ZeRO-1 weight all-gather as an IR node)."""
    g = Graph("zero1_gather")
    chunk = g.placeholder((chunk_size,), name="param_chunk")
    flat = g.all_gather(chunk, axis_name=axis_name)
    outs, off = [], 0
    for s in shapes:
        size = int(np.prod(s))
        outs.append(g.reshape(g.slice(flat, (off,), (off + size,)), s))
        off += size
    g.output(*outs)
    return g


def zero1_update_graph(chunk_size: int, n_pad: int, lr: float, beta: float,
                       axis_name: str, world: int) -> Graph:
    """IR graph: (param_chunk, vel_chunk, flat_grads [n_pad]) ->
    (param_chunk', vel_chunk'): reduce_scatter to this rank's mean-grad
    chunk, then the momentum update on the LOCAL shard only — the
    optimizer state never exists unsharded (ZeRO-1's defining property)."""
    g = Graph("zero1_update")
    p = g.placeholder((chunk_size,), name="param_chunk")
    v = g.placeholder((chunk_size,), name="vel_chunk")
    fg = g.placeholder((n_pad,), name="flat_grads")
    gs = g.reduce_scatter(fg, axis_name=axis_name) * (1.0 / world)
    v2 = v * beta + gs
    p2 = p - v2 * lr
    g.output(p2, v2)
    return g


def _mlp_grad_shapes(dims: Sequence[int]):
    """Gradient order w0,b0,w1,b1,... (the loss graph's placeholder
    order)."""
    return [s for din, dout in zip(dims[:-1], dims[1:])
            for s in ((din, dout), (dout,))]


def init_graph_mlp_zero1_state(dims: Sequence[int], rng, mesh,
                               axis: str = "dp") -> dict:
    """{"flat": [n_pad] P(axis), "vel": same} — module-identical init
    values, flattened in gradient order, zero-padded to a world multiple,
    physically sharded over ``axis``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nezha_tpu.models.mlp import MLP

    params = MLP(dims[0], tuple(dims[1:-1]), dims[-1]).init(rng)["params"]
    _, flatten, _ = _mlp_layout(dims)
    flat = np.concatenate([np.asarray(x, np.float32).reshape(-1)
                           for x in flatten(params)])
    world = int(mesh.shape[axis])
    n_pad = -(-flat.size // world) * world
    flat = np.pad(flat, (0, n_pad - flat.size))
    sh = NamedSharding(mesh, P(axis))
    return {"flat": jax.device_put(flat, sh),
            "vel": jax.device_put(np.zeros_like(flat), sh)}


def make_mlp_graph_zero1_train_step(dims: Sequence[int], global_batch: int,
                                    lr: float, mesh, beta: float = 0.9,
                                    axis: str = "dp",
                                    executor: Executor = None):
    """ZeRO-1 IR engine over ``init_graph_mlp_zero1_state`` state: the
    gather/flatten/update programs above, shard_map'd over ``mesh[axis]``
    with state 1-D-sharded and the batch leading-dim sharded. Numerically
    identical to the single-device graph engine on the same global batch
    (reduce-scattered mean grads == the global mean, chunk by chunk)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    executor = executor or Executor()
    world, local_batch = _dp_world(mesh, axis, global_batch)
    shapes = _mlp_grad_shapes(dims)
    n = sum(int(np.prod(s)) for s in shapes)
    n_pad = -(-n // world) * world
    chunk = n_pad // world

    loss_fn = to_callable(mlp_loss_graph(dims, local_batch))
    n_params = 2 * (len(dims) - 1)
    vg = jax.value_and_grad(loss_fn, argnums=tuple(range(n_params)))
    gather_fn = to_callable(zero1_gather_params_graph(shapes, chunk, axis))
    flatten_fn = to_callable(zero1_flatten_grads_graph(shapes, n_pad))
    upd_fn = to_callable(zero1_update_graph(chunk, n_pad, lr, beta, axis,
                                            world))

    def per_shard(state, b):
        params = gather_fn(state["flat"])          # weight all-gather (IR)
        loss, grads = vg(*params, b["image"], b["onehot"])
        flat_g = flatten_fn(*grads)
        p2, v2 = upd_fn(state["flat"], state["vel"], flat_g)
        loss = lax.pmean(loss, axis)               # metric only
        return {"flat": p2, "vel": v2}, loss

    mapped = None

    def step(state, b):
        nonlocal mapped
        if mapped is None:
            tmap = jax.tree_util.tree_map
            mapped = shard_map(
                per_shard, mesh=mesh,
                in_specs=({"flat": P(axis), "vel": P(axis)},
                          tmap(lambda _: P(axis), b)),
                out_specs=({"flat": P(axis), "vel": P(axis)}, P()))
        new_state, loss = executor.run(mapped, state, b)
        return new_state, {"loss": loss}

    step.executor = executor
    step.update_graph = zero1_update_graph(chunk, n_pad, lr, beta, axis,
                                           world)
    step.gather_graph = zero1_gather_params_graph(shapes, chunk, axis)
    return step


def materialize_graph_zero1_params(dims: Sequence[int], state) -> dict:
    """Host-side: sharded flat state -> the module-layout param tree (for
    checkpoints-to-eval/export interchange)."""
    flat = np.asarray(state["flat"])
    shapes = _mlp_grad_shapes(dims)
    _, _, unflatten = _mlp_layout(dims)
    leaves, off = [], 0
    for s in shapes:
        size = int(np.prod(s))
        leaves.append(flat[off:off + size].reshape(s))
        off += size
    return unflatten(leaves)


def make_mlp_graph_train_step(dims: Sequence[int], batch: int, lr: float,
                              beta: float = 0.9,
                              clip_norm: float = None,
                              executor: Executor = None):
    """Trainer-compatible ``step(state, batch) -> (state, metrics)`` whose
    forward/loss/update are Graph IR programs.

    ``state`` = {"params": {fcN/head: {"w","b"}}, "vel": same-shaped}.
    ``batch`` = {"image": [B, in], "onehot": [B, classes]} (see
    :func:`onehot_shard_fn`). ``clip_norm``: IR-authored global-norm
    gradient clipping (:func:`clip_scale_graph`).
    """
    executor = executor or Executor()
    loss_graph = mlp_loss_graph(dims, batch)
    loss_fn = to_callable(loss_graph)
    n_params = 2 * (len(dims) - 1)
    vg = jax.value_and_grad(loss_fn, argnums=tuple(range(n_params)))

    # One update graph per distinct parameter shape (placeholders are
    # shape-typed); the Executor dedupes compiles by graph fingerprint.
    shapes, flatten, unflatten = _mlp_layout(dims)
    upd_fns: Dict[Tuple[int, ...], callable] = {}
    for s in {tuple(s) for s in shapes}:
        upd_fns[s] = to_callable(momentum_update_graph(s, lr, beta))
    # Gradient order is w0,b0,w1,b1,... (flatten order), not `shapes` order.
    clip_fn, scale_fns = _make_clip(_mlp_grad_shapes(dims), clip_norm)

    def whole_step(*flat_and_batch):
        flat = flat_and_batch[:2 * n_params]
        params, vels = flat[:n_params], flat[n_params:]
        image, onehot = flat_and_batch[-2:]
        loss, grads = vg(*params, image, onehot)
        grads = _apply_clip(clip_fn, scale_fns, grads)
        new_p, new_v = [], []
        for p, v, gr in zip(params, vels, grads):
            pn, vn = upd_fns[tuple(p.shape)](p, v, gr)
            new_p.append(pn)
            new_v.append(vn)
        return (loss, *new_p, *new_v)

    def step(state, b):
        flat_p = flatten(state["params"])
        flat_v = flatten(state["vel"])
        out = executor.run(whole_step, *flat_p, *flat_v,
                           b["image"], b["onehot"])
        loss, rest = out[0], out[1:]
        return ({"params": unflatten(rest[:n_params]),
                 "vel": unflatten(rest[n_params:])},
                {"loss": loss})

    step.loss_graph = loss_graph  # for introspection/tests
    step.executor = executor
    return step


# ---------------------------------------------------------------------------
# GPT-2 authored in the IR (benchmark config 3 through --engine graph):
# attention is COMPOSED from IR ops (matmul/softmax/transpose + an additive
# causal-mask constant), the loss is log_softmax + take_along (no [B,S,V]
# one-hot), and AdamW is an update graph with bias correction done via the
# IR's pow op on a step placeholder.


def gpt2_loss_graph(cfg, param_template, batch: int, seq: int,
                    compute_dtype: str = "float32") -> Graph:
    """IR graph: (*flat_params, inputs[B,S] i32, targets[B,S] i32) -> loss.

    ``flat_params`` follows ``jax.tree_util.tree_flatten`` order of the
    module's param tree, so module-initialized params feed straight in.
    Mirrors ``models.gpt2.GPT2.apply`` (dropout=0).
    ``cfg.attn_impl`` auto/flash emits the fused ``flash_attention`` IR
    node (Pallas kernel on TPU — the same production attention as the
    module engine); "xla" keeps attention fully composed in the IR.
    ``compute_dtype="bfloat16"`` authors the module bf16 policy in the
    IR: fp32 master params cast to bf16 at each use, activations bf16,
    layernorm statistics fp32 (the ``layernorm`` node upcasts
    internally), logits fp32 for the CE — gradients flow back to the
    fp32 placeholders through the cast nodes, exactly like jax.grad
    through a policy cast.
    """
    if cfg.dropout:
        raise ValueError("graph GPT-2 has no dropout path; build with "
                         "dropout=0")
    if seq > cfg.max_positions:
        # Same loud failure as GPT2.apply: the position-embedding gather
        # below would silently clamp past the table's last row.
        raise ValueError(f"sequence length {seq} exceeds max_positions "
                         f"{cfg.max_positions}")
    g = Graph("gpt2_loss")
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
        param_template)
    syms = [g.placeholder(np.shape(leaf),
                          _leaf_dtype(leaf),
                          name=jax.tree_util.keystr(path))
            for path, leaf in leaves_with_path]
    p = jax.tree_util.tree_unflatten(treedef, syms)
    inputs = g.placeholder((batch, seq), "int32", name="inputs")
    targets = g.placeholder((batch, seq), "int32", name="targets")

    bf16 = compute_dtype == "bfloat16"
    cc = (lambda t: g.cast(t, compute_dtype)) if bf16 else (lambda t: t)

    h_dim, nh = cfg.hidden_size, cfg.num_heads
    hd = h_dim // nh
    x = g.take(cc(p["wte"]["embedding"]), inputs, axis=0)      # [B,S,H]
    x = x + g.take(cc(p["wpe"]["embedding"]),
                   g.constant(np.arange(seq)), axis=0)          # + [S,H]
    # Attention: the fused node (cfg.attn_impl auto/flash — lowers to the
    # Pallas kernel on TPU, composed elsewhere; the IR path's production
    # attention, VERDICT r4 item 6) or fully composed ops ("xla").
    use_flash_node = cfg.attn_impl in ("auto", "flash")
    if not use_flash_node:
        causal = np.where(np.tri(seq, dtype=bool), 0.0,
                          -np.inf).astype(np.float32)
        mask = g.constant(causal)

    def heads(t):  # [B,S,H] -> [B,nh,S,hd]
        return g.transpose(g.reshape(t, (batch, seq, nh, hd)), (0, 2, 1, 3))

    for i in range(cfg.num_layers):
        blk = p[f"h{i}"]
        y = g.layernorm(x, cc(blk["ln_1"]["scale"]),
                        cc(blk["ln_1"]["bias"]))
        qkv = (y @ cc(blk["attn"]["qkv"]["w"])) + cc(blk["attn"]["qkv"]["b"])
        q = heads(g.slice(qkv, (0, 0, 0), (batch, seq, h_dim)))
        k = heads(g.slice(qkv, (0, 0, h_dim), (batch, seq, 2 * h_dim)))
        v = heads(g.slice(qkv, (0, 0, 2 * h_dim), (batch, seq, 3 * h_dim)))
        if use_flash_node:
            att = g.flash_attention(
                q, k, v, causal=True,
                impl="auto" if cfg.attn_impl == "auto" else "pallas")
        else:
            scores = (q @ g.transpose(k, (0, 1, 3, 2))) * (1.0 / hd ** 0.5)
            if bf16:
                # fp32 softmax stats, bf16 P·V — the module policy.
                att = g.cast(g.softmax(g.cast(scores, "float32") + mask,
                                       axis=-1), compute_dtype) @ v
            else:
                att = g.softmax(scores + mask, axis=-1) @ v
        o = g.reshape(g.transpose(att, (0, 2, 1, 3)),
                      (batch, seq, h_dim))
        x = x + (o @ cc(blk["attn"]["proj"]["w"])) \
            + cc(blk["attn"]["proj"]["b"])
        y = g.layernorm(x, cc(blk["ln_2"]["scale"]),
                        cc(blk["ln_2"]["bias"]))
        y = g.gelu((y @ cc(blk["mlp"]["fc"]["w"]))
                   + cc(blk["mlp"]["fc"]["b"]))
        x = x + (y @ cc(blk["mlp"]["proj"]["w"])) \
            + cc(blk["mlp"]["proj"]["b"])

    x = g.layernorm(x, cc(p["ln_f"]["scale"]), cc(p["ln_f"]["bias"]))
    logits = x @ g.transpose(cc(p["wte"]["embedding"]), (1, 0))  # tied head
    if bf16:
        # The module's fused-head discipline (ops.losses fused CE): the
        # logit GEMM stays bf16 and the fp32 upcast feeds ONLY the
        # logsumexp reductions + target gather — XLA fuses the cast into
        # both consumers, so fp32 [B,S,V] never materializes in HBM.
        xf = g.cast(logits, "float32")
        m = g.max(xf, axis=-1, keepdims=True)              # [B,S,1]
        lse = g.log(g.sum(g.exp(xf - m), axis=-1,
                          keepdims=True)) + m              # [B,S,1]
        tgt = g.take_along(xf, targets, axis=2)            # [B,S]
        nll = g.mean(g.reshape(lse, (batch, seq)) - tgt)
    else:
        logp = g.log_softmax(logits, axis=-1)
        nll = -g.mean(g.take_along(logp, targets, axis=2))
    g.output(nll)
    return g


def adamw_update_graph(shape: Sequence[int], b1=0.9, b2=0.999, eps=1e-8,
                       weight_decay=0.1, axis_name: str = None,
                       world: int = 1) -> Graph:
    """IR graph: (param, mu, nu, grad, step_f32, lr) -> (p', mu', nu').

    Matches ``optim.adamw``'s math (bias correction from the
    post-increment step, decoupled weight decay on every leaf). With
    ``axis_name`` set, the incoming gradient is a LOCAL shard and the
    all-reduce mean over the mesh axis is authored as an IR node — ONE
    body for both engines so single-device and dp AdamW cannot drift."""
    g = Graph("dp_adamw_update" if axis_name else "adamw_update")
    p = g.placeholder(shape, name="param")
    m = g.placeholder(shape, name="mu")
    v = g.placeholder(shape, name="nu")
    grad = g.placeholder(shape, name="grad")
    t = g.placeholder((), name="step")   # post-increment, fp32
    lr = g.placeholder((), name="lr")
    if axis_name is not None:
        grad = g.all_reduce(grad, axis_name=axis_name) * (1.0 / world)
    m2 = m * b1 + grad * (1 - b1)
    v2 = v * b2 + (grad * grad) * (1 - b2)
    c1 = -(g.constant(np.float32(b1)) ** t) + 1.0
    c2 = -(g.constant(np.float32(b2)) ** t) + 1.0
    d = (m2 / c1) / ((v2 / c2) ** 0.5 + eps) + p * weight_decay
    g.output(p - d * lr, m2, v2)
    return g


def dp_adamw_update_graph(shape: Sequence[int], axis_name: str, world: int,
                          b1=0.9, b2=0.999, eps=1e-8,
                          weight_decay=0.1) -> Graph:
    """The dp AdamW engine (GPT-2, BERT): delegates to
    :func:`adamw_update_graph` with the collective enabled — same
    collective shape as :func:`dp_momentum_update_graph`. ``axis_name``
    and ``world`` are required together (a defaulted world would turn the
    mean into a silent sum)."""
    return adamw_update_graph(shape, b1=b1, b2=b2, eps=eps,
                              weight_decay=weight_decay,
                              axis_name=axis_name, world=world)


def init_graph_gpt2_state(model, rng) -> dict:
    """Graph-engine GPT-2 state, initialized identically to the module."""
    params = model.init(rng)["params"]
    zeros = lambda t: jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.float32), t)
    return {"params": params, "mu": zeros(params), "nu": zeros(params),
            "step": np.zeros((), np.int32)}


def _make_adamw_ir_step(build_loss_graph, feed_keys: Tuple[str, ...],
                        shape_key: str, lr_schedule,
                        weight_decay: float, clip_norm: float = None,
                        mesh=None, axis: str = "dp",
                        executor: Executor = None):
    """Shared IR-engine AdamW trainer: ``build_loss_graph(template, batch,
    seq) -> Graph`` whose placeholders are (*flat_params, *feed_keys
    tensors); state = {"params", "mu", "nu", "step"}; graphs built per
    (batch, seq) of ``b[shape_key]`` on first use. One implementation so
    the per-model engines (GPT-2, BERT) cannot drift apart. ``clip_norm``:
    IR-authored global-norm clipping before the update graphs.

    ``mesh``: data-parallel over ``mesh[axis]`` — the loss graph builds at
    the LOCAL batch, the update graphs become
    :func:`dp_adamw_update_graph` (all_reduce as an IR node), and the
    whole step runs inside shard_map (state/scalars replicated, feeds
    leading-dim sharded). Mutually exclusive with ``clip_norm`` (the clip
    must see reduced gradients; the CLI rejects the combo).
    """
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    executor = executor or Executor()
    world = int(mesh.shape[axis]) if mesh is not None else 1
    if mesh is not None and clip_norm is not None:
        raise ValueError("clip_norm under graph-dp is unsupported (the "
                         "all_reduce lives inside the update graphs)")
    _built: Dict[Tuple[int, int], dict] = {}

    def build(params_template, batch, seq):
        loss_graph = build_loss_graph(params_template, batch, seq)
        loss_fn = to_callable(loss_graph)
        leaves = jax.tree_util.tree_leaves(params_template)
        n_params = len(leaves)
        vg = jax.value_and_grad(loss_fn, argnums=tuple(range(n_params)))
        shapes = {tuple(np.shape(l)) for l in leaves}
        if mesh is None:
            upd = {s: to_callable(adamw_update_graph(
                s, weight_decay=weight_decay)) for s in shapes}
        else:
            upd = {s: to_callable(dp_adamw_update_graph(
                s, weight_decay=weight_decay, axis_name=axis, world=world))
                for s in shapes}
        clip_fn, scale_fns = _make_clip(
            [np.shape(l) for l in leaves], clip_norm)

        def whole_step(*args):
            flat = args[:3 * n_params]
            ps, ms, vs = (flat[:n_params], flat[n_params:2 * n_params],
                          flat[2 * n_params:])
            t_f32, lr = args[3 * n_params:3 * n_params + 2]
            feeds = args[3 * n_params + 2:]
            loss, grads = vg(*ps, *feeds)
            grads = _apply_clip(clip_fn, scale_fns, grads)
            new = [upd[tuple(x.shape)](x, m, v, gr, t_f32, lr)
                   for x, m, v, gr in zip(ps, ms, vs, grads)]
            new_p, new_m, new_v = zip(*new)
            if mesh is not None:
                loss = lax.pmean(loss, axis)  # metric only
            return (loss, *new_p, *new_m, *new_v)

        if mesh is not None:
            n_feeds = len(feed_keys)
            whole_step = shard_map(
                whole_step, mesh=mesh,
                in_specs=(P(),) * (3 * n_params + 2) + (P(axis),) * n_feeds,
                out_specs=(P(),) * (1 + 3 * n_params))
        return {"whole_step": whole_step, "n_params": n_params,
                "loss_graph": loss_graph}

    def step(state, b):
        batch, seq = b[shape_key].shape[:2]
        if batch % world:
            raise ValueError(f"global batch {batch} not divisible by "
                             f"mesh axis {axis}={world}")
        if (batch, seq) not in _built:
            _built[(batch, seq)] = build(state["params"], batch // world,
                                         seq)
        so = _built[(batch, seq)]
        n = so["n_params"]
        flat_p, treedef = jax.tree_util.tree_flatten(state["params"])
        flat_m = jax.tree_util.tree_leaves(state["mu"])
        flat_v = jax.tree_util.tree_leaves(state["nu"])
        t = int(state["step"])
        lr = np.float32(lr_schedule(t))       # module: lr from PRE-increment
        t_f32 = np.float32(t + 1)             # bias correction: post-increment
        out = executor.run(so["whole_step"], *flat_p, *flat_m, *flat_v,
                           t_f32, lr, *[b[k] for k in feed_keys])
        loss, rest = out[0], out[1:]
        unf = lambda leaves: jax.tree_util.tree_unflatten(treedef, leaves)
        return ({"params": unf(rest[:n]), "mu": unf(rest[n:2 * n]),
                 "nu": unf(rest[2 * n:]),
                 "step": np.asarray(t + 1, np.int32)},
                {"loss": loss})

    step.executor = executor
    step._built = _built  # introspection/tests
    return step


def make_gpt2_graph_train_step(model, lr_schedule, weight_decay: float = 0.1,
                               clip_norm: float = None, mesh=None,
                               executor: Executor = None,
                               compute_dtype: str = "float32"):
    """Trainer-compatible step over ``init_graph_gpt2_state`` state; batches
    are {"inputs": [B,S] i32, "targets": [B,S] i32} (see
    :func:`lm_shard_fn`). Graphs are built per batch shape on first use.
    ``mesh``: dp over the mesh's "dp" axis (IR all_reduce).
    ``compute_dtype="bfloat16"``: the module bf16 policy authored in the
    IR (fp32 master params; see :func:`gpt2_loss_graph`)."""
    cfg = model.cfg
    return _make_adamw_ir_step(
        lambda tmpl, batch, seq: gpt2_loss_graph(
            cfg, tmpl, batch, seq, compute_dtype=compute_dtype),
        feed_keys=("inputs", "targets"), shape_key="inputs",
        lr_schedule=lr_schedule, weight_decay=weight_decay,
        clip_norm=clip_norm, mesh=mesh, executor=executor)


def lm_shard_fn():
    """Host-side batch transform: {"tokens": [B,S+1]} -> inputs/targets."""

    def shard(b):
        toks = np.asarray(b["tokens"], np.int32)
        return {"inputs": toks[:, :-1],
                "targets": np.ascontiguousarray(toks[:, 1:])}

    return shard


# ---------------------------------------------------------------------------
# BERT authored in the IR (benchmark config 4's model through --engine
# graph, single-device): post-LN encoder, erf GELU, additive padding mask
# fed as a placeholder, MLM loss masked via host-prepared safe-labels +
# mask (the IR needs no comparison ops that way).


def bert_loss_graph(cfg, param_template, batch: int, seq: int) -> Graph:
    """IR graph: (*flat_params, tokens[B,S] i32, segment_ids[B,S] i32,
    attn_mask[B,1,1,S] f32 additive, safe_labels[B,S] i32,
    label_mask[B,S] f32) -> masked-mean MLM loss.

    Mirrors ``models.bert.Bert.apply`` + ``mlm_loss`` (ignore_index=-100
    becomes the host-side safe_labels/label_mask pair)."""
    if cfg.dropout:
        raise ValueError("graph BERT has no dropout path; build with "
                         "dropout=0")
    if seq > cfg.max_positions:
        # Same loud failure as Bert.apply (models/bert.py:116-120): the
        # position-embedding gather below would silently clamp.
        raise ValueError(f"sequence length {seq} exceeds max_positions "
                         f"{cfg.max_positions}")
    g = Graph("bert_mlm_loss")
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
        param_template)
    syms = [g.placeholder(np.shape(leaf), _leaf_dtype(leaf),
                          name=jax.tree_util.keystr(path))
            for path, leaf in leaves_with_path]
    p = jax.tree_util.tree_unflatten(treedef, syms)
    tokens = g.placeholder((batch, seq), "int32", name="tokens")
    segment_ids = g.placeholder((batch, seq), "int32", name="segment_ids")
    attn_mask = g.placeholder((batch, 1, 1, seq), name="attn_mask")
    safe_labels = g.placeholder((batch, seq), "int32", name="safe_labels")
    label_mask = g.placeholder((batch, seq), name="label_mask")

    h_dim, nh = cfg.hidden_size, cfg.num_heads
    hd = h_dim // nh
    eps = cfg.ln_eps

    def ln(prm, x):
        return g.layernorm(x, prm["scale"], prm["bias"], eps=eps)

    x = g.take(p["tok_emb"]["embedding"], tokens, axis=0)
    x = x + g.take(p["pos_emb"]["embedding"], g.constant(np.arange(seq)),
                   axis=0)
    x = x + g.take(p["type_emb"]["embedding"], segment_ids, axis=0)
    x = ln(p["emb_ln"], x)

    def heads(t):
        return g.transpose(g.reshape(t, (batch, seq, nh, hd)), (0, 2, 1, 3))

    for i in range(cfg.num_layers):
        lyr = p[f"layers{i}"]
        qkv = (x @ lyr["qkv"]["w"]) + lyr["qkv"]["b"]
        q = heads(g.slice(qkv, (0, 0, 0), (batch, seq, h_dim)))
        k = heads(g.slice(qkv, (0, 0, h_dim), (batch, seq, 2 * h_dim)))
        v = heads(g.slice(qkv, (0, 0, 2 * h_dim), (batch, seq, 3 * h_dim)))
        scores = (q @ g.transpose(k, (0, 1, 3, 2))) * (1.0 / hd ** 0.5)
        probs = g.softmax(scores + attn_mask, axis=-1)
        att = g.reshape(g.transpose(probs @ v, (0, 2, 1, 3)),
                        (batch, seq, h_dim))
        att = (att @ lyr["attn_out"]["w"]) + lyr["attn_out"]["b"]
        x = ln(lyr["attn_ln"], x + att)               # post-LN topology
        y = g.gelu((x @ lyr["fc"]["w"]) + lyr["fc"]["b"], approximate=False)
        y = (y @ lyr["fc_out"]["w"]) + lyr["fc_out"]["b"]
        x = ln(lyr["out_ln"], x + y)

    y = g.gelu((x @ p["mlm_dense"]["w"]) + p["mlm_dense"]["b"],
               approximate=False)
    y = ln(p["mlm_ln"], y)
    logits = (y @ g.transpose(p["tok_emb"]["embedding"], (1, 0))
              ) + p["mlm_bias"]
    logp = g.log_softmax(logits, axis=-1)
    picked = g.take_along(logp, safe_labels, axis=2)
    # masked mean; max(count, 1) = relu(count - 1) + 1 for count >= 0.
    count = g.sum(label_mask)
    nll = -(g.sum(picked * label_mask) / (g.relu(count + (-1.0)) + 1.0))
    g.output(nll)
    return g


def bert_shard_fn():
    """Host-side transform of BERT MLM batches into the graph's feeds.

    ``segment_ids`` is required (the IR program always adds type
    embeddings, matching the module path WITH segments — defaulting them
    to zeros would silently diverge from a module run without segments).
    ``padding_mask`` may be absent: all-attendable == additive zeros."""

    def shard(b):
        tokens = np.asarray(b["tokens"], np.int32)
        labels = np.asarray(b["labels"], np.int32)
        pad = np.asarray(b.get("padding_mask",
                               np.ones_like(tokens, bool)), bool)
        attn = np.where(pad, 0.0, -1e30).astype(np.float32)
        return {
            "tokens": tokens,
            "segment_ids": np.asarray(b["segment_ids"], np.int32),
            "attn_mask": attn[:, None, None, :],
            "safe_labels": np.where(labels == -100, 0, labels).astype(
                np.int32),
            "label_mask": (labels != -100).astype(np.float32),
        }

    return shard


def init_graph_bert_state(model, rng) -> dict:
    """Graph-engine BERT state (AdamW slots), module-identical init."""
    return init_graph_gpt2_state(model, rng)


def make_bert_graph_train_step(model, lr_schedule,
                               weight_decay: float = 0.01,
                               clip_norm: float = None, mesh=None,
                               executor: Executor = None):
    """Trainer-compatible step over ``init_graph_bert_state`` state;
    batches from :func:`bert_shard_fn`. ``mesh``: dp (IR all_reduce)."""
    cfg = model.cfg
    return _make_adamw_ir_step(
        lambda tmpl, batch, seq: bert_loss_graph(cfg, tmpl, batch, seq),
        feed_keys=("tokens", "segment_ids", "attn_mask", "safe_labels",
                   "label_mask"),
        shape_key="tokens", lr_schedule=lr_schedule,
        weight_decay=weight_decay, clip_norm=clip_norm, mesh=mesh,
        executor=executor)


# ---------------------------------------------------------------------------
# ResNet authored in the IR (benchmark config 2 through --engine graph):
# conv2d/batchnorm/max_pool2d/relu/mean IR ops compose the bottleneck
# topology of models.resnet.ResNet; training-mode batch statistics only
# (running stats for eval are the module engine's concern).


def resnet_loss_graph(stage_sizes: Sequence[int], param_template,
                      batch: int, size: int) -> Graph:
    """IR graph: (*flat_params, image[B,H,W,3], labels[B] i32) -> loss.

    Mirrors ``models.resnet.ResNet.apply`` in training mode (batch-stat
    batchnorm). ``flat_params`` follows tree_flatten order of the module's
    param tree.
    """
    g = Graph("resnet_loss")
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
        param_template)
    syms = [g.placeholder(np.shape(leaf), _leaf_dtype(leaf),
                          name=jax.tree_util.keystr(path))
            for path, leaf in leaves_with_path]
    p = jax.tree_util.tree_unflatten(treedef, syms)
    image = g.placeholder((batch, size, size, 3), name="image")
    labels = g.placeholder((batch,), "int32", name="labels")

    def conv(prm, x, stride):
        return g.conv2d(x, prm["w"], stride=(stride, stride), padding="SAME")

    def bn(prm, x):
        return g.batchnorm(x, prm["scale"], prm["bias"])

    x = g.relu(bn(p["stem_bn"], conv(p["stem_conv"], image, 2)))
    x = g.max_pool2d(x, 3, 2, "SAME")

    # Same block/channel bookkeeping as ResNet.__init__.
    in_ch, idx = 64, 0
    for stage, n_blocks in enumerate(stage_sizes):
        base = 64 * (2 ** stage)
        out_ch = base * 4
        for b in range(n_blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            blk = p[f"blocks{idx}"]
            y = g.relu(bn(blk["bn1"], conv(blk["conv1"], x, 1)))
            y = g.relu(bn(blk["bn2"], conv(blk["conv2"], y, stride)))
            y = bn(blk["bn3"], conv(blk["conv3"], y, 1))
            if (in_ch != out_ch) or (stride != 1):
                sc = bn(blk["proj_bn"], conv(blk["proj"], x, stride))
            else:
                sc = x
            x = g.relu(y + sc)
            in_ch = out_ch
            idx += 1

    x = g.mean(x, axis=(1, 2))                       # global average pool
    logits = (x @ p["head"]["w"]) + p["head"]["b"]
    logp = g.log_softmax(logits, axis=-1)
    nll = -g.mean(g.take_along(logp, labels, axis=1))
    g.output(nll)
    return g


def init_graph_resnet_state(model, rng) -> dict:
    """Graph-engine ResNet state, initialized identically to the module
    (including the zero-init of each block's last BN scale)."""
    params = model.init(rng)["params"]
    vel = jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.asarray(x).dtype), params)
    return {"params": params, "vel": vel}


def make_resnet_graph_dp_train_step(model, global_batch: int, lr: float,
                                    mesh, beta: float = 0.9,
                                    axis: str = "dp",
                                    executor: Executor = None):
    """Data-parallel IR ResNet: per-shard loss graph -> ``jax.grad`` ->
    :func:`dp_momentum_update_graph` (the gradient all-reduce as an IR
    node), shard_map'd over ``mesh[axis]`` — the conv path through the
    same op-graph + collectives shape as the MLP dp engine.

    BatchNorm uses per-shard batch statistics — the standard DP-BN
    semantics, identical to the module engine's dp step (which also
    normalizes per-replica and only pmean-syncs the RUNNING stats this
    training-mode engine doesn't track). Consequence: a dp run matches a
    single-device run exactly only when every shard sees identical rows
    (how the test pins the all-reduce), and statistically otherwise.

    ``state`` layouts match :func:`make_resnet_graph_train_step`; batch =
    {"image": [B,H,W,3], "labels": [B]} placed via ``parallel.shard_batch``;
    graphs build per image size on first use.
    """
    executor = executor or Executor()
    world, local_batch = _dp_world(mesh, axis, global_batch)
    _built: Dict[int, callable] = {}

    def build(params_template, size):
        loss_graph = resnet_loss_graph(model.stage_sizes, params_template,
                                       local_batch, size)
        loss_fn = to_callable(loss_graph)
        leaves = jax.tree_util.tree_leaves(params_template)
        n_params = len(leaves)
        vg = jax.value_and_grad(loss_fn, argnums=tuple(range(n_params)))
        shapes = {tuple(np.shape(l)) for l in leaves}
        upd = {s: to_callable(dp_momentum_update_graph(s, lr, beta, axis,
                                                       world))
               for s in shapes}

        def flatten_params(tree):
            flat, treedef = jax.tree_util.tree_flatten(tree)
            return flat, (lambda ls:
                          jax.tree_util.tree_unflatten(treedef, ls))

        return _dp_per_shard(vg, upd, flatten_params,
                             feed_keys=("image", "labels"), axis=axis)

    def step(state, b):
        size = b["image"].shape[1]
        if size not in _built:
            _built[size] = _dp_shard_map(
                mesh, axis, build(state["params"], size), state, b)
        new_state, loss = executor.run(_built[size], state, b)
        return new_state, {"loss": loss}

    step.executor = executor
    return step


def make_resnet_graph_train_step(model, lr: float, beta: float = 0.9,
                                 clip_norm: float = None,
                                 executor: Executor = None):
    """Trainer-compatible step over ``init_graph_resnet_state`` state;
    batches are {"image": [B,H,W,3] f32, "labels": [B] i32} (see
    :func:`image_shard_fn`). SGD-momentum update graphs, one per shape."""
    executor = executor or Executor()
    _built: Dict[Tuple[int, int], dict] = {}

    def build(params_template, batch, size):
        loss_graph = resnet_loss_graph(model.stage_sizes, params_template,
                                       batch, size)
        loss_fn = to_callable(loss_graph)
        leaves = jax.tree_util.tree_leaves(params_template)
        n_params = len(leaves)
        vg = jax.value_and_grad(loss_fn, argnums=tuple(range(n_params)))
        shapes = {tuple(np.shape(l)) for l in leaves}
        upd = {s: to_callable(momentum_update_graph(s, lr, beta))
               for s in shapes}
        clip_fn, scale_fns = _make_clip(
            [np.shape(l) for l in leaves], clip_norm)

        def whole_step(*args):
            flat = args[:2 * n_params]
            ps, vs = flat[:n_params], flat[n_params:]
            image, labels = args[2 * n_params:]
            loss, grads = vg(*ps, image, labels)
            grads = _apply_clip(clip_fn, scale_fns, grads)
            new = [upd[tuple(x.shape)](x, v, gr)
                   for x, v, gr in zip(ps, vs, grads)]
            new_p, new_v = zip(*new)
            return (loss, *new_p, *new_v)

        return {"whole_step": whole_step, "n_params": n_params,
                "loss_graph": loss_graph}

    def step(state, b):
        batch, size = b["image"].shape[0], b["image"].shape[1]
        if (batch, size) not in _built:
            _built[(batch, size)] = build(state["params"], batch, size)
        so = _built[(batch, size)]
        n = so["n_params"]
        flat_p, treedef = jax.tree_util.tree_flatten(state["params"])
        flat_v = jax.tree_util.tree_leaves(state["vel"])
        out = executor.run(so["whole_step"], *flat_p, *flat_v,
                           b["image"], b["labels"])
        loss, rest = out[0], out[1:]
        unf = lambda leaves: jax.tree_util.tree_unflatten(treedef, leaves)
        return ({"params": unf(rest[:n]), "vel": unf(rest[n:])},
                {"loss": loss})

    step.executor = executor
    step._built = _built
    return step


def image_shard_fn():
    """Host-side batch transform for the graph ResNet step."""

    def shard(b):
        return {"image": np.asarray(b["image"], np.float32),
                "labels": np.asarray(b["label"], np.int32)}

    return shard


def init_graph_mlp_state(dims: Sequence[int], rng) -> dict:
    """Initialize IR-engine state with the SAME values as models.MLP.init
    (so the two engines are numerically comparable)."""
    from nezha_tpu.models.mlp import MLP

    model = MLP(in_features=dims[0], hidden=tuple(dims[1:-1]),
                num_classes=dims[-1])
    params = model.init(rng)["params"]
    vel = jax.tree_util.tree_map(lambda p: np.zeros_like(np.asarray(p)),
                                 params)
    return {"params": params, "vel": vel}


def onehot_shard_fn(num_classes: int):
    """Host-side batch transform: integer labels -> one-hot floats."""
    eye = np.eye(num_classes, dtype=np.float32)

    def shard(b):
        img = np.asarray(b["image"], np.float32)
        return {"image": img.reshape(img.shape[0], -1),
                "onehot": eye[np.asarray(b["label"])]}

    return shard
