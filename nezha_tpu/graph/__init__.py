"""Internal op graph IR.

The reference builds an internal op graph and the north star is to "lower
the internal op graph to StableHLO and JIT via XLA" (SURVEY.md §0). This
package is that component: a small explicit graph IR (`Graph`, `Node`) whose
programs trace through JAX to StableHLO text/bytecode and compile to XLA
executables, with autograd derived on the same graph via `jax.grad`.
"""

from nezha_tpu.graph.graph import Graph, Node
from nezha_tpu.graph.lower import to_callable, lower_stablehlo, compile_graph, grad_callable

__all__ = ["Graph", "Node", "to_callable", "lower_stablehlo", "compile_graph",
           "grad_callable"]
