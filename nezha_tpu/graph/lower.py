"""Lowering: Graph IR -> JAX callable -> StableHLO -> XLA executable.

This is the north-star path (SURVEY.md §0: "lower the internal op graph to
StableHLO and JIT-compile via XLA"). The graph interprets into pure JAX ops
(one topological pass — the graph is already in SSA order), `jax.jit.lower`
produces StableHLO, and `.compile()` yields the XLA executable whose
lifetime the runtime's `Executor` caches. Autograd: `grad_callable` wraps
the interpreted function with `jax.grad`, so the backward graph is derived
from the same IR.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu.graph.graph import Graph
from nezha_tpu.ops import activations


def _eval_node(node, vals, feeds):
    op, attrs = node.op, node.attrs
    x = [vals[i] for i in node.inputs]
    if op == "placeholder":
        return feeds[node.id]
    if op == "constant":
        return jnp.asarray(attrs["value"])
    if op == "add":
        return x[0] + x[1]
    if op == "sub":
        return x[0] - x[1]
    if op == "mul":
        return x[0] * x[1]
    if op == "div":
        return x[0] / x[1]
    if op == "neg":
        return -x[0]
    if op == "pow":
        return x[0] ** x[1]
    if op == "matmul":
        return x[0] @ x[1]
    if op == "conv2d":
        return lax.conv_general_dilated(
            x[0], x[1], window_strides=attrs["stride"], padding=attrs["padding"],
            feature_group_count=attrs.get("groups", 1),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if op == "relu":
        return jnp.maximum(x[0], 0)
    if op == "gelu":
        return activations.gelu(x[0], approximate=attrs.get("approximate",
                                                            True))
    if op == "tanh":
        return jnp.tanh(x[0])
    if op == "exp":
        return jnp.exp(x[0])
    if op == "log":
        return jnp.log(x[0])
    if op == "sigmoid":
        return lax.logistic(x[0])
    if op == "softmax":
        return activations.softmax(x[0], axis=attrs.get("axis", -1))
    if op == "log_softmax":
        return activations.log_softmax(x[0], axis=attrs.get("axis", -1))
    if op == "layernorm":
        xf = jnp.asarray(x[0], jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + attrs["eps"])
        return (y * x[1] + x[2]).astype(x[0].dtype)
    if op == "batchnorm":  # training-mode batch stats over N,H,W (NHWC)
        xf = jnp.asarray(x[0], jnp.float32)
        axes = tuple(range(xf.ndim - 1))
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        y = (xf - mean) * lax.rsqrt(var + attrs["eps"])
        return (y * x[1] + x[2]).astype(x[0].dtype)
    if op == "max_pool2d":
        from nezha_tpu.nn.layers import max_pool
        return max_pool(x[0], attrs["window"], attrs["stride"],
                        attrs["padding"])
    if op == "avg_pool2d":
        from nezha_tpu.nn.layers import avg_pool
        return avg_pool(x[0], attrs["window"], attrs["stride"],
                        attrs["padding"])
    if op == "reshape":
        return jnp.reshape(x[0], attrs["shape"])
    if op == "transpose":
        return jnp.transpose(x[0], attrs["perm"])
    if op == "broadcast_to":
        return jnp.broadcast_to(x[0], attrs["shape"])
    if op == "sum":
        return jnp.sum(x[0], axis=attrs["axis"], keepdims=attrs["keepdims"])
    if op == "mean":
        return jnp.mean(x[0], axis=attrs["axis"], keepdims=attrs["keepdims"])
    if op == "max":
        return jnp.max(x[0], axis=attrs["axis"], keepdims=attrs["keepdims"])
    if op == "cast":
        return x[0].astype(attrs["dtype"])
    if op == "concat":
        return jnp.concatenate(x, axis=attrs.get("axis", 0))
    if op == "slice":
        return lax.slice(x[0], attrs["start"], attrs["limit"],
                         attrs.get("strides"))
    if op == "take":
        return jnp.take(x[0], x[1], axis=attrs.get("axis", 0))
    if op == "take_along":
        axis = attrs["axis"]
        return jnp.take_along_axis(
            x[0], jnp.expand_dims(x[1], axis), axis=axis).squeeze(axis)
    if op == "flash_attention":
        impl = attrs.get("impl", "auto")
        causal, scale = attrs["causal"], attrs.get("scale")
        use_kernel = (impl == "pallas"
                      or (impl == "auto"
                          and jax.default_backend() == "tpu"))
        if use_kernel:
            from nezha_tpu.ops.pallas import flash_attention
            return flash_attention(x[0], x[1], x[2], causal=causal,
                                   scale=scale)
        # Composed fallback — identical math, S x S scores materialized.
        from nezha_tpu import ops as _ops
        s_q, s_k = x[0].shape[2], x[1].shape[2]
        mask = _ops.causal_mask(s_q, s_k) if causal else None
        return _ops.dot_product_attention(x[0], x[1], x[2], mask=mask,
                                          scale=scale)
    if op == "all_reduce":
        return lax.psum(x[0], attrs["axis_name"])
    if op == "reduce_scatter":
        return lax.psum_scatter(x[0], attrs["axis_name"], scatter_dimension=0,
                                tiled=True)
    if op == "all_gather":
        return lax.all_gather(x[0], attrs["axis_name"], axis=0, tiled=True)
    raise NotImplementedError(op)


def to_callable(graph: Graph) -> Callable:
    """Interpret the graph as a pure function of its placeholders (in
    declaration order). Single output -> value; multiple -> tuple."""

    def fn(*args):
        if len(args) != len(graph.placeholders):
            raise TypeError(
                f"graph {graph.name} takes {len(graph.placeholders)} inputs, "
                f"got {len(args)}")
        feeds = dict(zip(graph.placeholders, args))
        vals: List = [None] * len(graph.nodes)
        for node in graph.nodes:  # SSA order by construction
            vals[node.id] = _eval_node(node, vals, feeds)
        outs = tuple(vals[i] for i in graph.outputs)
        return outs[0] if len(outs) == 1 else outs

    fn.__name__ = graph.name
    return fn


def _example_args(graph: Graph):
    args = []
    for pid in graph.placeholders:
        attrs = graph.nodes[pid].attrs
        args.append(jax.ShapeDtypeStruct(attrs["shape"], jnp.dtype(attrs["dtype"])))
    return args


def lower_stablehlo(graph: Graph, example_args: Sequence = None) -> str:
    """Graph -> StableHLO module text."""
    fn = to_callable(graph)
    args = list(example_args) if example_args is not None else _example_args(graph)
    lowered = jax.jit(fn).lower(*args)
    return str(lowered.compiler_ir(dialect="stablehlo"))


def compile_graph(graph: Graph, example_args: Sequence = None):
    """Graph -> XLA executable (callable on device arrays)."""
    fn = to_callable(graph)
    args = list(example_args) if example_args is not None else _example_args(graph)
    return jax.jit(fn).lower(*args).compile()


def grad_callable(graph: Graph, wrt: Sequence[int] = (0,)) -> Callable:
    """d(first output)/d(placeholders[wrt]); the first output must be a
    scalar (a loss). Raises at trace time otherwise."""
    fn = to_callable(graph)
    argnums = tuple(wrt)
    if len(argnums) == 1:
        argnums = argnums[0]  # single grad, not a 1-tuple

    def scalar_loss(*a):
        out = fn(*a)
        loss = out[0] if isinstance(out, tuple) else out
        if getattr(loss, "ndim", 0) != 0:
            raise ValueError(
                f"grad_callable needs a scalar first output, got shape "
                f"{getattr(loss, 'shape', None)} from graph {graph.name!r}")
        return loss

    return jax.grad(scalar_loss, argnums=argnums)
