"""Graph IR: nodes, ops, graph construction.

A deliberately small SSA-ish IR: `Node`s name an op with input nodes and
static attributes; a `Graph` owns nodes, placeholders (inputs), and outputs.
No shapes are inferred here — shape/dtype checking happens when the graph is
traced by JAX during lowering (`nezha_tpu.graph.lower`), which reuses XLA's
own checking rather than duplicating it (SURVEY.md §1 "Op graph & autograd").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# Op registry: name -> callable(jax-arrays..., **attrs). Populated by lower.py.
OP_SET = (
    "placeholder", "constant",
    "add", "sub", "mul", "div", "neg", "pow",
    "matmul", "conv2d",
    "relu", "gelu", "tanh", "exp", "log", "sigmoid",
    "softmax", "log_softmax", "layernorm", "batchnorm",
    "max_pool2d", "avg_pool2d",
    "reshape", "transpose", "broadcast_to", "sum", "mean", "max",
    "cast", "concat", "slice", "take", "take_along",
    "all_reduce", "reduce_scatter", "all_gather",  # collective graph ops
    "flash_attention",  # fused-attention node -> Pallas kernel on TPU
)


@dataclasses.dataclass
class Node:
    id: int
    op: str
    inputs: Tuple[int, ...]
    attrs: Dict[str, Any]
    name: str

    def __repr__(self):
        ins = ", ".join(f"%{i}" for i in self.inputs)
        return f"%{self.id} = {self.op}({ins}) {self.attrs or ''}".rstrip()


class Graph:
    """Builder + container. Methods return `Node`s; operators are overloaded
    on a thin `Sym` wrapper for ergonomic construction."""

    def __init__(self, name: str = "graph"):
        self.name = name
        self.nodes: List[Node] = []
        self.placeholders: List[int] = []
        self.outputs: List[int] = []

    # -- construction ------------------------------------------------------

    def _add(self, op: str, inputs: Sequence["Sym | Node | int"],
             attrs: Optional[dict] = None, name: str = "") -> "Sym":
        if op not in OP_SET:
            raise ValueError(f"unknown op {op!r}")
        ids = tuple(self._node_id(i) for i in inputs)
        node = Node(len(self.nodes), op, ids, attrs or {}, name or op)
        self.nodes.append(node)
        return Sym(self, node.id)

    @staticmethod
    def _node_id(x) -> int:
        if isinstance(x, Sym):
            return x.id
        if isinstance(x, Node):
            return x.id
        return int(x)

    def placeholder(self, shape: Sequence[int], dtype: str = "float32",
                    name: str = "") -> "Sym":
        sym = self._add("placeholder", [],
                        {"shape": tuple(shape), "dtype": dtype}, name or "input")
        self.placeholders.append(sym.id)
        return sym

    def constant(self, value, name: str = "") -> "Sym":
        return self._add("constant", [], {"value": np.asarray(value)}, name or "const")

    def output(self, *syms: "Sym") -> None:
        self.outputs.extend(self._node_id(s) for s in syms)

    # -- op helpers --------------------------------------------------------

    def matmul(self, a, b):
        return self._add("matmul", [a, b])

    def conv2d(self, x, w, stride=(1, 1), padding="SAME", groups=1):
        return self._add("conv2d", [x, w],
                         {"stride": tuple(stride), "padding": padding,
                          "groups": groups})

    def flash_attention(self, q, k, v, causal: bool = True, scale=None,
                        impl: str = "auto"):
        """Fused scaled-dot-product attention over [B, H, S, D] operands.

        The one IR node that lowers to a custom kernel rather than
        composed jnp ops: ``impl="auto"`` picks the Pallas flash kernel
        on TPU backends (ops/pallas/flash_attention.py — fused fwd+bwd
        with a custom VJP, no S x S score materialization) and the
        composed softmax(QK^T)V elsewhere; "pallas"/"xla" force a path
        (pallas runs the kernel in interpret mode off-TPU — the parity-
        test hook)."""
        if impl not in ("auto", "pallas", "xla"):
            raise ValueError(f"unknown flash_attention impl {impl!r}")
        return self._add("flash_attention", [q, k, v],
                         {"causal": causal, "scale": scale, "impl": impl})

    def relu(self, x):
        return self._add("relu", [x])

    def gelu(self, x, approximate: bool = True):
        return self._add("gelu", [x], {"approximate": approximate})

    def softmax(self, x, axis=-1):
        return self._add("softmax", [x], {"axis": axis})

    def log_softmax(self, x, axis=-1):
        return self._add("log_softmax", [x], {"axis": axis})

    def layernorm(self, x, scale, bias, eps=1e-5):
        return self._add("layernorm", [x, scale, bias], {"eps": eps})

    def batchnorm(self, x, scale, bias, eps=1e-5):
        """Training-mode batch norm over N,H,W (NHWC): batch statistics
        computed in-graph; running-stat tracking is the trainer's concern."""
        return self._add("batchnorm", [x, scale, bias], {"eps": eps})

    def max_pool2d(self, x, window: int, stride: int, padding="SAME"):
        return self._add("max_pool2d", [x],
                         {"window": int(window), "stride": int(stride),
                          "padding": padding})

    def avg_pool2d(self, x, window: int, stride: int, padding="SAME"):
        return self._add("avg_pool2d", [x],
                         {"window": int(window), "stride": int(stride),
                          "padding": padding})

    def concat(self, xs, axis: int = 0):
        return self._add("concat", list(xs), {"axis": axis})

    def take(self, table, ids, axis=0):
        return self._add("take", [table, ids], {"axis": axis})

    def take_along(self, x, idx, axis):
        """Pick one element along ``axis`` per position of ``idx`` (the
        target-logit gather of a CE loss); output drops ``axis``."""
        return self._add("take_along", [x, idx], {"axis": axis})

    def slice(self, x, start, limit, strides=None):
        return self._add("slice", [x], {"start": tuple(start),
                                        "limit": tuple(limit),
                                        "strides": strides})

    def reshape(self, x, shape):
        return self._add("reshape", [x], {"shape": tuple(shape)})

    def transpose(self, x, perm):
        return self._add("transpose", [x], {"perm": tuple(perm)})

    def sum(self, x, axis=None, keepdims=False):
        return self._add("sum", [x], {"axis": axis, "keepdims": keepdims})

    def mean(self, x, axis=None, keepdims=False):
        return self._add("mean", [x], {"axis": axis, "keepdims": keepdims})

    def max(self, x, axis=None, keepdims=False):
        return self._add("max", [x], {"axis": axis, "keepdims": keepdims})

    def exp(self, x):
        return self._add("exp", [x])

    def log(self, x):
        return self._add("log", [x])

    def cast(self, x, dtype: str):
        return self._add("cast", [x], {"dtype": dtype})

    def all_reduce(self, x, axis_name: str = "dp"):
        return self._add("all_reduce", [x], {"axis_name": axis_name})

    def reduce_scatter(self, x, axis_name: str = "dp"):
        return self._add("reduce_scatter", [x], {"axis_name": axis_name})

    def all_gather(self, x, axis_name: str = "dp"):
        return self._add("all_gather", [x], {"axis_name": axis_name})

    # -- introspection -----------------------------------------------------

    def __repr__(self):
        lines = [f"graph {self.name}:"]
        lines += [f"  {n!r}" for n in self.nodes]
        lines.append(f"  outputs: {['%%%d' % o for o in self.outputs]}")
        return "\n".join(lines)


@dataclasses.dataclass(frozen=True)
class Sym:
    """Handle to a node within a graph, with operator sugar."""
    graph: Graph
    id: int

    def _bin(self, op, other):
        if not isinstance(other, Sym):
            other = self.graph.constant(other)
        return self.graph._add(op, [self, other])

    def __add__(self, other):
        return self._bin("add", other)

    def __sub__(self, other):
        return self._bin("sub", other)

    def __mul__(self, other):
        return self._bin("mul", other)

    def __truediv__(self, other):
        return self._bin("div", other)

    def __matmul__(self, other):
        return self._bin("matmul", other)

    def __pow__(self, other):
        return self._bin("pow", other)

    def __neg__(self):
        return self.graph._add("neg", [self])
