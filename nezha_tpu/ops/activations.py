"""Elementwise activations and numerically-stable softmax.

These map to VPU ops and fuse into neighbouring MXU ops under XLA; no
hand-scheduling needed (SURVEY.md §2: the reference's custom CUDA
elementwise/softmax kernels).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax


def relu(x):
    return jnp.maximum(x, 0)


def gelu(x, approximate: bool = True):
    """GPT-2/BERT use the tanh approximation."""
    if approximate:
        c = math.sqrt(2.0 / math.pi)
        return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))
    return 0.5 * x * (1.0 + lax.erf(x / math.sqrt(2.0)))


def silu(x):
    return x * lax.logistic(x)


def softmax(x, axis: int = -1):
    x_max = jnp.max(x, axis=axis, keepdims=True)
    unnorm = jnp.exp(x - lax.stop_gradient(x_max))
    return unnorm / jnp.sum(unnorm, axis=axis, keepdims=True)


def log_softmax(x, axis: int = -1):
    x_max = jnp.max(x, axis=axis, keepdims=True)
    shifted = x - lax.stop_gradient(x_max)
    return shifted - jnp.log(jnp.sum(jnp.exp(shifted), axis=axis, keepdims=True))
