"""Shared symmetric int8 quantization core: one audited implementation.

Two subsystems quantize with the same EQuARX-style recipe (PAPERS.md,
arXiv:2506.17615 — int8 payloads + per-block fp32 absmax scales, fp32
accumulation around the narrow storage/wire format):

- the **wire collectives** (``parallel/quantized.py``): gradients ride
  ICI as int8 + scales, dequantized and summed in fp32 per hop;
- the **paged KV cache** (``serve/slots.py`` + ``models/gpt2.py``):
  ``ServeConfig.kv_dtype="int8"`` stores K/V blocks as int8 with one
  fp32 scale per (block, head), dequantized inside the flash-decode
  kernel's block loop (``ops/pallas/decode_attention.py``).

Both call the functions here so there is exactly one rounding/clipping/
zero-guard policy to audit — a fix to either consumer's numerics lands
in both. Two entry shapes, one policy:

- :func:`quantize_blocks` / :func:`dequantize` — last-axis blocking
  (``[..., k*block] -> int8 [..., k, block] + scales [..., k, 1]``),
  the wire layout. Extracted VERBATIM from ``parallel/quantized.py``;
  tests pin the collectives bit-identical across the extraction.
- :func:`quantize_kv_block` / :func:`dequantize_kv_block` — trailing
  ``[..., bs, D]`` tiles quantized with ONE scale per leading index
  (per block, per head for ``[N, H, bs, D]`` pools), the KV-cache
  layout. Unlike the wire path (whose inputs are finite gradients by
  construction), KV writes can carry a NaN/inf burst (the PR-4 fault
  surface), so this path SANITIZES first — deterministic saturation,
  never a NaN scale poisoning a whole block.

Policy (shared):

- symmetric: ``q = clip(round(x / scale), -127, 127)``, scale =
  ``amax / 127`` — no zero point, so dequant is one fused multiply;
- zero guard: an all-zero block takes ``scale = 1.0`` (quantizes to
  exact zeros, dequantizes to exact zeros, no div-by-zero);
- sanitize (KV path only): ``NaN -> 0``, ``±inf -> ±float32 max`` —
  deterministic, and the serve layer's ``finite_rows`` tripwire still
  catches the burst at the logits (a saturated block is garbage data,
  not garbage CONTROL FLOW);
- scales are fp32; accumulation around the int8 format is the
  caller's job and is fp32 everywhere in this repo.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

QMAX = 127.0

# The ±inf saturation value. Deliberately BELOW float32 max: the scale
# ``amax / 127`` rounds up in fp32, so dequantizing the extreme element
# (``127 * scale``) of a block whose amax is exactly f32max would
# overflow to inf — saturating at 3e38 keeps the whole
# quantize->dequantize round trip finite (3e38 * (1 + 2^-23) is still
# representable).
SATURATE_MAX = 3.0e38


def _scale_of(amax: jax.Array) -> jax.Array:
    """absmax -> fp32 scale with the shared zero guard."""
    return jnp.where(amax > 0, amax / QMAX, 1.0).astype(jnp.float32)


# ------------------------------------------------------- wire layout
def quantize_blocks(x: jax.Array, block: int):
    """Symmetric per-block int8 quantization of ``x`` [..., k*block] ->
    (int8 [..., k, block], fp32 scales [..., k, 1]). The wire-collective
    layout — kept bit-identical to the pre-extraction
    ``parallel/quantized.py`` implementation (regression-pinned)."""
    xb = x.reshape(*x.shape[:-1], x.shape[-1] // block, block)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = _scale_of(amax)
    q = jnp.clip(jnp.round(xb / scale), -QMAX, QMAX).astype(jnp.int8)
    return q, scale


def dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    """int8 + broadcastable fp32 scales -> fp32."""
    return q.astype(jnp.float32) * scale


# --------------------------------------------------------- KV layout
def sanitize(x: jax.Array) -> jax.Array:
    """Deterministic non-finite saturation for quantizer inputs:
    ``NaN -> 0``, ``±inf -> ±SATURATE_MAX``. Without it a single
    non-finite element makes the block's absmax (hence scale, hence
    every dequantized element) NaN; with it the round trip stays
    finite end to end."""
    return jnp.nan_to_num(x.astype(jnp.float32), nan=0.0,
                          posinf=SATURATE_MAX, neginf=-SATURATE_MAX)


def quantize_kv_block(x: jax.Array):
    """Quantize trailing ``[..., bs, D]`` tiles with one absmax scale
    per leading index: ``x [..., bs, D]`` (any float dtype) ->
    ``(int8 [..., bs, D], fp32 scales [...])``. For a ``[N, H, bs, D]``
    KV block pool that is one scale per (block, head) — the
    ``[kv_num_blocks, H]`` scale buffers ``PagedSlotPool`` keeps
    alongside each pool. Inputs are sanitized (see :func:`sanitize`)."""
    xf = sanitize(x)
    amax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    scale = _scale_of(amax)
    q = jnp.clip(jnp.round(xf / scale[..., None, None]),
                 -QMAX, QMAX).astype(jnp.int8)
    return q, scale


def dequantize_kv_block(q: jax.Array, scale: jax.Array,
                        dtype=jnp.float32) -> jax.Array:
    """``int8 [..., bs, D]`` + ``fp32 scales [...]`` -> ``dtype``.
    The exact dequant both attention paths (Pallas kernel block loop
    and the gathered XLA fallback) apply, so ``decode_impl="xla"``
    stays a bit-faithful escape hatch for the int8 cache."""
    return (q.astype(jnp.float32)
            * scale[..., None, None]).astype(dtype)


def kv_roundtrip_error(x: jax.Array) -> jax.Array:
    """Max-abs dequant error of one KV-block quantization of ``x``
    (``[..., bs, D]``) -> scalar fp32. The ``serve.kv.quant_error``
    histogram's sample; bounded by ``amax / 254`` per block (half a
    quantization step) for finite inputs."""
    q, s = quantize_kv_block(x)
    return jnp.max(jnp.abs(sanitize(x)
                           - dequantize_kv_block(q, s, jnp.float32)))
