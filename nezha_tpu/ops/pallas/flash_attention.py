"""Flash attention as a Pallas TPU kernel.

Blockwise attention with online softmax: grid = (B, H, Q-blocks, K-blocks)
with the K dimension sequential ("arbitrary" semantics), VMEM scratch
carrying the running max/denominator/accumulator across K blocks, and causal
blocks skipped entirely before the diagonal. Q·Kᵀ and P·V hit the MXU in
fp32 accumulation; memory per program is O(block_q · block_k), never the
full S×S score matrix. (Reference composes attention from graph ops —
SURVEY.md §1; this is the TPU-fused production path.)

Backward: `jax.custom_vjp` with a recompute-based backward (standard
composed-op attention under `jax.vjp`). That keeps training numerically
exact; a fused backward kernel is a further optimization, the forward is
where inference/serving wins land.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30


def _pick_block(size: int, target: int) -> int:
    """Largest divisor of ``size`` that is <= target (block shapes must tile
    the sequence exactly)."""
    b = min(size, target)
    while size % b:
        b -= 1
    return b


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: skip blocks strictly above the diagonal.
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                  # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)                  # [bk, d]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_BIG)

        m_prev = m_scr[:, :1]                                # [bq, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                               # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                       # [bq, 1]
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """q, k, v: [B, H, S, D] -> [B, H, S, D].

    ``interpret=None`` auto-selects: compiled on TPU backends, interpreter
    elsewhere (so CPU tests run the same kernel code).
    """
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)[0]


def _flash_call(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bq = _pick_block(s_q, block_q)
    bk = _pick_block(s_k, block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    grid = (b, h, s_q // bq, s_k // bk)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    scratch = [pltpu.VMEM((bq, 128), jnp.float32),
               pltpu.VMEM((bq, 128), jnp.float32),
               pltpu.VMEM((bq, d), jnp.float32)]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
        **kwargs,
    )(q, k, v)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_call(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _reference_attention(q, k, v, causal, scale):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = s.shape[-2], s.shape[-1]
        i = jnp.arange(s_q)[:, None]
        j = jnp.arange(s_k)[None, :]
        s = jnp.where(j <= i + (s_k - s_q), s, _NEG_BIG)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference_attention(q_, k_, v_, causal, scale),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
