"""Flash attention as Pallas TPU kernels — fused forward AND backward.

Forward: blockwise attention with online softmax — grid = (B, H, Q-blocks,
K-blocks) with the K dimension sequential ("arbitrary" semantics), VMEM
scratch carrying the running max/denominator/accumulator across K blocks,
and causal blocks skipped entirely above the diagonal. Q·Kᵀ and P·V hit the
MXU in fp32 accumulation; memory per program is O(block_q · block_k), never
the full S×S score matrix. The training path additionally emits the
per-row logsumexp residual (lane-broadcast to 128, the TPU-native layout).

Backward (FlashAttention-2 style): two kernels that recompute P blockwise
from (q, k, lse) instead of materializing S×S —

* dQ kernel: grid (B, H, Q-blocks, K-blocks), K sequential, accumulating
  dq = Σ_k ds·K with ds = P∘(dP − δ), dP = dO·Vᵀ, δ = rowsum(dO∘O)
  computed in-register from the dO/O blocks (never materialized).
* dK/dV kernel: grid (B, H, K-blocks, Q-blocks), Q sequential, accumulating
  dv = Σ_q Pᵀ·dO and dk = Σ_q dsᵀ·Q.

(Reference composes attention from graph ops — SURVEY.md §1; these kernels
are the TPU-fused production path for long-context training, where the
S×S score matrix would dominate HBM.)

Measured on a v5e chip (fwd+bwd, bf16, causal): with the tuned block
sizes in ``_auto_blocks`` (whole-row q blocks at S<=1024, square 512s
beyond) this kernel beats XLA's fused composed attention at every
measured S — 12.8ms vs 14.2ms at S=1024 (B=8 H=12 D=64; +17% e2e on
GPT-2 train), 17.9ms vs 23.9ms at S=2048 — and is the only option at
S=32k, where the composed path fails to compile (the S×S scores alone
need ~24 GB HBM) while these kernels run the step in ~0.95 s. An
early untuned square-block build lost to XLA below S=16k; the
block-size policy is what closed that, so keep ``_auto_blocks`` in
sync with measurements. ``attn_impl="auto"`` selects flash on TPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

# The online-softmax scratch math and the package scalar helpers moved
# to ops/pallas/common.py (shared with the decode and prefill kernels);
# the aliases preserve this module's historical import surface
# (decode_attention once imported _compiler_params/_pick_block from
# here) and keep the kernel bodies bit-identical to the pre-factoring
# inline version.
from nezha_tpu.ops.pallas.common import (
    LANES as _LANES,
    NEG_BIG as _NEG_BIG,
    compiler_params as _compiler_params,
    pick_block as _pick_block,
    scratch_init as _scratch_init,
    softmax_block_update as _softmax_block_update,
    softmax_finalize as _softmax_finalize,
)


def _auto_blocks(s_q: int, s_k: int):
    """Measured-on-v5e defaults (bf16 fwd+bwd, B=8 H=12 D=64): at S<=1024 a
    single whole-row q block wins (grid overhead dominates; 12.8ms vs 14.2ms
    XLA at S=1024); at S>=2048 square 512 blocks win (17.9ms vs 23.9ms XLA
    at S=2048) — the causal block-skip starts paying once there are enough
    q rows to skip."""
    bq = s_q if s_q <= 1024 else 512
    return bq, 512


def _causal_mask(s, qi, ki, block_q, block_k):
    qpos = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = ki * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(kpos <= qpos, s, _NEG_BIG)


def _length_mask(s, ki, block_k, kv_len):
    """Mask key columns at positions >= kv_len (right-padding support).
    ``kv_len`` is a traced scalar read from the per-batch lengths input."""
    kpos = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(kpos < kv_len, s, _NEG_BIG)


# ---------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, lse_ref, m_scr, l_scr,
                acc_scr, *, scale: float, causal: bool, block_q: int,
                block_k: int):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        _scratch_init(m_scr, l_scr, acc_scr)

    # Causal: skip blocks strictly above the diagonal.
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _block():
        # Dots take the inputs' native dtype (bf16 on the training path —
        # double MXU rate vs fp32) and accumulate fp32; softmax stats and
        # the running accumulator stay fp32 throughout.
        q = q_ref[0, 0]                                      # [bq, d]
        k = k_ref[0, 0]                                      # [bk, d]
        v = v_ref[0, 0]                                      # [bk, d]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        if len_ref is not None:
            s = _length_mask(s, ki, block_k, len_ref[0, 0])
        _softmax_block_update(s, v, m_scr, l_scr, acc_scr)

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        _softmax_finalize(o_ref, m_scr, l_scr, acc_scr, lse_ref=lse_ref)


def _flash_call(q, k, v, causal, scale, block_q, block_k, interpret,
                return_lse: bool = False, kv_lengths=None):
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    if causal and s_q != s_k:
        # _causal_mask has no (s_k - s_q) diagonal offset, so rectangular
        # causal inputs would get a silently-wrong mask.
        raise ValueError(
            f"causal flash_attention requires s_q == s_k, got {s_q} != {s_k}")
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    auto_q, auto_k = _auto_blocks(s_q, s_k)
    bq = _pick_block(s_q, block_q or auto_q)
    bk = _pick_block(s_k, block_k or auto_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    has_len = kv_lengths is not None
    grid = (b, h, s_q // bq, s_k // bk)
    full = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=bq, block_k=bk)
    # The kernel's (len_ref, lse_ref) slots are optional: wrappers splice
    # None into whichever positional slots this call doesn't wire.
    if has_len and return_lse:
        kernel = full
    elif has_len:
        def kernel(q_ref, k_ref, v_ref, len_ref, o_ref, m_scr, l_scr,
                   acc_scr):
            full(q_ref, k_ref, v_ref, len_ref, o_ref, None, m_scr, l_scr,
                 acc_scr)
    elif return_lse:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                   acc_scr):
            full(q_ref, k_ref, v_ref, None, o_ref, lse_ref, m_scr, l_scr,
                 acc_scr)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr):
            full(q_ref, k_ref, v_ref, None, o_ref, None, m_scr, l_scr,
                 acc_scr)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    scratch = [pltpu.VMEM((bq, _LANES), jnp.float32),
               pltpu.VMEM((bq, _LANES), jnp.float32),
               pltpu.VMEM((bq, d), jnp.float32)]
    qo_spec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0))
    in_specs = [qo_spec, kv_spec, kv_spec]
    operands = [q, k, v]
    if has_len:
        # Lengths ride as a [B, LANES] int32 lane-broadcast (the TPU-native
        # small-operand layout); each program reads its batch row's scalar.
        len2d = jnp.broadcast_to(
            jnp.asarray(kv_lengths, jnp.int32)[:, None], (b, _LANES))
        in_specs.append(pl.BlockSpec((1, _LANES),
                                     lambda b_, h_, qi, ki: (b_, 0)))
        operands.append(len2d)
    out_specs = qo_spec
    out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    if return_lse:
        lse_spec = pl.BlockSpec((1, 1, bq, _LANES),
                                lambda b_, h_, qi, ki: (b_, h_, qi, 0))
        out_specs = [qo_spec, lse_spec]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((b, h, s_q, _LANES), jnp.float32)]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
        **kwargs,
    )(*operands)


# --------------------------------------------------------------- backward
def _recompute_p(q_ref, k_ref, lse_ref, qi, ki, scale, causal, bq, bk,
                 len_ref=None):
    q = q_ref[0, 0]                                          # [bq, d]
    k = k_ref[0, 0]                                          # [bk, d]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, qi, ki, bq, bk)
    if len_ref is not None:
        s = _length_mask(s, ki, bk, len_ref[0, 0])
    return jnp.exp(s - lse_ref[0, 0][:, :1])                 # [bq, bk]


def _ds_block(p, do, o, v, scale):
    """ds = p * (dp - delta) * scale, delta computed from the dO/O blocks.

    ``do``/``v`` native dtype for the MXU dot; ``p``/``delta`` fp32."""
    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)  # [bq, bk]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # [bq, 1]
    return p * (dp - delta) * scale


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, len_ref,
                   dq_ref, dq_scr, delta_scr, *, scale, causal, block_q,
                   block_k):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)
        # delta depends only on the q block — compute once per q row, not
        # once per K iteration.
        do = do_ref[0, 0].astype(jnp.float32)
        o = o_ref[0, 0].astype(jnp.float32)
        delta = jnp.sum(do * o, axis=-1, keepdims=True)      # [bq, 1]
        delta_scr[:] = jnp.broadcast_to(delta, delta_scr.shape)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _block():
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, scale, causal,
                         block_q, block_k, len_ref)
        do = do_ref[0, 0]
        v = v_ref[0, 0]
        k = k_ref[0, 0]
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_scr[:, :1]) * scale             # [bq, bk]
        dq_scr[:] += lax.dot_general(ds.astype(k.dtype), k,
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, len_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                    block_q, block_k):
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # Causal: q blocks entirely above the diagonal contribute nothing.
    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _block():
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, scale, causal,
                         block_q, block_k, len_ref)
        do = do_ref[0, 0]
        o = o_ref[0, 0]
        v = v_ref[0, 0]
        q = q_ref[0, 0]
        dv_scr[:] += lax.dot_general(p.astype(do.dtype), do,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        ds = _ds_block(p, do, o, v, scale)                   # [bq, bk]
        dk_scr[:] += lax.dot_general(ds.astype(q.dtype), q,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(qi == pl.num_programs(3) - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_call(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                    interpret, kv_lengths=None):
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    if causal and s_q != s_k:
        raise ValueError(
            f"causal flash_attention requires s_q == s_k, got {s_q} != {s_k}")
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    auto_q, auto_k = _auto_blocks(s_q, s_k)
    bq = _pick_block(s_q, block_q or auto_q)
    bk = _pick_block(s_k, block_k or auto_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qo_spec = lambda grid_q: pl.BlockSpec(
        (1, 1, bq, d), (lambda b_, h_, i, j: (b_, h_, i, 0)) if grid_q
        else (lambda b_, h_, i, j: (b_, h_, j, 0)))
    kv_spec = lambda grid_q: pl.BlockSpec(
        (1, 1, bk, d), (lambda b_, h_, i, j: (b_, h_, j, 0)) if grid_q
        else (lambda b_, h_, i, j: (b_, h_, i, 0)))
    lse_spec = lambda grid_q: pl.BlockSpec(
        (1, 1, bq, _LANES), (lambda b_, h_, i, j: (b_, h_, i, 0)) if grid_q
        else (lambda b_, h_, i, j: (b_, h_, j, 0)))

    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))

    # The lse residual is saved compactly as [B, H, S]; re-broadcast to the
    # TPU lane layout only transiently for the kernel calls (a per-layer
    # scratch, not a residual pinned across the whole forward pass).
    lse = jnp.broadcast_to(lse[..., None], lse.shape + (_LANES,))

    has_len = kv_lengths is not None
    operands = [q, k, v, o, do, lse]
    len_specs = []
    if has_len:
        len2d = jnp.broadcast_to(
            jnp.asarray(kv_lengths, jnp.int32)[:, None], (b, _LANES))
        operands.append(len2d)
        len_specs = [pl.BlockSpec((1, _LANES), lambda b_, h_, i, j: (b_, 0))]

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                                  block_q=bq, block_k=bk)
    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale,
                                   causal=causal, block_q=bq, block_k=bk)
    if not has_len:  # splice None into the kernels' len_ref slot
        dq_full, dkv_full = dq_kernel, dkv_kernel

        def dq_kernel(q_, k_, v_, o_, do_, lse_, dq_, s1, s2):
            dq_full(q_, k_, v_, o_, do_, lse_, None, dq_, s1, s2)

        def dkv_kernel(q_, k_, v_, o_, do_, lse_, dk_, dv_, s1, s2):
            dkv_full(q_, k_, v_, o_, do_, lse_, None, dk_, dv_, s1, s2)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, s_q // bq, s_k // bk),
        in_specs=[qo_spec(True), kv_spec(True), kv_spec(True), qo_spec(True),
                  qo_spec(True), lse_spec(True)] + len_specs,
        out_specs=qo_spec(True),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(*operands)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, s_k // bk, s_q // bq),
        in_specs=[qo_spec(False), kv_spec(False), kv_spec(False),
                  qo_spec(False), qo_spec(False), lse_spec(False)]
        + len_specs,
        out_specs=[kv_spec(False), kv_spec(False)],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(*operands)
    return dq, dk, dv


# ---------------------------------------------------- ring building blocks
# Raw (no-VJP) entry points for ring attention (parallel/ring.py), which
# authors its OWN custom VJP over the whole ring: the forward needs each
# hop's (out, lse) pair to merge blocks log-sum-exp-stably, and the
# backward re-runs the per-block kernels with the GLOBAL row lse (which
# makes the recomputed p the true global softmax probability — the
# standard multi-block flash backward).


def flash_block_fwd(q, k, v, causal: bool, scale: Optional[float] = None,
                    interpret: Optional[bool] = None):
    """One block pair, no autodiff: -> (out [B,H,S,D], lse [B,H,S] fp32)."""
    out, lse = _flash_call(q, k, v, causal, scale, None, None, interpret,
                           return_lse=True)
    return out, lse[..., 0]


def flash_block_bwd(q, k, v, o, lse, do, causal: bool,
                    scale: Optional[float] = None,
                    interpret: Optional[bool] = None):
    """Gradients for one block pair given the GLOBAL row lse [B,H,S] and
    the GLOBAL output o (delta = rowsum(dO*O)): -> (dq, dk, dv)."""
    return _flash_bwd_call(q, k, v, o, lse, do, causal, scale, None, None,
                           interpret)


# ------------------------------------------------------------- public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_dense(q, k, v, causal: bool = True,
                           scale: Optional[float] = None,
                           block_q: Optional[int] = None,
                           block_k: Optional[int] = None,
                           interpret: Optional[bool] = None):
    return _flash_call(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_call(q, k, v, causal, scale, block_q, block_k,
                           interpret, return_lse=True)
    # Residual kept at [B, H, S] (1/128th of the kernel's lane-broadcast
    # output) — at long context the broadcast form would rival the K/V
    # residuals themselves in HBM.
    return out, (q, k, v, out, lse[..., 0])


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_bwd_call(q, k, v, out, lse, g, causal, scale, block_q,
                           block_k, interpret)


_flash_attention_dense.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_varlen(q, k, v, kv_lengths, causal, scale, block_q,
                            block_k, interpret):
    return _flash_call(q, k, v, causal, scale, block_q, block_k, interpret,
                       kv_lengths=kv_lengths)


def _flash_varlen_fwd(q, k, v, kv_lengths, causal, scale, block_q, block_k,
                      interpret):
    out, lse = _flash_call(q, k, v, causal, scale, block_q, block_k,
                           interpret, return_lse=True, kv_lengths=kv_lengths)
    return out, (q, k, v, out, lse[..., 0], kv_lengths)


def _flash_varlen_bwd(causal, scale, block_q, block_k, interpret, residuals,
                      g):
    import numpy as np

    q, k, v, out, lse, kv_lengths = residuals
    dq, dk, dv = _flash_bwd_call(q, k, v, out, lse, g, causal, scale,
                                 block_q, block_k, interpret,
                                 kv_lengths=kv_lengths)
    # Integer lengths carry no gradient: the float0 zero cotangent.
    dlen = np.zeros(kv_lengths.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dlen


_flash_attention_varlen.defvjp(_flash_varlen_fwd, _flash_varlen_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    kv_lengths=None):
    """q, k, v: [B, H, S, D] -> [B, H, S, D].

    ``block_q``/``block_k`` default to the measured-best sizes for the
    sequence length (see ``_auto_blocks``). ``interpret=None``
    auto-selects: compiled on TPU backends, interpreter elsewhere (so CPU
    tests run the same kernel code).

    ``kv_lengths`` ([B] int32) masks key/value positions at or beyond each
    batch row's length — the right-padding contract (BERT on real,
    unpacked data). Lengths are clamped to >= 1: a fully-padded row
    attends to position 0 only (without the clamp the kernel's online
    softmax would silently attend uniformly to ALL positions, while the
    composed-XLA path NaNs — one defined behavior for both). Query rows
    beyond the length produce arbitrary finite outputs; downstream must
    mask them (MLM's -100 labels do). Gradients for padded keys/values
    are exactly zero — except position 0 of a zero-length row, which the
    clamp makes attendable and which therefore carries gradient.
    """
    if kv_lengths is None:
        return _flash_attention_dense(q, k, v, causal, scale, block_q,
                                      block_k, interpret)
    kv_lengths = jnp.maximum(jnp.asarray(kv_lengths, jnp.int32), 1)
    return _flash_attention_varlen(q, k, v, kv_lengths, causal, scale,
                                   block_q, block_k, interpret)
