"""Fused LayerNorm as Pallas kernels: one VMEM pass computes statistics and
applies scale/shift (the reference fused this in a custom CUDA kernel —
SURVEY.md §2). Rows are tiled over the grid; statistics in fp32.

Differentiable: a custom VJP pairs the forward kernel with a fused backward
kernel that recomputes the row statistics from x (cheaper than storing
mean/rstd residuals at [rows] when the whole row is re-read anyway) and
emits dx plus per-block partial reductions for dscale/dbias, which XLA sums
outside the kernel (a [n_blocks, D] add — negligible).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU lowering)


def _ln_kernel(x_ref, scale_ref, bias_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)                      # [bn, D]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    y = y * scale_ref[:].astype(jnp.float32) + bias_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def _ln_bwd_kernel(x_ref, scale_ref, dy_ref, dx_ref, dscale_ref, dbias_ref,
                   *, eps: float):
    x = x_ref[:].astype(jnp.float32)                      # [bn, D]
    dy = dy_ref[:].astype(jnp.float32)
    scale = scale_ref[:].astype(jnp.float32)              # [1, D]
    d = x.shape[-1]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    r = lax.rsqrt(var + eps)
    xhat = (x - mean) * r
    g = dy * scale                                        # dL/dxhat
    m1 = jnp.sum(g, axis=-1, keepdims=True) / d
    m2 = jnp.sum(g * xhat, axis=-1, keepdims=True) / d
    dx = r * (g - m1 - xhat * m2)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dscale_ref[:] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    dbias_ref[:] = jnp.sum(dy, axis=0, keepdims=True)


def _pick_block(size: int, target: int) -> int:
    b = min(size, target)
    while size % b:
        b -= 1
    return b


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _ln_fwd_raw(x2, scale, bias, eps: float, interpret: bool):
    rows, d = x2.shape
    bn = _pick_block(rows, 256)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(rows // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x2.dtype),
        interpret=interpret,
    )(x2, scale.reshape(1, d), bias.reshape(1, d))


def _ln_bwd_raw(x2, scale, dy2, eps: float, interpret: bool):
    rows, d = x2.shape
    bn = _pick_block(rows, 256)
    n_blocks = rows // bn
    dx, dscale_p, dbias_p = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, d), x2.dtype),
            jax.ShapeDtypeStruct((n_blocks, d), jnp.float32),
            jax.ShapeDtypeStruct((n_blocks, d), jnp.float32),
        ],
        interpret=interpret,
    )(x2, scale.reshape(1, d), dy2)
    return dx, dscale_p.sum(axis=0), dbias_p.sum(axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_ln(x2, scale, bias, eps: float, interpret: bool):
    return _ln_fwd_raw(x2, scale, bias, eps, interpret)


def _fused_ln_fwd(x2, scale, bias, eps, interpret):
    # `bias` rides along only to pin its cotangent dtype ([D] — negligible).
    return _ln_fwd_raw(x2, scale, bias, eps, interpret), (x2, scale, bias)


def _fused_ln_bwd(eps, interpret, res, dy2):
    x2, scale, bias = res
    dx, dscale, dbias = _ln_bwd_raw(x2, scale, dy2, eps, interpret)
    return dx, dscale.astype(scale.dtype), dbias.astype(bias.dtype)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def fused_layer_norm(x, scale, bias, eps: float = 1e-5,
                     interpret: Optional[bool] = None):
    """x: [..., D]; scale, bias: [D]. Returns layernorm(x) in x.dtype.
    Differentiable (fused backward kernel, see module docstring)."""
    orig_shape = x.shape
    d = orig_shape[-1]
    rows = 1
    for dim in orig_shape[:-1]:
        rows *= dim
    x2 = x.reshape(rows, d)
    out = _fused_ln(x2, scale, bias, eps, _resolve_interpret(interpret))
    return out.reshape(orig_shape)
