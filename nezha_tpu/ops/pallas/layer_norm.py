"""Fused LayerNorm as a Pallas kernel: one VMEM pass computes statistics and
applies scale/shift (the reference fused this in a custom CUDA kernel —
SURVEY.md §2). Rows are tiled over the grid; statistics in fp32."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _ln_kernel(x_ref, scale_ref, bias_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)                      # [bn, D]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    y = y * scale_ref[:].astype(jnp.float32) + bias_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def _pick_block(size: int, target: int) -> int:
    b = min(size, target)
    while size % b:
        b -= 1
    return b


def fused_layer_norm(x, scale, bias, eps: float = 1e-5,
                     interpret: Optional[bool] = None):
    """x: [..., D]; scale, bias: [D]. Returns layernorm(x) in x.dtype."""
    orig_shape = x.shape
    d = orig_shape[-1]
    rows = 1
    for dim in orig_shape[:-1]:
        rows *= dim
    x2 = x.reshape(rows, d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bn = _pick_block(rows, 256)
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(rows // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(x2, scale.reshape(1, d), bias.reshape(1, d))
    return out.reshape(orig_shape)
