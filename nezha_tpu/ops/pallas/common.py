"""Shared online-softmax core for the Pallas attention kernels.

Every attention kernel in this package — training flash
(``flash_attention.py``), single-token decode (``decode_attention.py``)
and paged prefill (``prefill_attention.py``) — folds KV blocks into the
same three-piece VMEM scratch: a running row max ``m``, a running
denominator ``l`` and an fp32 output accumulator ``acc``. The update
math was duplicated verbatim between the decode ``_block_step`` and the
flash ``_fwd_kernel`` body; this module is the single source both (and
the prefill kernel) now call. Grouping it here is a pure factoring:
the op sequence is bit-identical to what each kernel inlined before,
so every existing kernel test pins the refactor.

Also hosts the package-wide scalar helpers: the finite ``NEG_BIG``
"-inf" (fully-masked rows must stay NaN-free), the ``LANES`` lane
width small per-row operands broadcast to, the CompilerParams rename
shim and the block-divisor picker.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.experimental.pallas import tpu as pltpu

NEG_BIG = -1e30
LANES = 128  # per-row scalars ride lane-broadcast: [B, 128]

# jax renamed pltpu.TPUCompilerParams -> CompilerParams; resolve whichever
# this install ships so the compiled-TPU path works on either side of the
# rename (the interpret path never touches it).
compiler_params = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def pick_block(size: int, target: int) -> int:
    """Largest divisor of ``size`` that is <= target (block shapes must
    tile the sequence exactly)."""
    b = min(size, target)
    while size % b:
        b -= 1
    return b


def scratch_init(m_scr, l_scr, acc_scr):
    """Reset the online-softmax scratch at the first KV block — shared
    by every kernel variant."""
    m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
    l_scr[:] = jnp.zeros_like(l_scr)
    acc_scr[:] = jnp.zeros_like(acc_scr)


def softmax_block_update(s, v, m_scr, l_scr, acc_scr):
    """Fold one masked score block ``s [rows, bk]`` and its value tile
    ``v [bk, d]`` into the running ``(max, sum, acc)`` scratch — THE
    online-softmax step every kernel shares. ``s`` arrives fully masked
    (causal / length / start-offset masking is the caller's business);
    softmax statistics and the accumulator stay fp32, P·V dots in the
    value tile's native dtype."""
    m_prev = m_scr[:, :1]                                # [rows, 1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)                               # [rows, bk]
    corr = jnp.exp(m_prev - m_new)
    l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * corr + lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)


def softmax_finalize(o_ref, m_scr, l_scr, acc_scr, lse_ref=None):
    """Write the normalized accumulator at the last KV block. The denom
    guard keeps a row whose scratch never saw a block (zero-length /
    inactive) at an exact-zero output instead of 0/0. With ``lse_ref``
    (the training forward) the per-row logsumexp residual is emitted
    lane-broadcast alongside."""
    denom = jnp.maximum(l_scr[:, :1], 1e-30)
    o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)
    if lse_ref is not None:
        lse = m_scr[:, :1] + jnp.log(denom)              # [rows, 1]
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref[0, 0].shape)


def block_step(q, k, v, length, ki, m_scr, l_scr, acc_scr, *,
               scale: float, block_k: int):
    """One length-masked KV block folded into the scratch — the shared
    core of the decode-kernel variants (dense, paged, paged-int8) and
    the prefill kernel's prior-block path: the variants differ only in
    WHERE ``k``/``v`` came from (BlockSpec gather, in-kernel dequant)
    and in any EXTRA masking applied on top, never in the fold."""
    s = lax.dot_general(q.astype(k.dtype), k,
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    kpos = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(kpos < length, s, NEG_BIG)             # partial block
    softmax_block_update(s, v, m_scr, l_scr, acc_scr)
