"""Flash-decode: batched single-token attention against a pooled KV cache.

The serving decode step asks one question per row: attend ONE query token
over that row's cache prefix ``[0, length)``. The composed path answers it
by materializing a ``[B, 1, 1, L_max]`` additive mask and running dense
attention over the FULL pool — every row pays ``L_max`` bandwidth whatever
its depth, and the softmax round-trips a score matrix through HBM. This
kernel is built for the actual access pattern (the "Harnessing HPC
Kernels" argument from PAPERS.md: shape-specialized hot loops deserve a
kernel, not a generic lowering):

- grid ``(B, H, KV-blocks)`` with the KV dimension sequential
  ("arbitrary" semantics) — the split-K layout: each program folds one
  KV block into VMEM running ``(max, sum, acc)`` scratch via online
  softmax, merged at the final block (no score matrix, no mask tensor);
- a per-row ``lengths`` operand: a program whose block starts at or past
  its row's length SKIPS the block entirely (``@pl.when``), so short rows
  and inactive rows (``length == 0``) cost block-bookkeeping only — work
  is proportional to ``sum(lengths)``, not ``B * L_max``;
- Q·Kᵀ and P·V accumulate fp32 over the caches' native dtype (bf16 pool
  dots run at the doubled MXU rate; the softmax statistics and the
  accumulator stay fp32 throughout);
- ``interpret=None`` auto-selects the Pallas interpreter off-TPU, so CPU
  tests exercise the same kernel code that compiles on hardware.

Decode is inference-only, so there is no VJP; ``models/gpt2.py`` routes
its single-token cache branch here behind the ``attn_impl="auto"``
resolution (``GPT2Config.decode_impl`` / ``NEZHA_NO_DECODE_KERNEL=1``
are the escape hatches back to the composed masked path).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# The online-softmax core (scratch init / block fold / finalize) is
# shared with flash_attention.py and prefill_attention.py — see
# ops/pallas/common.py. The aliases keep this module's kernel bodies
# reading as before; the math is bit-identical to the pre-factoring
# inline version.
from nezha_tpu.ops.pallas.common import (
    LANES as _LANES,
    block_step as _block_step,
    compiler_params as _compiler_params,
    pick_block as _pick_block,
    scratch_init as _scratch_init,
    softmax_finalize,
)


def _finalize(o_ref, l_scr, acc_scr):
    """Write the normalized accumulator at the last KV block (decode
    emits no lse residual — inference only)."""
    softmax_finalize(o_ref, None, l_scr, acc_scr)


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, m_scr, l_scr,
                   acc_scr, *, scale: float, block_k: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        _scratch_init(m_scr, l_scr, acc_scr)

    length = len_ref[0, 0]
    # The block-skip that the dense masked path cannot see: blocks at or
    # past this row's length never load K/V or touch the MXU. A row with
    # length == 0 (inactive slot) runs no block at all and finalizes to
    # an all-zero output.
    run = ki * block_k < length

    @pl.when(run)
    def _block():
        _block_step(q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], length, ki,
                    m_scr, l_scr, acc_scr, scale=scale,
                    block_k=block_k)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _final():
        _finalize(o_ref, l_scr, acc_scr)


def _paged_decode_kernel(tab_ref, q_ref, k_ref, v_ref, len_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, scale: float,
                         block_k: int):
    # Identical math to the dense kernel: the block table only changed
    # WHERE block ki lives (the BlockSpec index map gathered it), not
    # what it means — per-row lengths still skip blocks at/past the
    # row's depth, so work tracks sum(lengths) over the block
    # indirection exactly as it did over the dense pool.
    _decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, m_scr, l_scr,
                   acc_scr, scale=scale, block_k=block_k)


def _paged_quant_decode_kernel(tab_ref, q_ref, k_ref, v_ref, ks_ref,
                               vs_ref, len_ref, o_ref, m_scr, l_scr,
                               acc_scr, *, scale: float, block_k: int):
    """Paged kernel over an INT8 block pool: the per-(block, head) fp32
    scale rides its own gathered (1, 1) operand and the dequant happens
    right here in the block loop — int8 blocks never round-trip through
    a dense bf16 cache. Dequantized tiles are cast to the query's dtype
    (bf16 pools dot at the doubled MXU rate); softmax statistics and
    the accumulator stay fp32, and the per-row length skip means a
    skipped block never even DMAs its scale."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        _scratch_init(m_scr, l_scr, acc_scr)

    length = len_ref[0, 0]
    run = ki * block_k < length

    @pl.when(run)
    def _block():
        q = q_ref[0, 0]                                      # [1, d]
        # THE dequant both attention paths share (see
        # ops/quant.dequantize_kv_block): int8 * fp32 scale, cast to
        # the compute dtype — the XLA gather fallback applies the same
        # expression, so kernel and fallback see identical tiles.
        k = (k_ref[0, 0].astype(jnp.float32)
             * ks_ref[0, 0]).astype(q.dtype)                 # [bk, d]
        v = (v_ref[0, 0].astype(jnp.float32)
             * vs_ref[0, 0]).astype(q.dtype)                 # [bk, d]
        _block_step(q, k, v, length, ki, m_scr, l_scr, acc_scr,
                    scale=scale, block_k=block_k)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _final():
        _finalize(o_ref, l_scr, acc_scr)


def _paged_call(q, k, v, lengths, block_tables, scale, interpret,
                block_scales=None):
    """Paged layout: k/v are BLOCK POOLS ``[N, H, bs, D]`` and
    ``block_tables [B, M]`` maps row b's KV block ki to pool block
    ``block_tables[b, ki]``. The table rides as a SCALAR-PREFETCH
    operand (pltpu.PrefetchScalarGridSpec) so the grid's KV dimension
    gathers blocks through the table in its index map — the kernel body
    is unchanged, per-row length skipping included. With
    ``block_scales`` (int8 pools) the per-(block, head) fp32 scales are
    gathered through the SAME index map as (1, 1) operands and the
    kernel dequantizes each tile in the block loop."""
    b, h, _, d = q.shape
    n_blocks, _, bs, _ = k.shape
    m = block_tables.shape[1]
    quant = block_scales is not None
    kernel = functools.partial(
        _paged_quant_decode_kernel if quant else _paged_decode_kernel,
        scale=scale, block_k=bs)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    len2d = jnp.broadcast_to(
        jnp.clip(jnp.asarray(lengths, jnp.int32), 0, m * bs)[:, None],
        (b, _LANES))
    kv_spec = pl.BlockSpec((1, 1, bs, d),
                           lambda b_, h_, ki, tab: (tab[b_, ki], h_, 0, 0))
    in_specs = [
        pl.BlockSpec((1, 1, 1, d), lambda b_, h_, ki, tab: (b_, h_, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [q, k, v]
    if quant:
        scale_spec = pl.BlockSpec(
            (1, 1), lambda b_, h_, ki, tab: (tab[b_, ki], h_))
        in_specs += [scale_spec, scale_spec]
        ks, vs = block_scales
        operands += [jnp.asarray(ks, jnp.float32),
                     jnp.asarray(vs, jnp.float32)]
    in_specs.append(
        pl.BlockSpec((1, _LANES), lambda b_, h_, ki, tab: (b_, 0)))
    operands.append(len2d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, m),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, 1, d),
                               lambda b_, h_, ki, tab: (b_, h_, 0, 0)),
        scratch_shapes=[pltpu.VMEM((1, _LANES), jnp.float32),
                        pltpu.VMEM((1, _LANES), jnp.float32),
                        pltpu.VMEM((1, d), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
        **kwargs,
    )(jnp.asarray(block_tables, jnp.int32), *operands)


def flash_decode_attention(q, k, v, lengths,
                           scale: Optional[float] = None,
                           block_k: Optional[int] = None,
                           interpret: Optional[bool] = None,
                           block_tables=None, block_scales=None):
    """q ``[B, H, 1, D]``, k/v ``[B, H, L, D]``, lengths ``[B]`` int32
    -> ``[B, H, 1, D]``.

    ``lengths[b]`` is the number of attendable cache positions for row
    ``b`` (the decode convention: ``pos + 1``, the query's own position
    included). ``lengths[b] == 0`` marks an inactive row: every KV block
    is skipped and the output row is exactly zero (callers discard it —
    the serve engine freezes inactive rows host-side). Lengths are
    clamped to ``[0, L]``.

    With ``block_tables`` (``[B, M]`` int32 — the paged serving
    layout), k/v are instead BLOCK POOLS shaped
    ``[num_blocks, H, block_size, D]``: row ``b``'s positions
    ``[ki*block_size, (ki+1)*block_size)`` live in pool block
    ``block_tables[b, ki]``, and the kernel gathers KV blocks through
    the table via a scalar-prefetch index map. The per-row length skip
    is preserved verbatim — a row only DMAs the table entries below its
    own depth. ``block_k`` is ignored (the pool's block_size IS the KV
    block).

    With ``block_scales`` (paged only — a ``(k_scales, v_scales)`` pair
    of ``[num_blocks, H]`` fp32 arrays) the pools are INT8 and each
    gathered tile is dequantized INSIDE the block loop
    (``tile.astype(f32) * scale -> q.dtype`` — the exact expression of
    ``ops.quant.dequantize_kv_block``, so the composed XLA fallback
    dequantizes identically): dots run in the query's dtype over
    dequantized tiles, softmax statistics and the accumulator stay
    fp32, and skipped blocks never load data OR scales. (On real TPU
    hardware int8 tiles want ``block_size * D`` at or above the int8
    native tile — tiny test shapes run in interpret mode.)

    ``block_k`` defaults to the largest divisor of ``L`` that is <= 256
    (KV pools are padded to power-of-two-ish capacities, so real shapes
    get real blocks). ``interpret=None`` auto-selects: compiled on TPU,
    interpreter elsewhere.
    """
    b, h, s_q, d = q.shape
    if s_q != 1:
        raise ValueError(
            f"flash_decode_attention is the single-token kernel; got "
            f"s_q={s_q} (use flash_attention for prefill/training)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_scales is not None and block_tables is None:
        raise ValueError("block_scales requires block_tables (int8 is "
                         "a paged-pool format)")
    if block_tables is not None:
        if k.shape != v.shape or k.shape[1] != h or k.shape[3] != d:
            raise ValueError(
                f"paged k/v pools {k.shape}/{v.shape} do not match q "
                f"{q.shape}")
        if block_tables.shape[0] != b:
            raise ValueError(
                f"block_tables {block_tables.shape} does not match "
                f"batch {b}")
        if block_scales is not None:
            ks, vs = block_scales
            want = (k.shape[0], h)
            if tuple(ks.shape) != want or tuple(vs.shape) != want:
                raise ValueError(
                    f"block_scales {ks.shape}/{vs.shape} must be "
                    f"[num_blocks, H] = {want}")
        scale = scale if scale is not None else 1.0 / (d ** 0.5)
        return _paged_call(q, k, v, lengths, block_tables, scale,
                           interpret, block_scales=block_scales)
    if k.shape != v.shape or k.shape[:2] != (b, h) or k.shape[3] != d:
        raise ValueError(f"k/v {k.shape}/{v.shape} do not match q {q.shape}")
    L = k.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bk = _pick_block(L, block_k or min(L, 256))

    kernel = functools.partial(_decode_kernel, scale=scale, block_k=bk)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    len2d = jnp.broadcast_to(
        jnp.clip(jnp.asarray(lengths, jnp.int32), 0, L)[:, None],
        (b, _LANES))
    q_spec = pl.BlockSpec((1, 1, 1, d), lambda b_, h_, ki: (b_, h_, 0, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, ki: (b_, h_, ki, 0))
    len_spec = pl.BlockSpec((1, _LANES), lambda b_, h_, ki: (b_, 0))
    return pl.pallas_call(
        kernel,
        grid=(b, h, L // bk),
        in_specs=[q_spec, kv_spec, kv_spec, len_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((1, _LANES), jnp.float32),
                        pltpu.VMEM((1, _LANES), jnp.float32),
                        pltpu.VMEM((1, d), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(q, k, v, len2d)


def flash_decode_attention_sharded(q, k, v, lengths, mesh, *,
                                   scale: Optional[float] = None,
                                   block_tables=None, block_scales=None,
                                   interpret: Optional[bool] = None):
    """:func:`flash_decode_attention` PER SHARD under a nested
    ``shard_map`` over the mesh's ``tp`` (head) axis — the sharded
    serve engine's decode-attention path.

    Heads are embarrassingly parallel in decode attention (each head's
    online softmax reads only its own K/V slice), so sharding
    ``q [B, H, 1, D]``, the K/V block pools ``[N, H, bs, D]``, and the
    per-(block, head) scale rows ``[N, H]`` on the H axis runs the
    Mosaic kernel device-locally on an ``H / tp`` slice — the GSPMD
    auto-partitioner (which cannot partition a Pallas custom call)
    never sees it, exactly the ``_tp_sharded_flash`` idiom the
    training path proved. The per-row ``lengths`` and the
    scalar-prefetched ``block_tables`` REPLICATE: block identities are
    mesh-invariant host bookkeeping (see serve/sharded/pool.py — and
    the ``mesh-host-side-tables`` lint rule that keeps it so).

    ``scale`` defaults per shard to ``1/sqrt(D)`` — D is untouched by
    head sharding, so per-shard defaulting equals the unsharded
    kernel's. Output is ``[B, H, 1, D]`` sharded on H, matching the
    enclosing program's head-sharded activations."""
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    hspec = P(None, "tp")
    rep = P()

    if block_scales is not None:
        ks, vs = block_scales

        def body_q(q_, k_, v_, l_, t_, ks_, vs_):
            return flash_decode_attention(
                q_, k_, v_, l_, scale=scale, interpret=interpret,
                block_tables=t_, block_scales=(ks_, vs_))

        f = shard_map(body_q, mesh=mesh,
                      in_specs=(hspec, hspec, hspec, rep, rep, hspec,
                                hspec),
                      out_specs=hspec)
        return f(q, k, v, lengths, block_tables, ks, vs)
    if block_tables is not None:
        def body_t(q_, k_, v_, l_, t_):
            return flash_decode_attention(
                q_, k_, v_, l_, scale=scale, interpret=interpret,
                block_tables=t_)

        f = shard_map(body_t, mesh=mesh,
                      in_specs=(hspec, hspec, hspec, rep, rep),
                      out_specs=hspec)
        return f(q, k, v, lengths, block_tables)

    def body(q_, k_, v_, l_):
        return flash_decode_attention(q_, k_, v_, l_, scale=scale,
                                      interpret=interpret)

    f = shard_map(body, mesh=mesh,
                  in_specs=(hspec, hspec, hspec, rep), out_specs=hspec)
    return f(q, k, v, lengths)
