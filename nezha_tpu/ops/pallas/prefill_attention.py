"""Paged flash-prefill: chunked prompt attention against a block pool,
with the int8 cache write fused into the kernel epilogue.

The serving prefill path processes one bucket-width chunk of prompt at a
traced offset ``start``: its queries attend the row's cached prefix
``[0, start)`` (earlier chunks / a shared-prefix hit, reached through
the block table) plus the chunk itself causally. The composed path
gathers the WHOLE pool per layer (``k_pool[tab]`` — ``M * bs``
bandwidth whatever the prefix depth), materializes an ``[S, L]`` mask,
and on int8 pools pays a separate gather→dequant→insert→requant→scatter
chain per written block (``models/gpt2._quant_prefill_write``). This
kernel is built for the actual access pattern:

- grid ``(B, H, Q-tiles, M + 1)`` with the KV axis sequential: steps
  ``t < M`` fold pool block ``t`` (gathered through the scalar-
  prefetched block table, exactly the decode kernel's index map) into
  the shared online-softmax scratch, masked to the PREFIX ``[0, start)``
  and skipped entirely once ``t*bs >= start`` — prefix work tracks the
  row's real depth, not the table capacity; the final step folds the
  chunk's own K/V causally from the fresh operands (the pool is never
  read at chunk positions, so the attention is independent of whether
  the chunk write landed yet);
- ``start`` rides per-row as a second scalar-prefetch operand, so
  chunked continuation and shared-prefix partial prefills (nonzero
  start) are the SAME compiled program as a cold start — the engine's
  one-program-per-bucket contract;
- on int8 pools the block write FUSES into the epilogue: during the
  last Q-tile sweep each touched pool block is merged in-VMEM (old
  dequantized content below ``start`` — the block was just gathered for
  prefix attention anyway — chunk values in ``[start, start+S)``, zeros
  after: stale previous-occupant garbage must never set the new absmax),
  requantized with a fresh per-(block, head) fp32 scale, and scattered
  through a table-indexed OUTPUT BlockSpec aliased onto the pool.
  Non-writing grid steps route the output index map to the scratch
  block (block 0 — the same over-cover routing
  ``_quant_prefill_write`` uses) with zeroed content and unit scale.
  The whole ``_quant_prefill_write`` chain collapses into the
  attention kernel: one program, no pool-sized gather/scatter round
  trip.

The quantization policy is ``ops.quant.quantize_kv_block`` verbatim
(sanitize → absmax/127 with the zero guard → round/clip), and the
max-abs dequant error over the written span comes back as a
``[B, H]`` output so the engine keeps feeding ``serve.kv.quant_error``.

Aliased-write ordering: writes happen only in the LAST Q-tile sweep,
each touched block is read (for the old-content merge) at the same
sequential step that writes it, and the KV axis only moves forward —
no step ever re-reads a block a previous step wrote. Rows of one call
must not share touched blocks (the engine prefills one row per
program; prefix blocks are read-only and may be shared freely).

``interpret=None`` auto-selects the Pallas interpreter off-TPU, so CPU
tests exercise the same kernel code that compiles on hardware.
Inference-only: no VJP. ``models/gpt2.py`` routes its paged
prefill-chunk branch here behind ``GPT2Config.prefill_impl``
(``NEZHA_NO_PREFILL_KERNEL=1`` is the escape hatch back to the
composed masked path).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nezha_tpu.ops.pallas.common import (
    LANES,
    NEG_BIG,
    block_step,
    compiler_params,
    pick_block,
    scratch_init,
    softmax_block_update,
    softmax_finalize,
)
from nezha_tpu.ops.quant import QMAX, SATURATE_MAX

_Q_TILE_TARGET = 256   # q rows per tile (divisor-clamped to the chunk)
_KC_TILE_TARGET = 256  # chunk-KV rows per self-attention tile


def _chunk_self_attention(qi, q_ref, kc_ref, vc_ref, m_scr, l_scr,
                          acc_scr, *, scale, block_q, block_kc, s_chunk,
                          cast_dtype, qoff=None):
    """Fold the chunk's own K/V causally (chunk-local positions — the
    shared ``start`` offset cancels out of the causal comparison).
    ``cast_dtype`` routes the fresh tiles through the pool's storage
    dtype first so a bf16 pool attends exactly the values the composed
    path reads back after its write. ``qi`` is passed in (program ids
    must be read at kernel top level, outside any ``pl.when`` body).

    ``qoff`` (traced per-row scalar, or None) shifts the queries by a
    GLOBAL offset relative to the chunk's start: query ``i`` sits at
    chunk-local position ``i + qoff``, so the causal comparison runs in
    global coordinates — the sequence-sharded prefill path hands each
    mesh shard a SLICE of the chunk's queries against the full chunk
    K/V. ``None`` keeps the original statically-skipped diagonal (the
    compiled default path is unchanged byte-for-byte)."""
    q = q_ref[0, 0]                                          # [bq, d]
    for kj in range(s_chunk // block_kc):
        if qoff is None:
            # Tiles strictly above this q tile's causal diagonal are
            # skipped at TRACE time — a static Python bool.
            run = kj * block_kc <= qi * block_q + block_q - 1
        else:
            # The diagonal moves with the traced offset: the skip is a
            # per-program predicate, still zero work for future tiles.
            run = kj * block_kc <= qi * block_q + block_q - 1 + qoff

        @pl.when(run)
        def _tile(kj=kj):
            k = kc_ref[0, 0, kj * block_kc:(kj + 1) * block_kc, :]
            v = vc_ref[0, 0, kj * block_kc:(kj + 1) * block_kc, :]
            if cast_dtype is not None:
                k = k.astype(cast_dtype)
                v = v.astype(cast_dtype)
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)
            s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            if qoff is not None:
                qpos = qpos + qoff
            kpos = kj * block_kc + lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, NEG_BIG)
            softmax_block_update(s, v, m_scr, l_scr, acc_scr)


def _prefill_kernel(tab_ref, start_ref, q_ref, kc_ref, vc_ref, kp_ref,
                    vp_ref, o_ref, m_scr, l_scr, acc_scr, *, scale,
                    s_chunk, block_q, block_kc, bs, m, cast_dtype):
    """bf16/float pool variant: attention only (the float chunk write is
    a single cheap XLA scatter the caller keeps)."""
    b_ = pl.program_id(0)
    qi = pl.program_id(2)
    t = pl.program_id(3)
    start = start_ref[b_]

    @pl.when(t == 0)
    def _init():
        scratch_init(m_scr, l_scr, acc_scr)

    # Prefix pool block: masked to [0, start) and skipped entirely once
    # the block starts at/past the row's prefix depth.
    @pl.when((t < m) & (t * bs < start))
    def _prefix():
        block_step(q_ref[0, 0], kp_ref[0, 0], vp_ref[0, 0], start, t,
                   m_scr, l_scr, acc_scr, scale=scale, block_k=bs)

    @pl.when(t == m)
    def _chunk():
        _chunk_self_attention(qi, q_ref, kc_ref, vc_ref, m_scr, l_scr,
                              acc_scr, scale=scale, block_q=block_q,
                              block_kc=block_kc, s_chunk=s_chunk,
                              cast_dtype=cast_dtype)
        softmax_finalize(o_ref, m_scr, l_scr, acc_scr)


def _prefill_qoff_kernel(tab_ref, start_ref, qoff_ref, q_ref, kc_ref,
                         vc_ref, kp_ref, vp_ref, o_ref, m_scr, l_scr,
                         acc_scr, *, scale, s_chunk, block_q, block_kc,
                         bs, m, cast_dtype):
    """Float-pool variant with PER-ROW GLOBAL QUERY OFFSETS: query ``i``
    of row ``b`` sits at absolute position ``qoffs[b] + i`` while the
    chunk K/V operands occupy ``[starts[b], starts[b] + s_chunk)`` and
    the pool prefix ``[0, starts[b])``. Requires ``qoffs >= starts``
    (every query postdates the whole prefix, so the prefix fold needs
    no extra mask — the invariant the default kernel already relies
    on). This is the sequence-sharded prefill building block: one mesh
    shard's slice of the chunk's queries runs ONE program against the
    full chunk + its local pool shard, per (mesh, bucket) — chunked
    continuation and shared-prefix starts ride the same traced scalars
    as the default path."""
    b_ = pl.program_id(0)
    qi = pl.program_id(2)
    t = pl.program_id(3)
    start = start_ref[b_]
    qoff = qoff_ref[b_] - start      # chunk-local offset of query 0

    @pl.when(t == 0)
    def _init():
        scratch_init(m_scr, l_scr, acc_scr)

    @pl.when((t < m) & (t * bs < start))
    def _prefix():
        block_step(q_ref[0, 0], kp_ref[0, 0], vp_ref[0, 0], start, t,
                   m_scr, l_scr, acc_scr, scale=scale, block_k=bs)

    @pl.when(t == m)
    def _chunk():
        _chunk_self_attention(qi, q_ref, kc_ref, vc_ref, m_scr, l_scr,
                              acc_scr, scale=scale, block_q=block_q,
                              block_kc=block_kc, s_chunk=s_chunk,
                              cast_dtype=cast_dtype, qoff=qoff)
        softmax_finalize(o_ref, m_scr, l_scr, acc_scr)


def _quant_merge_write(wpos, start, s_chunk, old_deq, stage, ci,
                       pool_out, scale_out, bs):
    """Merge one touched block (old prefix / fresh chunk / stale-zero),
    requantize with a fresh absmax scale — ``ops.quant.quantize_kv_block``
    verbatim — and write block + scale. Returns the max-abs dequant
    error over the written span (``serve.kv.quant_error``'s sample)."""
    fresh = pl.load(stage, (pl.dslice(ci, bs), slice(None)))
    merged = jnp.where(wpos < start, old_deq, fresh)         # [bs, d]
    merged = jnp.nan_to_num(merged, nan=0.0, posinf=SATURATE_MAX,
                            neginf=-SATURATE_MAX)
    amax = jnp.max(jnp.abs(merged))
    sc = jnp.where(amax > 0, amax / QMAX, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(merged / sc), -QMAX, QMAX)
    pool_out[0, 0] = q.astype(pool_out.dtype)
    scale_out[0, 0] = sc
    err = jnp.abs(merged - q * sc)
    return jnp.max(jnp.where(wpos < start + s_chunk, err, 0.0))


def _quant_prefill_kernel(tab_ref, start_ref, q_ref, kc_ref, vc_ref,
                          kp_ref, vp_ref, ks_ref, vs_ref, o_ref,
                          kp_out, vp_out, ks_out, vs_out, qerr_ref,
                          m_scr, l_scr, acc_scr, k_stage, v_stage,
                          qerr_scr, *, scale, s_chunk, block_q,
                          block_kc, bs, m):
    """Int8 pool variant: prefix blocks dequantize in the block loop
    (the decode kernel's expression — kernel and XLA fallback see
    identical tiles) and the chunk write fuses into the epilogue."""
    b_ = pl.program_id(0)
    qi = pl.program_id(2)
    t = pl.program_id(3)
    nq = pl.num_programs(2)
    start = start_ref[b_]
    last_q = qi == nq - 1

    @pl.when(t == 0)
    def _init():
        scratch_init(m_scr, l_scr, acc_scr)

    @pl.when((qi == 0) & (t == 0))
    def _err_init():
        qerr_scr[:] = jnp.zeros_like(qerr_scr)

    @pl.when(last_q & (t == 0))
    def _stage():
        # The chunk staged fp32 into a zero-padded buffer: touched
        # blocks slice their rows at a traced offset, and rows past the
        # chunk end read the stale-position zeros for free.
        k_stage[:] = jnp.zeros_like(k_stage)
        v_stage[:] = jnp.zeros_like(v_stage)
        k_stage[bs:bs + s_chunk, :] = kc_ref[0, 0].astype(jnp.float32)
        v_stage[bs:bs + s_chunk, :] = vc_ref[0, 0].astype(jnp.float32)

    @pl.when((t < m) & (t * bs < start))
    def _prefix():
        q = q_ref[0, 0]
        # THE dequant both attention paths share (see
        # ops/quant.dequantize_kv_block).
        k = (kp_ref[0, 0].astype(jnp.float32)
             * ks_ref[0, 0]).astype(q.dtype)
        v = (vp_ref[0, 0].astype(jnp.float32)
             * vs_ref[0, 0]).astype(q.dtype)
        block_step(q, k, v, start, t, m_scr, l_scr, acc_scr,
                   scale=scale, block_k=bs)

    wb0 = start // bs
    wb1 = (start + s_chunk - 1) // bs
    writing = last_q & (t < m) & (t >= wb0) & (t <= wb1)

    @pl.when(writing)
    def _write():
        wpos = t * bs + lax.broadcasted_iota(jnp.int32, (bs, 1), 0)
        ci = t * bs - start + bs                 # stage offset, >= 0
        old_k = kp_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0]
        old_v = vp_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0]
        ek = _quant_merge_write(wpos, start, s_chunk, old_k, k_stage,
                                ci, kp_out, ks_out, bs)
        ev = _quant_merge_write(wpos, start, s_chunk, old_v, v_stage,
                                ci, vp_out, vs_out, bs)
        qerr_scr[:] = jnp.maximum(qerr_scr[:], jnp.maximum(ek, ev))

    @pl.when(~writing)
    def _scratch_route():
        # Non-writing steps land on the scratch block (the output index
        # map routed them there): zero content, unit scale — exactly
        # what _quant_prefill_write's over-cover rows scatter.
        kp_out[0, 0] = jnp.zeros_like(kp_out[0, 0])
        vp_out[0, 0] = jnp.zeros_like(vp_out[0, 0])
        ks_out[0, 0] = jnp.float32(1.0)
        vs_out[0, 0] = jnp.float32(1.0)

    @pl.when(t == m)
    def _chunk():
        _chunk_self_attention(qi, q_ref, kc_ref, vc_ref, m_scr, l_scr,
                              acc_scr, scale=scale, block_q=block_q,
                              block_kc=block_kc, s_chunk=s_chunk,
                              cast_dtype=None)
        softmax_finalize(o_ref, m_scr, l_scr, acc_scr)
        # The qerr output's index never moves within (b, h): the last
        # write before the flush — the final q sweep's — wins.
        qerr_ref[0, 0] = qerr_scr[0, 0]


def _prefill_qoff_call(q, k_chunk, v_chunk, k_pool, v_pool,
                       block_tables, starts, q_offsets, scale,
                       interpret):
    """Float-path build with per-row global query offsets: the query
    extent ``S_q`` may differ from the chunk-K/V extent ``S_kc`` (a
    sequence shard holds ``S_kc / world`` queries against the full
    chunk), and a THIRD scalar-prefetch operand carries ``q_offsets``.
    The default build stays byte-identical — this is a separate
    program, keyed by its own (S_q, S_kc, M, bs, D) signature."""
    b, h, s_q, d = q.shape
    s_chunk = k_chunk.shape[2]
    bs = k_pool.shape[2]
    m = block_tables.shape[1]
    nq_block = pick_block(s_q, _Q_TILE_TARGET)
    nkc_block = pick_block(s_chunk, _KC_TILE_TARGET)
    nq = s_q // nq_block

    tab = jnp.asarray(block_tables, jnp.int32)
    starts32 = jnp.asarray(starts, jnp.int32)
    qoffs32 = jnp.asarray(q_offsets, jnp.int32)

    def _gather_idx(b_, h_, qi, t, tab, starts, qoffs):
        return (tab[b_, jnp.minimum(t, m - 1)], h_, 0, 0)

    q_spec = pl.BlockSpec((1, 1, nq_block, d),
                          lambda b_, h_, qi, t, tab, starts, qoffs:
                          (b_, h_, qi, 0))
    chunk_spec = pl.BlockSpec((1, 1, s_chunk, d),
                              lambda b_, h_, qi, t, tab, starts, qoffs:
                              (b_, h_, 0, 0))
    pool_spec = pl.BlockSpec((1, 1, bs, d), _gather_idx)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary"))
    scratch = [pltpu.VMEM((nq_block, LANES), jnp.float32),
               pltpu.VMEM((nq_block, LANES), jnp.float32),
               pltpu.VMEM((nq_block, d), jnp.float32)]
    kernel = functools.partial(
        _prefill_qoff_kernel, scale=scale, s_chunk=s_chunk,
        block_q=nq_block, block_kc=nkc_block, bs=bs, m=m,
        cast_dtype=k_pool.dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, h, nq, m + 1),
        in_specs=[q_spec, chunk_spec, chunk_spec, pool_spec, pool_spec],
        out_specs=q_spec,
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
        **kwargs,
    )(tab, starts32, qoffs32, q, k_chunk, v_chunk, k_pool, v_pool)


def _prefill_call(q, k_chunk, v_chunk, k_pool, v_pool, block_tables,
                  starts, scale, interpret, block_scales=None):
    b, h, s_chunk, d = q.shape
    bs = k_pool.shape[2]
    m = block_tables.shape[1]
    nq_block = pick_block(s_chunk, _Q_TILE_TARGET)
    nkc_block = pick_block(s_chunk, _KC_TILE_TARGET)
    nq = s_chunk // nq_block
    quant = block_scales is not None

    tab = jnp.asarray(block_tables, jnp.int32)
    starts32 = jnp.asarray(starts, jnp.int32)

    def _gather_idx(b_, h_, qi, t, tab, starts):
        return (tab[b_, jnp.minimum(t, m - 1)], h_, 0, 0)

    def _gather_scale_idx(b_, h_, qi, t, tab, starts):
        return (tab[b_, jnp.minimum(t, m - 1)], h_)

    def _write_blk(b_, qi, t, tab, starts):
        start = starts[b_]
        wb0 = start // bs
        wb1 = (start + s_chunk - 1) // bs
        touched = ((qi == nq - 1) & (t < m) & (t >= wb0) & (t <= wb1))
        return jnp.where(touched, tab[b_, jnp.minimum(t, m - 1)], 0)

    q_spec = pl.BlockSpec((1, 1, nq_block, d),
                          lambda b_, h_, qi, t, tab, starts:
                          (b_, h_, qi, 0))
    chunk_spec = pl.BlockSpec((1, 1, s_chunk, d),
                              lambda b_, h_, qi, t, tab, starts:
                              (b_, h_, 0, 0))
    pool_spec = pl.BlockSpec((1, 1, bs, d), _gather_idx)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary"))
    scratch = [pltpu.VMEM((nq_block, LANES), jnp.float32),
               pltpu.VMEM((nq_block, LANES), jnp.float32),
               pltpu.VMEM((nq_block, d), jnp.float32)]
    grid = (b, h, nq, m + 1)

    if not quant:
        kernel = functools.partial(
            _prefill_kernel, scale=scale, s_chunk=s_chunk,
            block_q=nq_block, block_kc=nkc_block, bs=bs, m=m,
            cast_dtype=k_pool.dtype)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[q_spec, chunk_spec, chunk_spec, pool_spec,
                      pool_spec],
            out_specs=q_spec,
            scratch_shapes=scratch,
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
            **kwargs,
        )(tab, starts32, q, k_chunk, v_chunk, k_pool, v_pool)

    ks, vs = block_scales
    kernel = functools.partial(
        _quant_prefill_kernel, scale=scale, s_chunk=s_chunk,
        block_q=nq_block, block_kc=nkc_block, bs=bs, m=m)
    scale_spec = pl.BlockSpec((1, 1), _gather_scale_idx)
    pool_out_spec = pl.BlockSpec(
        (1, 1, bs, d),
        lambda b_, h_, qi, t, tab, starts:
        (_write_blk(b_, qi, t, tab, starts), h_, 0, 0))
    scale_out_spec = pl.BlockSpec(
        (1, 1),
        lambda b_, h_, qi, t, tab, starts:
        (_write_blk(b_, qi, t, tab, starts), h_))
    qerr_spec = pl.BlockSpec((1, 1),
                             lambda b_, h_, qi, t, tab, starts: (b_, h_))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[q_spec, chunk_spec, chunk_spec, pool_spec, pool_spec,
                  scale_spec, scale_spec],
        out_specs=[q_spec, pool_out_spec, pool_out_spec,
                   scale_out_spec, scale_out_spec, qerr_spec],
        scratch_shapes=scratch + [
            pltpu.VMEM((s_chunk + 2 * bs, d), jnp.float32),
            pltpu.VMEM((s_chunk + 2 * bs, d), jnp.float32),
            pltpu.VMEM((1, LANES), jnp.float32)],
    )
    out, kp_new, vp_new, ks_new, vs_new, qerr = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
            jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
            jax.ShapeDtypeStruct(ks.shape, jnp.float32),
            jax.ShapeDtypeStruct(vs.shape, jnp.float32),
            jax.ShapeDtypeStruct((b, h), jnp.float32),
        ],
        # Operand order: tab(0) starts(1) q(2) kc(3) vc(4) kp(5) vp(6)
        # ks(7) vs(8) — the pools and scales alias their outputs so the
        # fused write is in place (untouched blocks keep their data).
        input_output_aliases={5: 1, 6: 2, 7: 3, 8: 4},
        interpret=interpret,
        **kwargs,
    )(tab, starts32, q, k_chunk, v_chunk, k_pool, v_pool,
      jnp.asarray(ks, jnp.float32), jnp.asarray(vs, jnp.float32))
    return out, kp_new, vp_new, ks_new, vs_new, jnp.max(qerr)


def flash_prefill_attention(q, k_chunk, v_chunk, k_pool, v_pool,
                            block_tables, starts,
                            scale: Optional[float] = None,
                            interpret: Optional[bool] = None,
                            block_scales=None, q_offsets=None):
    """Paged prefill-chunk attention (+ fused int8 write).

    ``q``/``k_chunk``/``v_chunk`` ``[B, H, S, D]`` are the fresh
    chunk's projections; ``k_pool``/``v_pool`` ``[N, H, bs, D]`` the
    row's KV block pools reached through ``block_tables [B, M]`` int32;
    ``starts [B]`` int32 is each row's chunk offset (query ``i`` sits
    at absolute position ``starts[b] + i`` and attends the cached
    prefix ``[0, starts[b])`` plus the chunk causally).

    Float pools -> ``out [B, H, S, D]``: attention only — the caller
    keeps its one-scatter chunk write (the fresh tiles are routed
    through the pool dtype in-kernel, so the output matches the
    composed gather-after-write path bit-for-bit in what it attends).

    Int8 pools (``block_scales=(k_scales, v_scales)`` ``[N, H]`` fp32)
    -> ``(out, k_pool', v_pool', k_scales', v_scales', qerr)``: the
    chunk write is FUSED — touched blocks are merged (old prefix below
    ``start``, chunk values, stale positions zeroed), requantized with
    fresh per-(block, head) absmax scales (``ops.quant.quantize_kv_block``
    policy verbatim, sanitize included) and scattered in-kernel through
    an aliased table-indexed output; ``qerr`` is the scalar max-abs
    dequant error over the written span. Rows must not share touched
    blocks (prefix blocks may be shared — they are read-only here).

    ``starts + S`` must fit the table capacity ``M * bs``. One compiled
    program serves every ``start`` at a given (S, M, bs, D) — the
    engine's frozen program-count contract.

    ``q_offsets [B]`` int32 (float pools only) decouples the QUERY
    origin from the chunk origin: query ``i`` of row ``b`` sits at
    absolute position ``q_offsets[b] + i`` while the chunk K/V still
    occupy ``[starts[b], starts[b] + S_kc)``. ``q`` may then carry
    fewer rows than the chunk (``S_q != S_kc``) — the sequence-sharded
    prefill hands each mesh shard its slice of the chunk's queries
    against the full chunk. Requires ``starts[b] <= q_offsets[b]``
    per row (queries never predate the prefix boundary). One compiled
    program per (S_q, S_kc, M, bs, D) — chunked continuation and
    shared-prefix starts stay traced scalars.
    """
    b, h, s_chunk, d = q.shape
    if q_offsets is not None:
        if block_scales is not None:
            raise ValueError(
                "q_offsets is a read-layout feature of the float path; "
                "int8 pools fuse the block write and need the full "
                "chunk's queries resident (use the per-shard fused "
                "write on head-resharded operands instead)")
        if k_chunk.shape[:2] != q.shape[:2] \
                or k_chunk.shape[3] != d \
                or v_chunk.shape != k_chunk.shape:
            raise ValueError(
                f"chunk k/v {k_chunk.shape}/{v_chunk.shape} do not "
                f"match q {q.shape} on (B, H, D)")
    elif k_chunk.shape != q.shape or v_chunk.shape != q.shape:
        raise ValueError(
            f"chunk k/v {k_chunk.shape}/{v_chunk.shape} do not match q "
            f"{q.shape}")
    if k_pool.shape != v_pool.shape or k_pool.shape[1] != h \
            or k_pool.shape[3] != d:
        raise ValueError(
            f"paged k/v pools {k_pool.shape}/{v_pool.shape} do not "
            f"match q {q.shape}")
    if block_tables.shape[0] != b:
        raise ValueError(
            f"block_tables {block_tables.shape} does not match batch "
            f"{b}")
    if block_scales is not None:
        ks, vs = block_scales
        want = (k_pool.shape[0], h)
        if tuple(ks.shape) != want or tuple(vs.shape) != want:
            raise ValueError(
                f"block_scales {ks.shape}/{vs.shape} must be "
                f"[num_blocks, H] = {want}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    if q_offsets is not None:
        return _prefill_qoff_call(q, k_chunk, v_chunk, k_pool, v_pool,
                                  block_tables, starts, q_offsets,
                                  scale, interpret)
    return _prefill_call(q, k_chunk, v_chunk, k_pool, v_pool,
                         block_tables, starts, scale, interpret,
                         block_scales=block_scales)


def flash_prefill_attention_sharded(q, k_chunk, v_chunk, k_pool, v_pool,
                                    block_tables, starts, mesh, *,
                                    scale: Optional[float] = None,
                                    block_scales=None,
                                    interpret: Optional[bool] = None,
                                    q_offsets=None):
    """:func:`flash_prefill_attention` PER SHARD under a nested
    ``shard_map`` over the mesh's ``tp`` (head) axis — the sharded
    serve engine's prefill path, same idiom as
    ``flash_decode_attention_sharded``: heads are embarrassingly
    parallel (each head's online softmax and each head's block write
    touch only its own H slice), so q/chunks/pools/scales shard on H
    while the block table and per-row starts REPLICATE (block
    identities are mesh-invariant host bookkeeping). ``scale`` defaults
    per shard to ``1/sqrt(D)`` — D is untouched by head sharding."""
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map

    hspec = P(None, "tp")
    rep = P()

    if block_scales is not None:
        ks, vs = block_scales

        def body_q(q_, kc_, vc_, kp_, vp_, t_, st_, ks_, vs_):
            out, kp_n, vp_n, ks_n, vs_n, qerr = flash_prefill_attention(
                q_, kc_, vc_, kp_, vp_, t_, st_, scale=scale,
                interpret=interpret, block_scales=(ks_, vs_))
            # Each shard's qerr covers only its own heads; the scalar
            # the engine observes is the max across the head axis.
            return out, kp_n, vp_n, ks_n, vs_n, lax.pmax(qerr, "tp")

        f = shard_map(body_q, mesh=mesh,
                      in_specs=(hspec, hspec, hspec, hspec, hspec, rep,
                                rep, hspec, hspec),
                      out_specs=(hspec, hspec, hspec, hspec, hspec,
                                 rep))
        out, kp_new, vp_new, ks_new, vs_new, qerr = f(
            q, k_chunk, v_chunk, k_pool, v_pool, block_tables, starts,
            ks, vs)
        return out, kp_new, vp_new, ks_new, vs_new, qerr

    if q_offsets is not None:
        def body_off(q_, kc_, vc_, kp_, vp_, t_, st_, qo_):
            return flash_prefill_attention(
                q_, kc_, vc_, kp_, vp_, t_, st_, scale=scale,
                interpret=interpret, q_offsets=qo_)

        f = shard_map(body_off, mesh=mesh,
                      in_specs=(hspec, hspec, hspec, hspec, hspec, rep,
                                rep, rep),
                      out_specs=hspec)
        return f(q, k_chunk, v_chunk, k_pool, v_pool, block_tables,
                 starts, q_offsets)

    def body(q_, kc_, vc_, kp_, vp_, t_, st_):
        return flash_prefill_attention(
            q_, kc_, vc_, kp_, vp_, t_, st_, scale=scale,
            interpret=interpret)

    f = shard_map(body, mesh=mesh,
                  in_specs=(hspec, hspec, hspec, hspec, hspec, rep,
                            rep),
                  out_specs=hspec)
    return f(q, k_chunk, v_chunk, k_pool, v_pool, block_tables, starts)
