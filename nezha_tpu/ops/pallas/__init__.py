"""Pallas TPU kernels for the hot fused ops.

The reference's equivalent layer is its custom CUDA kernels (softmax,
layernorm, fused elementwise — SURVEY.md §2 `pkg/cuda`). Here the hot ops
are Mosaic/Pallas kernels tiled for MXU/VPU and VMEM:

- `flash_attention`: blockwise attention, online softmax, O(S) memory.
- `flash_decode_attention`: split-K single-token decode attention over a
  pooled KV cache — per-row lengths skip KV blocks instead of masking
  them (the serving hot path).
- `flash_prefill_attention`: chunked prefill attention through the block
  table, with the int8 block write fused into the kernel epilogue (the
  TTFT hot path).
- `fused_layer_norm`: single-pass normalization on VMEM rows.

The shared online-softmax scratch core lives in `common.py`. All kernels
run in interpret mode on CPU (tests) and compile on TPU.
"""

from nezha_tpu.ops.pallas.decode_attention import (
    flash_decode_attention,
    flash_decode_attention_sharded,
)
from nezha_tpu.ops.pallas.flash_attention import flash_attention
from nezha_tpu.ops.pallas.layer_norm import fused_layer_norm
from nezha_tpu.ops.pallas.prefill_attention import (
    flash_prefill_attention,
    flash_prefill_attention_sharded,
)

__all__ = ["flash_attention", "flash_decode_attention",
           "flash_decode_attention_sharded", "flash_prefill_attention",
           "flash_prefill_attention_sharded", "fused_layer_norm"]
