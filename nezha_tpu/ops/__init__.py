"""Functional op library.

The TPU-native analogue of the reference's kernel layer (`pkg/cuda`:
cuBLAS/cuDNN GEMM+conv plus custom elementwise/softmax/layernorm kernels —
SURVEY.md §2). Here the "kernels" are jax.numpy/lax compositions XLA fuses
onto MXU/VPU, with Pallas TPU kernels for the hot fused ops in
`nezha_tpu.ops.pallas`.
"""

from nezha_tpu.ops import quant
from nezha_tpu.ops.activations import relu, gelu, silu, softmax, log_softmax
from nezha_tpu.ops.losses import (
    cross_entropy_with_logits,
    softmax_cross_entropy_with_integer_labels,
    chunked_lm_cross_entropy,
    lm_cross_entropy_from_hidden,
    lm_ce_from_fused,
    lm_objective,
    mse_loss,
    accuracy,
)
from nezha_tpu.ops.attention import (
    dot_product_attention,
    causal_mask,
    make_attention_mask,
)

__all__ = [
    "quant",
    "relu", "gelu", "silu", "softmax", "log_softmax",
    "cross_entropy_with_logits", "softmax_cross_entropy_with_integer_labels",
    "chunked_lm_cross_entropy", "lm_cross_entropy_from_hidden",
    "lm_ce_from_fused", "lm_objective",
    "mse_loss", "accuracy",
    "dot_product_attention", "causal_mask", "make_attention_mask",
]
