"""Losses and metrics. Loss math always in fp32 even under a bf16 policy."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu.ops.activations import log_softmax


def cross_entropy_with_logits(logits, labels_onehot):
    """Mean CE; ``labels_onehot`` may be soft (label smoothing)."""
    logits = jnp.asarray(logits, jnp.float32)
    logp = log_softmax(logits)
    return -jnp.mean(jnp.sum(labels_onehot * logp, axis=-1))


def softmax_cross_entropy_with_integer_labels(logits, labels,
                                              ignore_index: int | None = None,
                                              label_smoothing: float = 0.0):
    """Mean CE over integer labels; positions equal to ``ignore_index`` are
    masked out (BERT MLM uses this for unmasked positions).

    ``label_smoothing=eps`` trains against ``(1-eps)*one_hot + eps/V``
    (the standard ImageNet recipe) — computed as a blend of the picked
    log-prob and the mean log-prob, so no [.., V] target tensor is built."""
    logits = jnp.asarray(logits, jnp.float32)
    logp = log_softmax(logits)
    safe_labels = jnp.where(labels == (ignore_index if ignore_index is not None else -1),
                            0, labels)
    picked = jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    if label_smoothing:
        eps = label_smoothing
        picked = (1.0 - eps) * picked + eps * jnp.mean(logp, axis=-1)
    if ignore_index is None:
        return -jnp.mean(picked)
    mask = (labels != ignore_index).astype(jnp.float32)
    return -jnp.sum(picked * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def lm_cross_entropy_from_hidden(hidden, emb, targets,
                                 ignore_index: int | None = None, bias=None):
    """Tied-head LM CE with compute-dtype (bf16) logits and the fp32 upcast
    fused into the logsumexp reduction — the fp32 [B,S,V] tensor is never
    written to HBM. Measured on v5e (GPT-2 124M, B=8 S=1024): +3% step
    throughput over casting the dense logits to fp32 first; equal loss to
    within bf16 rounding. Use ``chunked_lm_cross_entropy`` instead when
    even the compute-dtype logits don't fit.

    ``ignore_index``/``bias`` serve BERT MLM (mask out unmasked positions;
    per-vocab output bias), same contract as
    ``softmax_cross_entropy_with_integer_labels``."""
    logits = hidden @ emb.astype(hidden.dtype).T  # [B,S,V] compute dtype
    if bias is not None:
        logits = logits + bias.astype(logits.dtype)
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    if ignore_index is None:
        picked = jnp.take_along_axis(logits, targets[..., None],
                                     axis=-1)[..., 0]
        return jnp.mean(lse - picked.astype(jnp.float32))
    safe = jnp.where(targets == ignore_index, 0, targets)
    picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    mask = (targets != ignore_index).astype(jnp.float32)
    nll = (lse - picked.astype(jnp.float32)) * mask
    return jnp.sum(nll) / jnp.maximum(jnp.sum(mask), 1.0)


def chunked_lm_cross_entropy(hidden, emb, targets, chunk: int = 128,
                             ignore_index: int | None = None, bias=None):
    """Tied-head LM cross-entropy that never materializes [B, S, V] logits.

    The fp32 logit tensor is the GPT-2 HBM bottleneck (124M at B=8 S=1024:
    1.6 GB live through the loss/backward window — BENCH_NOTES r2). Here the
    sequence is processed in ``chunk``-position slices inside a ``lax.scan``:
    each slice computes its [B, chunk, V] logits on the MXU (bf16 inputs,
    fp32 accumulation — same recipe as the flash kernel), folds them into
    the CE sum, and frees them; ``jax.checkpoint`` recomputes the slice in
    the backward pass, so peak logit memory is S/chunk times smaller in both
    directions.

    ``hidden``: [B, S, H] final activations; ``emb``: [V, H] tied embedding
    table; ``targets``: [B, S] int labels; positions whose label equals
    ``ignore_index`` are masked out of the mean (same contract as
    ``softmax_cross_entropy_with_integer_labels``, in both the chunked path
    and the ragged-tail fallback). Returns the mean CE (fp32).
    """
    b, s, h = hidden.shape
    emb = emb.astype(hidden.dtype)
    if s <= chunk:  # one chunk's worth or less: dense is strictly cheaper
        logits = jnp.einsum("bsh,vh->bsv", hidden, emb,
                            preferred_element_type=jnp.float32)
        if bias is not None:
            logits = logits + bias.astype(logits.dtype)
        return softmax_cross_entropy_with_integer_labels(
            logits, targets, ignore_index=ignore_index)
    if s % chunk:
        # Never silently materialize the dense logits the chunked path
        # exists to avoid — at long context that IS the OOM.
        raise ValueError(
            f"sequence length {s} not divisible by loss chunk {chunk}; "
            f"pick a divisor (or <= {chunk} positions for the dense path)")
    n = s // chunk
    h_chunks = hidden.reshape(b, n, chunk, h).transpose(1, 0, 2, 3)
    t_chunks = targets.reshape(b, n, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def body(carry, ht):
        nll_sum, count = carry
        hc, tc = ht
        logits = jnp.einsum("bch,vh->bcv", hc, emb,
                            preferred_element_type=jnp.float32)
        if bias is not None:
            logits = logits + bias.astype(logits.dtype)
        logp = log_softmax(logits)
        if ignore_index is None:  # static: no masking, like the dense path
            picked = jnp.take_along_axis(logp, tc[..., None],
                                         axis=-1)[..., 0]
            return (nll_sum - jnp.sum(picked),
                    count + jnp.float32(picked.size)), None
        safe = jnp.where(tc == ignore_index, 0, tc)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        mask = (tc != ignore_index).astype(jnp.float32)
        return (nll_sum - jnp.sum(picked * mask),
                count + jnp.sum(mask)), None

    (total, count), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (h_chunks, t_chunks))
    return total / jnp.maximum(count, 1.0)


def lm_ce_from_fused(out: dict, targets, ignore_index: int | None = None):
    """CE from a fused-head model output dict ({"hidden", "wte", "chunk"},
    optional "bias" — see ``GPT2Config.fused_loss_chunk`` and
    ``BertConfig.fused_loss_chunk``). The single interpreter of that
    protocol: chunk == -1 -> dense bf16-logit logsumexp fusion; chunk > 0
    -> sequence-chunked scan."""
    bias = out.get("bias")
    if out["chunk"] == -1:
        return lm_cross_entropy_from_hidden(out["hidden"], out["wte"],
                                            targets,
                                            ignore_index=ignore_index,
                                            bias=bias)
    return chunked_lm_cross_entropy(out["hidden"], out["wte"], targets,
                                    chunk=out["chunk"],
                                    ignore_index=ignore_index, bias=bias)


def lm_objective(out, targets, ignore_index: int | None = None):
    """Next-token CE for ANY GPT-2 ``apply()`` output shape: dense logits,
    the MoE {"logits", "aux_loss"} dict, or the fused-head dict (with or
    without "aux_loss"). Pre-weighted MoE load-balance aux is added when
    present. The single objective used by ``models.gpt2.lm_loss`` and the
    sequence-parallel train step's default loss."""
    if isinstance(out, dict):
        aux = out.get("aux_loss", 0.0)
        if "logits" in out:
            return softmax_cross_entropy_with_integer_labels(
                out["logits"], targets, ignore_index=ignore_index) + aux
        return lm_ce_from_fused(out, targets,
                                ignore_index=ignore_index) + aux
    return softmax_cross_entropy_with_integer_labels(
        out, targets, ignore_index=ignore_index)


def mse_loss(pred, target):
    pred = jnp.asarray(pred, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    return jnp.mean((pred - target) ** 2)


def accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
