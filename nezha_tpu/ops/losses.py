"""Losses and metrics. Loss math always in fp32 even under a bf16 policy."""

from __future__ import annotations

import jax.numpy as jnp

from nezha_tpu.ops.activations import log_softmax


def cross_entropy_with_logits(logits, labels_onehot):
    """Mean CE; ``labels_onehot`` may be soft (label smoothing)."""
    logits = jnp.asarray(logits, jnp.float32)
    logp = log_softmax(logits)
    return -jnp.mean(jnp.sum(labels_onehot * logp, axis=-1))


def softmax_cross_entropy_with_integer_labels(logits, labels, ignore_index: int | None = None):
    """Mean CE over integer labels; positions equal to ``ignore_index`` are
    masked out (BERT MLM uses this for unmasked positions)."""
    logits = jnp.asarray(logits, jnp.float32)
    logp = log_softmax(logits)
    safe_labels = jnp.where(labels == (ignore_index if ignore_index is not None else -1),
                            0, labels)
    picked = jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    if ignore_index is None:
        return -jnp.mean(picked)
    mask = (labels != ignore_index).astype(jnp.float32)
    return -jnp.sum(picked * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def mse_loss(pred, target):
    pred = jnp.asarray(pred, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    return jnp.mean((pred - target) ** 2)


def accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
