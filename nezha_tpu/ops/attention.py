"""Scaled dot-product attention (reference composition).

The reference composes attention out of graph ops (SURVEY.md §1: "attention-
as-composed-ops"); here the baseline path is einsum+softmax that XLA fuses
on the MXU. A Pallas flash-attention kernel (`nezha_tpu.ops.pallas`) serves
as the fused production path on TPU where available. Softmax accumulates in
fp32 regardless of compute dtype.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def causal_mask(q_len: int, kv_len: int, dtype=jnp.float32):
    """Additive mask: 0 where attendable, -inf above the diagonal."""
    i = jnp.arange(q_len)[:, None]
    j = jnp.arange(kv_len)[None, :]
    offset = kv_len - q_len  # supports q being a suffix of kv (decoding)
    return jnp.where(j <= i + offset, 0.0, -jnp.inf).astype(dtype)


def make_attention_mask(padding_mask):
    """[B, S] boolean (True = real token) -> [B, 1, 1, S] additive mask."""
    m = jnp.where(padding_mask, 0.0, -jnp.inf).astype(jnp.float32)
    return m[:, None, None, :]


def dot_product_attention(q, k, v, mask: Optional[jnp.ndarray] = None,
                          scale: Optional[float] = None):
    """q,k,v: [B, H, S, D]. ``mask`` additive, broadcastable to [B,H,Sq,Sk]."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        scores = scores + mask
    weights = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)
