"""nezha_tpu — a TPU-native deep-learning training framework.

A ground-up rebuild of the capabilities of fast-ml/nezha (a Go distributed
training framework with a cgo CUDA/NCCL backend) designed TPU-first:

- compute is JAX/XLA (the MXU does the GEMMs/convs cuBLAS/cuDNN did),
- collectives are XLA collectives over ICI (psum / reduce-scatter /
  all-gather / ppermute) in place of cgo NCCL ring collectives,
- device memory is XLA/PJRT device buffers in place of cudaMalloc,
- the op graph lowers to StableHLO and is JIT-compiled (SURVEY.md §0
  "north_star"), with an explicit graph IR in `nezha_tpu.graph`,
- hot ops get Pallas TPU kernels in `nezha_tpu.ops.pallas`,
- scale-out is a `jax.sharding.Mesh` + shard_map (DP, ZeRO-1, tensor,
  and sequence/ring-attention parallelism) in `nezha_tpu.parallel`,
- the host-side runtime mirrors the reference's goroutine pool + gRPC
  coordinator (SURVEY.md §1): a prefetching worker pool in
  `nezha_tpu.runtime` and a native C++ coordinator/loader under `csrc/`.

Reference parity note: /root/reference was EMPTY when surveyed (see
SURVEY.md blocker note), so parity citations point at SURVEY.md sections,
which were derived from BASELINE.json.
"""

__version__ = "0.1.0"

from nezha_tpu import nn, ops, optim, parallel, models, data, train, graph, runtime
from nezha_tpu import dist, obs, utils, faults

__all__ = [
    "nn",
    "ops",
    "optim",
    "parallel",
    "models",
    "data",
    "train",
    "graph",
    "runtime",
    "dist",
    "obs",
    "utils",
    "faults",
    "__version__",
]
