"""Weight interchange with the Hugging Face GPT-2 checkpoint format.

``gpt2_params_from_hf`` maps a ``transformers.GPT2LMHeadModel`` state dict
onto this framework's parameter pytree (HF's Conv1D already stores weights
[in, out], matching ``nn.Linear``), so published GPT-2 checkpoints load
directly and — the other direction — our trained params can be exported.
The numerical contract (LayerNorm eps 1e-5, tanh-approx GELU, pre-norm
blocks, tied LM head) is verified against the torch reference in
tests/test_convert.py.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nezha_tpu.models.gpt2 import GPT2, GPT2Config


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().cpu().numpy()
    return np.asarray(t)


def gpt2_config_from_hf(hf_config) -> GPT2Config:
    # Reject config values the framework can't express — silent numeric
    # divergence is worse than a conversion error.
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported activation_function={act!r}; "
                         "the GPT-2 block uses tanh-approximate GELU")
    eps = getattr(hf_config, "layer_norm_epsilon", 1e-5)
    if abs(eps - 1e-5) > 1e-12:
        raise ValueError(f"unsupported layer_norm_epsilon={eps}; "
                         "GPT-2 layers use eps=1e-5")
    for flag in ("scale_attn_by_inverse_layer_idx", "reorder_and_upcast_attn"):
        if getattr(hf_config, flag, False):
            raise ValueError(f"unsupported GPT2Config.{flag}=True")
    # n_inner=None means 4*n_embd (the HF default); a set value must divide
    # evenly into a ratio or the config can't represent the checkpoint.
    n_inner = getattr(hf_config, "n_inner", None)
    if n_inner is None:
        mlp_ratio = 4
    elif n_inner % hf_config.n_embd == 0:
        mlp_ratio = n_inner // hf_config.n_embd
    else:
        raise ValueError(
            f"n_inner={n_inner} is not a multiple of n_embd="
            f"{hf_config.n_embd}; GPT2Config.mlp_ratio cannot express it")
    return GPT2Config(
        vocab_size=hf_config.vocab_size,
        max_positions=hf_config.n_positions,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        hidden_size=hf_config.n_embd,
        mlp_ratio=mlp_ratio,
        dropout=0.0,
    )


def gpt2_params_from_hf(state_dict: Dict[str, Any],
                        num_layers: int) -> Dict[str, Any]:
    """HF ``transformer.*`` state dict -> nezha_tpu GPT-2 params pytree."""
    sd = {k: _np(v) for k, v in state_dict.items()}

    def pre(k):  # checkpoints may or may not carry the "transformer." prefix
        return sd[k if k in sd else f"transformer.{k}"]

    params: Dict[str, Any] = {
        "wte": {"embedding": pre("wte.weight")},
        "wpe": {"embedding": pre("wpe.weight")},
        "ln_f": {"scale": pre("ln_f.weight"), "bias": pre("ln_f.bias")},
    }
    for i in range(num_layers):
        h = f"h.{i}"
        params[f"h{i}"] = {
            "ln_1": {"scale": pre(f"{h}.ln_1.weight"),
                     "bias": pre(f"{h}.ln_1.bias")},
            "attn": {
                "qkv": {"w": pre(f"{h}.attn.c_attn.weight"),
                        "b": pre(f"{h}.attn.c_attn.bias")},
                "proj": {"w": pre(f"{h}.attn.c_proj.weight"),
                         "b": pre(f"{h}.attn.c_proj.bias")},
            },
            "ln_2": {"scale": pre(f"{h}.ln_2.weight"),
                     "bias": pre(f"{h}.ln_2.bias")},
            "mlp": {
                "fc": {"w": pre(f"{h}.mlp.c_fc.weight"),
                       "b": pre(f"{h}.mlp.c_fc.bias")},
                "proj": {"w": pre(f"{h}.mlp.c_proj.weight"),
                         "b": pre(f"{h}.mlp.c_proj.bias")},
            },
        }
    return params


def gpt2_from_hf(hf_model) -> tuple:
    """(model, variables) from a ``transformers.GPT2LMHeadModel``."""
    import jax.numpy as jnp
    import jax.tree_util as jtu

    cfg = gpt2_config_from_hf(hf_model.config)
    model = GPT2(cfg)
    params = gpt2_params_from_hf(hf_model.state_dict(), cfg.num_layers)
    params = jtu.tree_map(lambda x: jnp.asarray(x, jnp.float32), params)
    return model, {"params": params, "state": {}}


def gpt2_params_to_hf(params: Dict[str, Any],
                      num_layers: int) -> Dict[str, np.ndarray]:
    """Export back to the HF ``transformer.*`` key layout (numpy)."""
    out = {
        "transformer.wte.weight": _np(params["wte"]["embedding"]),
        "transformer.wpe.weight": _np(params["wpe"]["embedding"]),
        "transformer.ln_f.weight": _np(params["ln_f"]["scale"]),
        "transformer.ln_f.bias": _np(params["ln_f"]["bias"]),
        "lm_head.weight": _np(params["wte"]["embedding"]),  # tied
    }
    for i in range(num_layers):
        blk = params[f"h{i}"]
        h = f"transformer.h.{i}"
        out[f"{h}.ln_1.weight"] = _np(blk["ln_1"]["scale"])
        out[f"{h}.ln_1.bias"] = _np(blk["ln_1"]["bias"])
        out[f"{h}.attn.c_attn.weight"] = _np(blk["attn"]["qkv"]["w"])
        out[f"{h}.attn.c_attn.bias"] = _np(blk["attn"]["qkv"]["b"])
        out[f"{h}.attn.c_proj.weight"] = _np(blk["attn"]["proj"]["w"])
        out[f"{h}.attn.c_proj.bias"] = _np(blk["attn"]["proj"]["b"])
        out[f"{h}.ln_2.weight"] = _np(blk["ln_2"]["scale"])
        out[f"{h}.ln_2.bias"] = _np(blk["ln_2"]["bias"])
        out[f"{h}.mlp.c_fc.weight"] = _np(blk["mlp"]["fc"]["w"])
        out[f"{h}.mlp.c_fc.bias"] = _np(blk["mlp"]["fc"]["b"])
        out[f"{h}.mlp.c_proj.weight"] = _np(blk["mlp"]["proj"]["w"])
        out[f"{h}.mlp.c_proj.bias"] = _np(blk["mlp"]["proj"]["b"])
    return out


# ----------------------------------------------------------------- BERT
def bert_config_from_hf(hf_config) -> "BertConfig":
    from nezha_tpu.models.bert import BertConfig

    act = getattr(hf_config, "hidden_act", "gelu")
    if act != "gelu":
        raise ValueError(f"unsupported hidden_act={act!r}; "
                         "the BERT block uses erf GELU")
    if hf_config.intermediate_size % hf_config.hidden_size:
        raise ValueError(
            f"intermediate_size={hf_config.intermediate_size} is not a "
            f"multiple of hidden_size={hf_config.hidden_size}")
    return BertConfig(
        vocab_size=hf_config.vocab_size,
        max_positions=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        hidden_size=hf_config.hidden_size,
        mlp_ratio=hf_config.intermediate_size // hf_config.hidden_size,
        dropout=0.0,
        ln_eps=hf_config.layer_norm_eps,
    )


def bert_params_from_hf(state_dict: Dict[str, Any],
                        num_layers: int) -> Dict[str, Any]:
    """HF ``BertForMaskedLM`` state dict -> nezha_tpu BERT params.

    torch Linear stores [out, in]; ours stores [in, out] — transposed
    here. The separate q/k/v projections concatenate into our fused qkv.
    """
    sd = {k: _np(v) for k, v in state_dict.items()}

    def lin(k):  # torch Linear -> (w [in,out], b)
        return {"w": sd[f"{k}.weight"].T, "b": sd[f"{k}.bias"]}

    def ln(k):
        return {"scale": sd[f"{k}.weight"], "bias": sd[f"{k}.bias"]}

    params: Dict[str, Any] = {
        "tok_emb": {"embedding":
                    sd["bert.embeddings.word_embeddings.weight"]},
        "pos_emb": {"embedding":
                    sd["bert.embeddings.position_embeddings.weight"]},
        "type_emb": {"embedding":
                     sd["bert.embeddings.token_type_embeddings.weight"]},
        "emb_ln": ln("bert.embeddings.LayerNorm"),
        "mlm_dense": lin("cls.predictions.transform.dense"),
        "mlm_ln": ln("cls.predictions.transform.LayerNorm"),
        "mlm_bias": sd["cls.predictions.bias"],
    }
    for i in range(num_layers):
        L = f"bert.encoder.layer.{i}"
        q = lin(f"{L}.attention.self.query")
        k = lin(f"{L}.attention.self.key")
        v = lin(f"{L}.attention.self.value")
        params[f"layers{i}"] = {
            "qkv": {"w": np.concatenate([q["w"], k["w"], v["w"]], axis=1),
                    "b": np.concatenate([q["b"], k["b"], v["b"]])},
            "attn_out": lin(f"{L}.attention.output.dense"),
            "attn_ln": ln(f"{L}.attention.output.LayerNorm"),
            "fc": lin(f"{L}.intermediate.dense"),
            "fc_out": lin(f"{L}.output.dense"),
            "out_ln": ln(f"{L}.output.LayerNorm"),
        }
    return params


def bert_from_hf(hf_model) -> tuple:
    """(model, variables) from a ``transformers.BertForMaskedLM``."""
    import jax.numpy as jnp
    import jax.tree_util as jtu

    from nezha_tpu.models.bert import Bert

    cfg = bert_config_from_hf(hf_model.config)
    model = Bert(cfg)
    params = bert_params_from_hf(hf_model.state_dict(), cfg.num_layers)
    params = jtu.tree_map(lambda x: jnp.asarray(x, jnp.float32), params)
    return model, {"params": params, "state": {}}


def bert_params_to_hf(params, num_layers, hidden_size):
    """Export back to the HF ``BertForMaskedLM`` key layout (numpy) — the
    inverse of :func:`bert_params_from_hf` (fused qkv splits into separate
    q/k/v; [in,out] Linears transpose back to torch's [out,in])."""
    out = {
        "bert.embeddings.word_embeddings.weight":
            _np(params["tok_emb"]["embedding"]),
        "bert.embeddings.position_embeddings.weight":
            _np(params["pos_emb"]["embedding"]),
        "bert.embeddings.token_type_embeddings.weight":
            _np(params["type_emb"]["embedding"]),
        "cls.predictions.bias": _np(params["mlm_bias"]),
        # Tied decoder: HF materializes the word embedding (and the shared
        # prediction bias) again under the decoder's own keys.
        "cls.predictions.decoder.weight":
            _np(params["tok_emb"]["embedding"]),
        "cls.predictions.decoder.bias": _np(params["mlm_bias"]),
    }

    def put_lin(key, p):
        out[f"{key}.weight"] = _np(p["w"]).T
        out[f"{key}.bias"] = _np(p["b"])

    def put_ln(key, p):
        out[f"{key}.weight"] = _np(p["scale"])
        out[f"{key}.bias"] = _np(p["bias"])

    put_ln("bert.embeddings.LayerNorm", params["emb_ln"])
    put_lin("cls.predictions.transform.dense", params["mlm_dense"])
    put_ln("cls.predictions.transform.LayerNorm", params["mlm_ln"])
    h = hidden_size
    for i in range(num_layers):
        blk = params[f"layers{i}"]
        L = f"bert.encoder.layer.{i}"
        w, b = _np(blk["qkv"]["w"]), _np(blk["qkv"]["b"])
        for j, name in enumerate(("query", "key", "value")):
            out[f"{L}.attention.self.{name}.weight"] = \
                w[:, j * h:(j + 1) * h].T
            out[f"{L}.attention.self.{name}.bias"] = b[j * h:(j + 1) * h]
        put_lin(f"{L}.attention.output.dense", blk["attn_out"])
        put_ln(f"{L}.attention.output.LayerNorm", blk["attn_ln"])
        put_lin(f"{L}.intermediate.dense", blk["fc"])
        put_lin(f"{L}.output.dense", blk["fc_out"])
        put_ln(f"{L}.output.LayerNorm", blk["out_ln"])
    return out
