"""BERT-base with an MLM head — benchmark config 4 (SURVEY.md §0:
"BERT-base — grad reduce-scatter + weight all-gather (ZeRO-1-style)").

Bidirectional encoder; padding handled with an additive mask; MLM loss masks
to the 15% corrupted positions via ``ignore_index=-100`` labels. Train with
`nezha_tpu.parallel.make_zero1_train_step` for the ZeRO-1 benchmark path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from nezha_tpu import nn, ops
from nezha_tpu.nn import initializers as init_lib
from nezha_tpu.nn.module import Module, Variables, child_vars, run_child
from nezha_tpu.tensor.policy import DEFAULT_POLICY, Policy, bf16_policy


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_positions: int = 512
    type_vocab_size: int = 2
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    mlp_ratio: int = 4
    dropout: float = 0.0
    # Published BERT checkpoints use 1e-12 (HF layer_norm_eps); kept in the
    # config so converted weights reproduce the torch reference exactly.
    ln_eps: float = 1e-12
    # Same protocol as GPT2Config.fused_loss_chunk: 0 -> dense fp32 logits
    # returned from apply(); -1 -> defer the tied decoder to the loss so the
    # CE keeps bf16 logits with the fp32 upcast fused into logsumexp (never
    # materializes fp32 [B,S,30522] — ~1 GB/step at B=16 S=512); >0 ->
    # sequence-chunked scan. Training-only; eval/convert paths get logits.
    fused_loss_chunk: int = 0
    # "auto": the Pallas flash kernel (causal=False) on TPU backends when a
    # layer sees NO padding mask — full-length batches, the packed-sequence
    # pretraining shape; the kernel has no arbitrary-mask path, so any
    # padding_mask falls back to composed XLA attention. Mirrors
    # GPT2Config.attn_impl (incl. the GSPMD auto-partitioner fallback).
    attn_impl: str = "auto"  # "xla" | "flash" | "auto"
    # Layer-stacked encoder applied via lax.scan — one compiled layer
    # program instead of num_layers inlined copies; params live under
    # "layers_scan" with a leading [num_layers] dim. Mirrors
    # GPT2Config.scan_layers (same parity contract, same converters via
    # nn.module.stack_prefixed_params).
    scan_layers: bool = False
    # "pallas" opts the 2/layer + emb + mlm layer norms into the fused
    # kernel on TPU (mirrors GPT2Config.ln_impl; default flips only on a
    # measured A/B win).
    ln_impl: str = "xla"


class EncoderLayer(Module):
    """Post-LN transformer encoder layer (original BERT topology)."""

    def __init__(self, cfg: BertConfig, policy: Policy):
        h = cfg.hidden_size
        self.cfg = cfg
        self.qkv = nn.Linear(h, 3 * h, kernel_init=init_lib.normal(0.02),
                             policy=policy)
        self.attn_out = nn.Linear(h, h, kernel_init=init_lib.normal(0.02),
                                  policy=policy)
        self.attn_ln = nn.LayerNorm(h, eps=cfg.ln_eps, policy=policy,
                                    impl=cfg.ln_impl)
        self.fc = nn.Linear(h, h * cfg.mlp_ratio,
                            kernel_init=init_lib.normal(0.02), policy=policy)
        self.fc_out = nn.Linear(h * cfg.mlp_ratio, h,
                                kernel_init=init_lib.normal(0.02), policy=policy)
        self.out_ln = nn.LayerNorm(h, eps=cfg.ln_eps, policy=policy,
                                   impl=cfg.ln_impl)
        self.drop = nn.Dropout(cfg.dropout)

    def apply(self, variables: Variables, x, mask=None, training: bool = False,
              rng=None, kv_lengths=None):
        cfg = self.cfg
        b, s, h = x.shape
        d = h // cfg.num_heads
        states: dict = {}
        qkv = run_child(self.qkv, "qkv", variables, states, x, training=training)
        qkv = qkv.reshape(b, s, 3, cfg.num_heads, d).transpose(2, 0, 3, 1, 4)
        impl = cfg.attn_impl
        if impl == "auto":
            from nezha_tpu.models.gpt2 import _resolve_auto_impl
            impl = _resolve_auto_impl(cfg) if mask is None else "xla"
        if impl == "flash_shmap":
            if mask is not None:
                raise ValueError("attn_impl='flash_shmap' cannot apply an "
                                 "arbitrary padding mask; use right-padded "
                                 "batches with kv_lengths, or 'xla'")
            from nezha_tpu.models.gpt2 import _tp_sharded_flash
            from nezha_tpu.parallel.gspmd import auto_partitioner_mesh
            mesh = auto_partitioner_mesh()
            if mesh is None or "tp" not in mesh.axis_names \
                    or cfg.num_heads % mesh.shape["tp"]:
                raise ValueError(
                    f"attn_impl='flash_shmap' needs an enclosing gspmd "
                    f"trace carrying a mesh with a 'tp' axis dividing "
                    f"num_heads={cfg.num_heads}")
            att = _tp_sharded_flash(qkv[0], qkv[1], qkv[2], mesh,
                                    causal=False, kv_lengths=kv_lengths)
        elif impl == "flash":
            if mask is not None:
                raise ValueError("attn_impl='flash' cannot apply an "
                                 "arbitrary padding mask; use right-padded "
                                 "batches with kv_lengths, or 'xla'")
            from nezha_tpu.ops.pallas import flash_attention
            att = flash_attention(qkv[0], qkv[1], qkv[2], causal=False,
                                  kv_lengths=kv_lengths)
        else:
            if kv_lengths is not None and mask is None:
                # Same right-padding contract as the flash path, composed:
                # a prefix mask built from the lengths, clamped to >= 1 so
                # a fully-padded row attends to position 0 instead of
                # NaN-ing the softmax (flash_attention clamps identically).
                import jax.numpy as jnp
                mask = ops.make_attention_mask(
                    jnp.arange(s)[None, :]
                    < jnp.maximum(kv_lengths, 1)[:, None])
            att = ops.dot_product_attention(qkv[0], qkv[1], qkv[2], mask=mask)
        att = att.transpose(0, 2, 1, 3).reshape(b, s, h)
        att = run_child(self.attn_out, "attn_out", variables, states, att,
                        training=training)
        att = run_child(self.drop, "drop", variables, states, att,
                        training=training, rng=rng)
        x = run_child(self.attn_ln, "attn_ln", variables, states, x + att,
                      training=training)
        y = run_child(self.fc, "fc", variables, states, x, training=training)
        y = ops.gelu(y, approximate=False)  # original BERT uses erf GELU
        y = run_child(self.fc_out, "fc_out", variables, states, y,
                      training=training)
        return run_child(self.out_ln, "out_ln", variables, states, x + y,
                         training=training), states


class ScannedEncoder(Module):
    """``num_layers`` homogeneous :class:`EncoderLayer`s with layer-stacked
    params, applied via ``lax.scan`` (one compiled layer program; see
    ``models.gpt2.ScannedBlocks`` for the full rationale). ``mask`` /
    ``kv_lengths`` are layer-invariant broadcast inputs (closures), not
    scan operands; per-layer dropout RNGs pre-split with the SAME
    ``layers{i}`` derivation as the unrolled encoder."""

    _init_with_parent_rng = True  # layer keys derive from Bert's rng

    def __init__(self, cfg: BertConfig, policy: Policy):
        self.cfg = cfg
        self.layer = EncoderLayer(cfg, policy)  # structure template

    def init(self, rng: jax.Array) -> Variables:
        from nezha_tpu.nn.module import scan_stack_init
        return scan_stack_init(self.layer, rng, self.cfg.num_layers,
                               "layers")

    def apply(self, variables: Variables, x, mask=None, training: bool = False,
              rng=None, kv_lengths=None):
        from nezha_tpu.nn.module import scan_stack_apply
        x = scan_stack_apply(self.layer, variables["params"], x,
                             self.cfg.num_layers, "layers", rng=rng,
                             mask=mask, training=training,
                             kv_lengths=kv_lengths)
        return x, {}


class Bert(Module):
    """Returns MLM logits [B, S, vocab] (decoder tied to token embeddings).

    ``batch``: {"tokens": [B,S], "segment_ids": [B,S], "padding_mask": [B,S]
    bool, "labels": [B,S] with -100 at unmasked positions}.
    """

    def __init__(self, cfg: BertConfig = BertConfig(),
                 policy: Policy = DEFAULT_POLICY):
        self.cfg = cfg
        self.policy = policy
        h = cfg.hidden_size
        self.tok_emb = nn.Embedding(cfg.vocab_size, h, policy=policy)
        self.pos_emb = nn.Embedding(cfg.max_positions, h,
                                    embedding_init=init_lib.normal(0.02),
                                    policy=policy)
        self.type_emb = nn.Embedding(cfg.type_vocab_size, h, policy=policy)
        self.emb_ln = nn.LayerNorm(h, eps=cfg.ln_eps, policy=policy,
                                   impl=cfg.ln_impl)
        self.drop = nn.Dropout(cfg.dropout)
        if cfg.scan_layers:
            self.layers_scan = ScannedEncoder(cfg, policy)
            self.layers = []
        else:
            self.layers = [EncoderLayer(cfg, policy)
                           for _ in range(cfg.num_layers)]
        # MLM head: transform + LN, decoder tied to tok_emb with a free bias.
        self.mlm_dense = nn.Linear(h, h, kernel_init=init_lib.normal(0.02),
                                   policy=policy)
        self.mlm_ln = nn.LayerNorm(h, eps=cfg.ln_eps, policy=policy,
                                   impl=cfg.ln_impl)

    def init(self, rng: jax.Array) -> Variables:
        v = super().init(rng)
        v["params"]["mlm_bias"] = jnp.zeros((self.cfg.vocab_size,),
                                            self.policy.param_dtype)
        return v

    def apply(self, variables: Variables, batch, training: bool = False, rng=None):
        tokens = batch["tokens"]
        segment_ids = batch.get("segment_ids")
        padding_mask = batch.get("padding_mask")
        # Right-padded batches: "kv_lengths" ([B] int32, each >= 1) keeps
        # the flash path (the kernel masks key columns >= length); the
        # composed path builds the equivalent prefix mask. Mutually
        # exclusive with an explicit padding_mask.
        kv_lengths = batch.get("kv_lengths") if isinstance(batch, dict) \
            else None
        if kv_lengths is not None and padding_mask is not None:
            raise ValueError("pass either padding_mask or kv_lengths, "
                             "not both")
        states: dict = {}
        s = tokens.shape[1]
        if s > self.cfg.max_positions:
            # Without this, the position-embedding gather silently clamps.
            raise ValueError(
                f"sequence length {s} exceeds max_positions "
                f"{self.cfg.max_positions}")
        pos = jnp.arange(s)[None, :]
        x = run_child(self.tok_emb, "tok_emb", variables, states, tokens,
                      training=training)
        x = x + run_child(self.pos_emb, "pos_emb", variables, states, pos,
                          training=training)
        if segment_ids is not None:
            x = x + run_child(self.type_emb, "type_emb", variables, states,
                              segment_ids, training=training)
        x = run_child(self.emb_ln, "emb_ln", variables, states, x,
                      training=training)
        x = run_child(self.drop, "drop", variables, states, x,
                      training=training, rng=rng)
        mask = (ops.make_attention_mask(padding_mask)
                if padding_mask is not None else None)
        if self.cfg.scan_layers:
            # rng passed RAW: ScannedEncoder derives per-layer layers{i}
            # keys itself, matching the unrolled encoder exactly.
            x, _ = self.layers_scan.apply(
                child_vars(variables, "layers_scan"), x, mask=mask,
                training=training, rng=rng, kv_lengths=kv_lengths)
        for i, layer in enumerate(self.layers):
            x = run_child(layer, f"layers{i}", variables, states, x,
                          mask=mask, training=training, rng=rng,
                          kv_lengths=kv_lengths)
        y = run_child(self.mlm_dense, "mlm_dense", variables, states, x,
                      training=training)
        y = ops.gelu(y, approximate=False)  # original BERT uses erf GELU
        y = run_child(self.mlm_ln, "mlm_ln", variables, states, y,
                      training=training)
        if self.cfg.fused_loss_chunk and training:
            # Defer the tied decoder to the loss (mlm_loss ->
            # ops.lm_ce_from_fused): bf16 logits with the fp32 upcast fused
            # into logsumexp, or a chunked scan — the fp32 [B,S,V] tensor is
            # never written to HBM. Same protocol as GPT-2's fused head.
            wte = child_vars(variables, "tok_emb")["params"]["embedding"]
            return {"hidden": y, "wte": wte,
                    "bias": variables["params"]["mlm_bias"],
                    "chunk": self.cfg.fused_loss_chunk}, states
        logits = self.tok_emb.attend(child_vars(variables, "tok_emb"), y)
        logits = logits + self.policy.cast_to_compute(
            variables["params"]["mlm_bias"])
        return jnp.asarray(logits, jnp.float32), states


def bert_base(policy: Policy | None = None, **overrides) -> Bert:
    cfg = BertConfig(**overrides)
    return Bert(cfg, policy=policy or bf16_policy())


def mlm_loss(out, batch):
    """MLM CE over the 15% corrupted positions (labels == -100 elsewhere).
    Accepts dense logits or the fused-head dict (BertConfig.fused_loss_chunk)."""
    if isinstance(out, dict):
        return ops.lm_ce_from_fused(out, batch["labels"], ignore_index=-100)
    return ops.softmax_cross_entropy_with_integer_labels(
        out, batch["labels"], ignore_index=-100)
