"""ResNet family: ResNet-50 and Wide-ResNet-101-2.

Benchmark configs 2 and 5 (SURVEY.md §0: "ResNet-50 / ImageNet
data-parallel, all-reduce" and "Wide-ResNet-101, large-batch mixed
bf16/fp32"). TPU-first choices: NHWC layout throughout (XLA:TPU's native
conv layout), BatchNorm stats in fp32 under the bf16 policy, zero-init of
each block's last BN scale (standard large-batch trick), and a single
residual topology XLA fuses aggressively.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from nezha_tpu import nn
from nezha_tpu.nn import initializers as init_lib
from nezha_tpu.nn.module import Module, Variables, child_vars, run_child
from nezha_tpu.tensor.policy import DEFAULT_POLICY, Policy


class Bottleneck(Module):
    """1x1 -> 3x3 (stride) -> 1x1 with projection shortcut when needed."""

    def __init__(self, in_ch: int, width: int, out_ch: int, stride: int,
                 policy: Policy = DEFAULT_POLICY):
        self.conv1 = nn.Conv2d(in_ch, width, 1, use_bias=False, policy=policy)
        self.bn1 = nn.BatchNorm(width, policy=policy)
        self.conv2 = nn.Conv2d(width, width, 3, stride=stride, use_bias=False,
                               policy=policy)
        self.bn2 = nn.BatchNorm(width, policy=policy)
        self.conv3 = nn.Conv2d(width, out_ch, 1, use_bias=False, policy=policy)
        self.bn3 = nn.BatchNorm(out_ch, policy=policy)
        self.needs_proj = (in_ch != out_ch) or (stride != 1)
        if self.needs_proj:
            self.proj = nn.Conv2d(in_ch, out_ch, 1, stride=stride,
                                  use_bias=False, policy=policy)
            self.proj_bn = nn.BatchNorm(out_ch, policy=policy)

    def init(self, rng: jax.Array) -> Variables:
        v = super().init(rng)
        # Zero-init the last BN scale so each block starts as identity —
        # improves large-batch trainability (used by the WRN-101 config).
        v["params"]["bn3"]["scale"] = jnp.zeros_like(v["params"]["bn3"]["scale"])
        return v

    def apply(self, variables: Variables, x, training: bool = False, rng=None):
        states: dict = {}
        y = run_child(self.conv1, "conv1", variables, states, x, training=training)
        y = run_child(self.bn1, "bn1", variables, states, y, training=training)
        y = jnp.maximum(y, 0)
        y = run_child(self.conv2, "conv2", variables, states, y, training=training)
        y = run_child(self.bn2, "bn2", variables, states, y, training=training)
        y = jnp.maximum(y, 0)
        y = run_child(self.conv3, "conv3", variables, states, y, training=training)
        y = run_child(self.bn3, "bn3", variables, states, y, training=training)
        if self.needs_proj:
            sc = run_child(self.proj, "proj", variables, states, x, training=training)
            sc = run_child(self.proj_bn, "proj_bn", variables, states, sc,
                           training=training)
        else:
            sc = x
        return jnp.maximum(y + sc, 0), states


def _space_to_depth_stem(x: jax.Array, w: jax.Array) -> jax.Array:
    """The 7x7/stride-2 stem conv, re-expressed MXU-first.

    A 7x7 conv over 3-channel images runs the systolic array at ~9% (the
    contraction dim is 7*7*3=147 elements of which only 3 land per lane and
    the strided window defeats tiling). Space-to-depth by 2 turns the same
    arithmetic into a 4x4 stride-1 conv over 12 channels: x[2i+a-2] with
    a-2 = 2*alpha + u becomes X[i+alpha, (u,v,c)], so

        y[i,j] = sum_{alpha,beta,u,v,c} X[i+alpha, j+beta, (u,v,c)]
                                        * w_pad[2*alpha+u, 2*beta+v, c]

    with w zero-padded from 7x7 to 8x8 (index 7 is the pad row/col) and
    padding (1,2) replacing SAME's (2,3). Bit-for-bit the same dot products
    as the original conv, in a layout the MXU can actually tile. The
    parameter stays [7,7,Cin,64] so checkpoints and HF interchange are
    unchanged; the pad+reshape is traced into the graph (a no-FLOP
    relayout). Requires even H,W — callers fall back to the plain conv
    otherwise.
    """
    b, h, wd, c = x.shape
    xs = x.reshape(b, h // 2, 2, wd // 2, 2, c)
    xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, wd // 2, 4 * c)
    wp = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
    out_ch = w.shape[-1]
    ws = wp.reshape(4, 2, 4, 2, c, out_ch)
    ws = ws.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, out_ch)
    return jax.lax.conv_general_dilated(
        xs, ws, window_strides=(1, 1), padding=((1, 2), (1, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class ResNet(Module):
    """Generic bottleneck ResNet over NHWC images.

    ``width_factor=2`` gives the Wide-ResNet variants (inner bottleneck
    width doubled, output channels unchanged). ``stem="s2d"`` routes the
    7x7/s2 stem through :func:`_space_to_depth_stem` (same parameters,
    same math, MXU-tileable layout): measured worth ~+3% e2e over
    ``"conv7"`` on RN50 (2,212 vs 2,141 img/s, r4 — different windows,
    tunnel-jitter caveat; the ~3x stem-in-isolation figure from the r3
    probe arithmetic did NOT materialize e2e, the step is
    bandwidth-bound elsewhere). ``"conv7"`` keeps the plain conv.
    """

    def __init__(self, stage_sizes: Sequence[int], num_classes: int = 1000,
                 width_factor: int = 1, in_channels: int = 3,
                 stem: str = "conv7", remat: bool = False,
                 policy: Policy = DEFAULT_POLICY):
        if stem not in ("conv7", "s2d"):
            raise ValueError(f"unknown stem {stem!r}")
        self.stage_sizes = tuple(stage_sizes)
        self.stem = stem
        # Per-bottleneck jax.checkpoint: backward recomputes each block
        # from its input instead of reading saved intermediates — the
        # big-batch memory knob, and an A/B lever for the bandwidth-bound
        # step (saved-activation reads traded for recompute FLOPs;
        # rn50_probe --variants remat measures the sign on chip).
        self.remat = remat
        self.policy = policy
        self.stem_conv = nn.Conv2d(in_channels, 64, 7, stride=2,
                                   use_bias=False, policy=policy)
        self.stem_bn = nn.BatchNorm(64, policy=policy)

        self.blocks = []
        in_ch = 64
        for stage, n_blocks in enumerate(self.stage_sizes):
            base = 64 * (2 ** stage)
            width = base * width_factor
            out_ch = base * 4
            for b in range(n_blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                self.blocks.append(
                    Bottleneck(in_ch, width, out_ch, stride, policy=policy))
                in_ch = out_ch
        self.head = nn.Linear(in_ch, num_classes,
                              kernel_init=init_lib.zeros, policy=policy)

    def apply(self, variables: Variables, batch, training: bool = False, rng=None):
        x = batch["image"] if isinstance(batch, dict) else batch
        states: dict = {}
        if self.stem == "s2d" and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            pol = self.stem_conv.policy
            x = _space_to_depth_stem(
                pol.cast_to_compute(x),
                pol.cast_to_compute(variables["params"]["stem_conv"]["w"]))
        else:
            x = run_child(self.stem_conv, "stem_conv", variables, states, x,
                          training=training)
        x = run_child(self.stem_bn, "stem_bn", variables, states, x,
                      training=training)
        x = jnp.maximum(x, 0)
        x = nn.max_pool(x, 3, 2, "SAME")
        remat = self.remat and training
        for i, block in enumerate(self.blocks):
            if remat:
                # Save only each bottleneck's input; recompute its convs/
                # BNs in backward (running-stat state updates come from
                # the forward pass as usual).
                name = f"blocks{i}"

                def block_fn(bvars, xx, block=block):
                    return block.apply(bvars, xx, training=True)

                x, st = jax.checkpoint(block_fn)(
                    child_vars(variables, name), x)
                if st:
                    states[name] = st
            else:
                x = run_child(block, f"blocks{i}", variables, states, x,
                              training=training)
        x = nn.global_avg_pool(x)
        logits = run_child(self.head, "head", variables, states, x,
                           training=training)
        return jnp.asarray(logits, jnp.float32), states


def resnet50(num_classes: int = 1000, stem: str = "conv7",
             remat: bool = False,
             policy: Policy = DEFAULT_POLICY) -> ResNet:
    return ResNet((3, 4, 6, 3), num_classes=num_classes, stem=stem,
                  remat=remat, policy=policy)


def wide_resnet101(num_classes: int = 1000, stem: str = "conv7",
                   remat: bool = False,
                   policy: Policy = DEFAULT_POLICY) -> ResNet:
    """Wide-ResNet-101-2 (bottleneck width x2) — benchmark config 5."""
    return ResNet((3, 4, 23, 3), num_classes=num_classes, width_factor=2,
                  stem=stem, remat=remat, policy=policy)
