"""GPT-2 (124M default) — benchmark config 3 (SURVEY.md §0: "GPT-2 124M —
GEMM-heavy transformer; exercises bf16").

TPU-first: pre-LN blocks whose QKV/proj/MLP matmuls are large bf16 GEMMs on
the MXU; attention softmax accumulates fp32; weights tied between the token
embedding and the LM head; causal mask built once per forward (static
shapes). Sequence parallelism hooks: ``attn_impl='ring'``/``'ulysses'``
switch attention to `nezha_tpu.parallel` collectives for long context
(call inside shard_map with the ``sp`` axis).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from nezha_tpu import nn, ops
from nezha_tpu.nn import initializers as init_lib
from nezha_tpu.nn.module import Module, Variables, child_rng, child_vars, run_child
from nezha_tpu.tensor.policy import DEFAULT_POLICY, Policy, bf16_policy


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_positions: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    mlp_ratio: int = 4
    dropout: float = 0.0  # 0 for throughput benchmarking; 0.1 for GPT-2 paper
    # "auto" (default): Pallas flash kernels on TPU backends, composed
    # einsum+softmax elsewhere. Measured on v5e (bf16 fwd+bwd train step,
    # GPT-2 124M B=8 S=1024): flash 102.0k tok/s vs xla 87.0k (+17%) once
    # the kernel dots run in bf16 with tuned blocks; flash also removes the
    # S x S score buffers, so B=32 trains where the xla path OOMs.
    # "auto" | "xla" | "flash" | "flash_shmap" (flash via nested
    # shard_map over tp-sharded heads inside a gspmd trace — auto picks
    # it on TPU when tp divides the heads) | "ring" | "ulysses"
    attn_impl: str = "auto"
    sp_axis: str = "sp"
    # ring/ulysses flash policy: None = auto (flash kernels on TPU,
    # composed elsewhere); True/False force it — the escape hatch back to
    # the composed sp paths on hardware without editing source.
    sp_use_flash: "bool | None" = None
    # Fused LM head: apply() returns {"hidden", "wte"} instead of logits and
    # `lm_loss` computes the CE without materializing fp32 [B,S,V] (1.6 GB
    # at B=8 S=1024). 0 = off (logits API, decode/HF paths). -1 = dense
    # compute-dtype logits with the fp32 upcast fused into logsumexp
    # (fastest on v5e: +3% e2e). >0 = sequence-chunked scan of this many
    # positions (ops.losses.chunked_lm_cross_entropy) — slower (-10% e2e,
    # measured) but peak logit memory drops S/chunk-fold in BOTH dtypes;
    # for very long context / big batch where even bf16 logits blow HBM.
    fused_loss_chunk: int = 0
    # Mixture-of-experts: >0 swaps every `moe_every`-th block's MLP for a
    # top-k routed expert layer (`parallel.expert.MoE`, dense-dispatch,
    # EP-shardable over an "ep" mesh axis). apply() then returns a dict
    # carrying the weighted load-balance aux loss, which `lm_loss` adds.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2  # blocks 1, 3, 5, ... are MoE when moe_every=2
    moe_aux_weight: float = 0.01
    # Single-token KV-cache decode attention (the serving hot path):
    # "auto" (default) runs the Pallas flash-decode kernel
    # (ops/pallas/decode_attention.py — split-K online softmax, per-row
    # lengths skip KV blocks) under the same backend policy as the
    # prefill flash path (compiled on TPU, composed masked attention
    # elsewhere, and wherever attn_impl itself forces "xla");
    # "kernel" forces the kernel (interpret mode off-TPU — the parity-
    # test path); "xla" forces the composed masked path.
    # NEZHA_NO_DECODE_KERNEL=1 is the day-1 escape hatch back to the
    # composed path without editing configs.
    decode_impl: str = "auto"
    # Paged prefill-chunk attention (the serving TTFT path): "auto"
    # (default) runs the Pallas flash-prefill kernel
    # (ops/pallas/prefill_attention.py — online softmax over the block
    # table with per-row start offsets; on int8 pools the block write
    # fuses into the kernel epilogue, replacing the whole
    # _quant_prefill_write gather/requant round trip) under the same
    # backend policy as decode_impl; "kernel" forces it (interpret mode
    # off-TPU — the parity-test path); "xla" forces the composed
    # masked path. NEZHA_NO_PREFILL_KERNEL=1 is the escape hatch back
    # to the composed path without editing configs. Only the paged
    # cache layout routes here — dense-slot prefill keeps the
    # attn_impl-resolved path.
    prefill_impl: str = "auto"
    # "pallas" opts layer norms into the fused kernel (fwd + bwd) on TPU.
    ln_impl: str = "xla"
    # Rematerialize each transformer block in backward (jax.checkpoint):
    # activation memory drops from O(layers) residuals to O(1) per block at
    # ~1/3 extra FLOPs — the long-context / big-batch memory knob (pairs
    # with fused_loss_chunk>0 and --parallel sp). Training-only; the
    # KV-cache decode path never remats.
    remat: bool = False
    # Layer-stacked trunk applied via lax.scan: ONE traced/compiled block
    # program instead of num_layers inlined copies — cuts XLA trace/
    # compile time and per-layer scheduling overhead (the r4 trunk-MFU
    # lever; A/B via experiments/gpt2_tune.py --variants scan). Changes
    # the params layout: blocks live under "h_scan" with a leading
    # [num_layers] dim on every leaf (convert with
    # stack_layer_params/unstack_layer_params). Homogeneous blocks only
    # (incompatible with moe_experts). Decode still runs per-layer so the
    # KV-cache/generate path is unchanged.
    scan_layers: bool = False


def _tp_sharded_flash(q, k, v, mesh, causal: bool = True,
                      kv_lengths=None):
    """Per-device flash attention over head-sharded blocks inside a GSPMD
    trace: heads are embarrassingly parallel over ``tp`` (the Megatron
    qkv column-parallel layout shards [B, H, S, D] on H), so a NESTED
    shard_map runs the Mosaic kernel device-locally — the auto-
    partitioner never sees the custom call, and TP training keeps the
    flash kernel instead of falling back to composed S x S attention.
    ``kv_lengths`` ([B] int32, BERT right-padding) shards with the
    batch."""
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.ops.pallas import flash_attention
    from nezha_tpu.parallel._compat import shard_map

    # Batch over dp (matching the enclosing data-parallel sharding — a
    # None there would make jit all-gather the batch and compute every
    # dp shard redundantly), heads over tp.
    bspec = "dp" if "dp" in mesh.axis_names else None
    spec = P(bspec, "tp", None, None)
    if kv_lengths is None:
        f = shard_map(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return f(q, k, v)
    f = shard_map(
        lambda q_, k_, v_, l_: flash_attention(q_, k_, v_, causal=causal,
                                               kv_lengths=l_),
        mesh=mesh, in_specs=(spec, spec, spec, P(bspec)), out_specs=spec)
    return f(q, k, v, kv_lengths)


def _tp_flash_mesh(num_heads: int):
    """The enclosing gspmd mesh when the nested-shard_map flash path is
    usable for ``num_heads`` (TPU backend, a ``tp`` axis that divides the
    heads); None otherwise. ``NEZHA_NO_NESTED_KERNELS=1`` disables it —
    the day-1 escape hatch if Mosaic-inside-shard_map misbehaves on real
    hardware (parity is virtual-mesh-proven; real-ICI compile is not)."""
    import os

    import jax

    from nezha_tpu.parallel.gspmd import auto_partitioner_mesh
    if os.environ.get("NEZHA_NO_NESTED_KERNELS"):
        return None
    mesh = auto_partitioner_mesh()
    if (mesh is not None and "tp" in mesh.axis_names
            and num_heads % mesh.shape["tp"] == 0
            and jax.default_backend() == "tpu"):
        return mesh
    return None


def _resolve_auto_impl(cfg) -> str:
    """THE attn_impl='auto' policy, shared by training and prefill:
    compiled flash on TPU; under a mesh-carrying GSPMD trace, the nested
    shard_map kernel when tp divides the heads; composed XLA otherwise."""
    if _flash_auto_ok():
        return "flash"
    if _tp_flash_mesh(cfg.num_heads) is not None:
        return "flash_shmap"
    return "xla"


def _decode_flash_ok(cfg) -> bool:
    """Whether the single-token decode step takes the flash-decode kernel.

    Same escape-hatch shape as the prefill flash path: an env kill switch
    (``NEZHA_NO_DECODE_KERNEL=1``), an explicit config override
    (``decode_impl="kernel"``/``"xla"``), and otherwise the shared
    ``attn_impl`` resolution — the kernel fires exactly where prefill
    flash would (TPU backend, not under the auto-partitioner), so one
    flag set governs the whole attention surface."""
    import os

    if os.environ.get("NEZHA_NO_DECODE_KERNEL"):
        return False
    if cfg.decode_impl == "kernel":
        return True
    if cfg.decode_impl != "auto":
        return False
    impl = cfg.attn_impl
    if impl == "auto":
        return _flash_auto_ok()
    return impl == "flash"


def _decode_flash_shmap_mesh(cfg):
    """The enclosing auto-partitioner mesh when the flash-DECODE kernel
    can run per-shard under a nested ``shard_map`` (the sharded serve
    engine's path, ops/pallas/decode_attention.py
    ``flash_decode_attention_sharded``); None otherwise. Same gates as
    the prefill ``flash_shmap`` idiom — TPU backend, a ``tp`` axis
    dividing the heads, ``NEZHA_NO_NESTED_KERNELS`` honored — plus the
    decode kernel's own switches (``decode_impl``, the shared
    ``attn_impl`` resolution, ``NEZHA_NO_DECODE_KERNEL``).
    ``decode_impl="kernel"`` honors the force on ANY backend (interpret
    mode off-TPU, the parity-test path — under the partitioner the raw
    Mosaic call is never an option, so the nested variant IS the forced
    kernel). Otherwise, off-TPU the composed masked path simply
    auto-partitions under the mesh."""
    import os

    if os.environ.get("NEZHA_NO_DECODE_KERNEL") \
            or os.environ.get("NEZHA_NO_NESTED_KERNELS"):
        return None
    if cfg.decode_impl == "xla":
        return None
    if cfg.decode_impl == "auto" and cfg.attn_impl not in ("auto",
                                                           "flash"):
        return None
    if cfg.decode_impl == "kernel":
        from nezha_tpu.parallel.gspmd import auto_partitioner_mesh
        mesh = auto_partitioner_mesh()
        if (mesh is not None and "tp" in mesh.axis_names
                and cfg.num_heads % mesh.shape["tp"] == 0):
            return mesh
        return None
    return _tp_flash_mesh(cfg.num_heads)


def _prefill_flash_ok(cfg) -> bool:
    """Whether the paged prefill-chunk branch takes the flash-prefill
    kernel — the same escape-hatch shape as :func:`_decode_flash_ok`:
    an env kill switch (``NEZHA_NO_PREFILL_KERNEL=1``), an explicit
    config override (``prefill_impl="kernel"``/``"xla"``), and
    otherwise the shared ``attn_impl`` resolution, so one flag set
    governs the whole attention surface."""
    import os

    if os.environ.get("NEZHA_NO_PREFILL_KERNEL"):
        return False
    if cfg.prefill_impl == "kernel":
        return True
    if cfg.prefill_impl != "auto":
        return False
    impl = cfg.attn_impl
    if impl == "auto":
        return _flash_auto_ok()
    return impl == "flash"


def _prefill_flash_shmap_mesh(cfg):
    """The enclosing auto-partitioner mesh when the flash-PREFILL
    kernel can run per-shard under a nested ``shard_map`` (the sharded
    serve engine's path, ops/pallas/prefill_attention.py
    ``flash_prefill_attention_sharded``); None otherwise. Same gates
    as :func:`_decode_flash_shmap_mesh` with the prefill knobs
    (``prefill_impl``, ``NEZHA_NO_PREFILL_KERNEL``) swapped in:
    ``prefill_impl="kernel"`` honors the force on ANY backend
    (interpret mode off-TPU — under the partitioner the raw Mosaic
    call is never an option, so the nested variant IS the forced
    kernel)."""
    import os

    if os.environ.get("NEZHA_NO_PREFILL_KERNEL") \
            or os.environ.get("NEZHA_NO_NESTED_KERNELS"):
        return None
    if cfg.prefill_impl == "xla":
        return None
    if cfg.prefill_impl == "auto" and cfg.attn_impl not in ("auto",
                                                            "flash"):
        return None
    if cfg.prefill_impl == "kernel":
        from nezha_tpu.parallel.gspmd import auto_partitioner_mesh
        mesh = auto_partitioner_mesh()
        if (mesh is not None and "tp" in mesh.axis_names
                and cfg.num_heads % mesh.shape["tp"] == 0):
            return mesh
        return None
    return _tp_flash_mesh(cfg.num_heads)


def _flash_auto_ok() -> bool:
    """ONE backend policy for every attn_impl='auto' site (train, prefill,
    BERT): compiled flash on TPU, and never under the GSPMD
    auto-partitioner (jit-with-shardings cannot partition a Mosaic custom
    call; shard_map paths see per-device blocks and are fine)."""
    import jax

    from nezha_tpu.parallel.gspmd import under_auto_partitioner
    return jax.default_backend() == "tpu" and not under_auto_partitioner()


def _quant_decode_write(pool, scales, blk, off, row):
    """One decode token's K (or V) into an INT8 block pool at BLOCK
    granularity: gather each row's target block, dequantize, zero the
    stale positions past the write offset (a freshly-bound block holds
    a previous occupant's int8 garbage — letting it into the absmax
    would inflate the new scale and crush the real entries), insert the
    new row, requantize with a fresh per-(block, head) scale, scatter
    block + scale back. Positions below ``off`` are this row's own
    earlier tokens: they re-round only if the block absmax moved
    (unchanged scale round-trips int8 exactly), which is the bounded
    re-quantization error the ``serve.kv.quant_error`` histogram
    samples. ``pool [N,H,bs,D] int8``, ``scales [N,H] f32``,
    ``blk``/``off [B]``, ``row [B,H,D]``."""
    from nezha_tpu.ops import quant
    bs = pool.shape[2]
    qblk = pool[blk]                                     # [B, H, bs, D]
    deq = qblk.astype(jnp.float32) * scales[blk][:, :, None, None]
    idx = jnp.arange(bs)
    keep = (idx[None, :] < off[:, None])[:, None, :, None]
    sel = (idx[None, :] == off[:, None])[:, None, :, None]
    deq = jnp.where(sel, row.astype(jnp.float32)[:, :, None, :],
                    jnp.where(keep, deq, 0.0))
    qn, sn = quant.quantize_kv_block(deq)
    return pool.at[blk].set(qn), scales.at[blk].set(sn)


def _quant_prefill_write(pool, scales, tab, pos, new, s):
    """One prefill chunk's K (or V) into an INT8 block pool: the chunk
    ``new [b,H,s,D]`` lands at traced offset ``pos`` through the block
    table ``tab [b,M]``. The touched-block window is STATIC
    (``ceil(s/bs)+1`` gathered blocks — ``s`` and ``bs`` are static,
    only ``pos`` is traced); per touched block, positions before the
    chunk keep their dequantized content (earlier chunks / COWed cached
    prefix), chunk positions take the new values, and positions past
    the chunk zero out (previous-occupant garbage must not set the
    scale). Over-covered window rows (the +1 slack when ``pos`` is
    block-aligned) are routed to the scratch block with zero content —
    never to a data block, whose content a duplicate-index scatter
    could otherwise clobber. Returns ``(pool, scales, err)`` with
    ``err`` the max-abs dequant error over the written span — the
    ``serve.kv.quant_error`` sample."""
    from nezha_tpu.ops import quant
    bs = pool.shape[2]
    m = tab.shape[1]
    t = min((s - 1) // bs + 2, m)
    fb = pos // bs
    tbi_raw = fb + jnp.arange(t)                         # [T]
    touched = tbi_raw <= (pos + s - 1) // bs
    blks = jnp.where(touched[None, :],
                     tab[:, jnp.clip(tbi_raw, 0, m - 1)], 0)   # [b, T]
    deq = (pool[blks].astype(jnp.float32)
           * scales[blks][..., None, None])              # [b,T,H,bs,D]
    wpos = tbi_raw[:, None] * bs + jnp.arange(bs)[None, :]     # [T, bs]
    keep = (wpos < pos) & touched[:, None]
    in_chunk = (wpos >= pos) & (wpos < pos + s) & touched[:, None]
    neww = new.astype(jnp.float32)[
        :, :, jnp.clip(wpos - pos, 0, s - 1), :]         # [b,H,T,bs,D]
    neww = jnp.transpose(neww, (0, 2, 1, 3, 4))          # [b,T,H,bs,D]
    deq = jnp.where(in_chunk[None, :, None, :, None], neww,
                    jnp.where(keep[None, :, None, :, None], deq, 0.0))
    qn, sn = quant.quantize_kv_block(deq)
    err = jnp.max(jnp.abs(jnp.where(
        (keep | in_chunk)[None, :, None, :, None],
        quant.sanitize(deq) - qn.astype(jnp.float32)
        * sn[..., None, None], 0.0)))
    return pool.at[blks].set(qn), scales.at[blks].set(sn), err


class Attention(Module):
    def __init__(self, cfg: GPT2Config, policy: Policy):
        h = cfg.hidden_size
        self.cfg = cfg
        self.qkv = nn.Linear(h, 3 * h, kernel_init=init_lib.normal(0.02),
                             policy=policy)
        self.proj = nn.Linear(
            h, h, kernel_init=init_lib.normal(0.02 / (2 * cfg.num_layers) ** 0.5),
            policy=policy)
        self.drop = nn.Dropout(cfg.dropout)

    def apply(self, variables: Variables, x, training: bool = False, rng=None,
              cache=None, pos=None, prefill: bool = False, active=None):
        cfg = self.cfg
        b, s, h = x.shape
        d = h // cfg.num_heads
        states: dict = {}
        qkv = run_child(self.qkv, "qkv", variables, states, x, training=training)
        qkv = qkv.reshape(b, s, 3, cfg.num_heads, d).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]  # each [B, H, S, D]

        if cache is not None and "tables" in cache:
            # PAGED cache (the serve engine's block-paged pool): k/v are
            # block POOLS [N, H, bs, D] and cache["tables"] [B, M] maps
            # this row's position p to pool block tables[p // bs] at
            # offset p % bs. Writes are a scatter through the table;
            # attention either runs the flash-decode kernel directly on
            # the pools (block-table gather operand, per-row length
            # skip preserved) or gathers the row's blocks and takes the
            # same masked path as the dense layout. Non-emitting rows
            # (``active`` False) route their frozen-position pad write
            # to block 0 — the pool's reserved scratch block — so a
            # retired slot can never scribble on a block that was
            # rebound to another request.
            return self._apply_paged(variables, x, q, k, v, cache, pos,
                                     prefill, active, states,
                                     training=training)
        if cache is not None:
            # Incremental decoding: append this chunk's K/V at `pos` in the
            # fixed-size cache and attend causally over everything written
            # so far. Static shapes throughout — `pos` is a traced scalar,
            # so one compiled program serves every decode step. A [B]
            # position VECTOR means per-row positions (the serve engine's
            # slot pool: every row is an independent request at its own
            # depth) — writes become a vmapped per-row update and the
            # causal mask gains a batch dim.
            import jax.lax as lax
            per_row = getattr(pos, "ndim", 0) == 1
            zero = jnp.zeros((), jnp.int32)
            if per_row and s == 1:
                def _row_update(c, new, p):
                    return lax.dynamic_update_slice(c, new, (zero, p, zero))

                k_all = jax.vmap(_row_update)(
                    cache["k"], k.astype(cache["k"].dtype), pos)
                v_all = jax.vmap(_row_update)(
                    cache["v"], v.astype(cache["v"].dtype), pos)
            elif per_row:
                # Speculative verify window: s tokens per row at
                # PER-ROW offsets. A dynamic_update_slice would CLAMP a
                # near-capacity row's window start backwards and
                # overwrite valid prefix K/V, so the write is a
                # per-position scatter with out-of-range (and
                # non-emitting-row) positions routed to the DROP index
                # — rejected draft positions within range just hold
                # garbage until the next window overwrites them (never
                # attended: each row's mask stops at its own depth).
                L_d = cache["k"].shape[2]
                ppos = pos[:, None] + jnp.arange(s)[None, :]   # [B, s]
                if active is not None:
                    ppos = jnp.where(active[:, None], ppos, L_d)
                ppos = jnp.where(ppos < L_d, ppos, L_d)        # OOB: drop
                bidx = jnp.arange(b)[:, None]
                k_all = cache["k"].at[bidx, :, ppos, :].set(
                    k.transpose(0, 2, 1, 3).astype(cache["k"].dtype),
                    mode="drop")
                v_all = cache["v"].at[bidx, :, ppos, :].set(
                    v.transpose(0, 2, 1, 3).astype(cache["v"].dtype),
                    mode="drop")
            else:
                k_all = lax.dynamic_update_slice(
                    cache["k"], k.astype(cache["k"].dtype),
                    (zero, zero, pos, zero))
                v_all = lax.dynamic_update_slice(
                    cache["v"], v.astype(cache["v"].dtype),
                    (zero, zero, pos, zero))
            use_flash_prefill = False
            if prefill and s > 1:
                # Prefill contract (ADVICE r5): ``prefill=True`` promises
                # the chunk IS the whole cache prefix — flash attends
                # within the chunk only, so a nonzero cache position would
                # silently drop attention to the cached prefix. Honor it
                # statically: only a pos known to be 0 at trace time (a
                # Python/numpy int or a concrete array, as generate.py
                # passes) takes the flash path; a traced or nonzero pos
                # falls back to masked attention over the cache, which is
                # correct at any position.
                from jax.core import Tracer as _Tracer
                try:
                    pos_is_zero = (not isinstance(pos, _Tracer)
                                   and int(pos) == 0)
                except TypeError:  # non-scalar / unconvertible pos
                    pos_is_zero = False
                if pos_is_zero:
                    # Nothing precedes the prompt, so attention is exactly
                    # causal flash over the chunk itself — no [B,H,S,L]
                    # score matrix against the padded cache. Same backend
                    # policy as the training path (shared helper).
                    impl = cfg.attn_impl
                    if impl == "auto":
                        impl = _resolve_auto_impl(cfg)
                    # (flash_shmap applies to the training path; prefill
                    # runs outside the gspmd trace, where auto resolves to
                    # plain flash/xla.)
                    use_flash_prefill = impl == "flash"
            use_decode_kernel = (not prefill and s == 1
                                 and _decode_flash_ok(cfg))
            if use_flash_prefill:
                from nezha_tpu.ops.pallas import flash_attention
                # Arbitrary prompt lengths: pad to a lane multiple so the
                # kernel gets real block sizes (a prime S would degrade
                # _pick_block to 1-wide blocks); padded keys are masked
                # via kv_lengths, padded query rows sliced off.
                pad = (-s) % 128
                if pad:
                    pq, pk, pv = (jnp.pad(t, ((0, 0), (0, 0), (0, pad),
                                              (0, 0)))
                                  for t in (q, k, v))
                    lens = jnp.full((b,), s, jnp.int32)
                    out = flash_attention(pq, pk, pv, causal=True,
                                          kv_lengths=lens)[:, :, :s, :]
                else:
                    out = flash_attention(q, k, v, causal=True)
            elif use_decode_kernel:
                # Single-token decode: the flash-decode kernel attends the
                # one query row over the cache prefix [0, pos] with per-row
                # lengths — rows only touch KV blocks below their own
                # depth, and inactive rows (the serve engine's empty slots)
                # skip every block instead of computing masked garbage.
                from nezha_tpu.ops.pallas import flash_decode_attention
                lengths = (pos if per_row
                           else jnp.broadcast_to(pos, (b,))) + 1
                if active is not None:
                    lengths = jnp.where(active, lengths, 0)
                out = flash_decode_attention(q, k_all, v_all, lengths)
            else:
                L = k_all.shape[2]
                if per_row:
                    # [B, 1, S, L]: each row masks against its own depth.
                    abs_q = pos[:, None] + jnp.arange(s)[None, :]
                    attendable = (jnp.arange(L)[None, None, :]
                                  <= abs_q[:, :, None])[:, None, :, :]
                else:
                    abs_q = pos + jnp.arange(s)[:, None]  # absolute positions
                    attendable = jnp.arange(L)[None, :] <= abs_q
                mask = jnp.where(attendable, 0.0, -jnp.inf).astype(jnp.float32)
                out = ops.dot_product_attention(q, k_all.astype(q.dtype),
                                                v_all.astype(q.dtype),
                                                mask=mask)
            states["cache"] = {"k": k_all, "v": v_all}
            out = out.transpose(0, 2, 1, 3).reshape(b, s, h)
            out = run_child(self.proj, "proj", variables, states, out,
                            training=training)
            return out, states

        impl = cfg.attn_impl
        if impl == "auto":
            # Compiled flash wins on TPU at every training shape measured
            # (S=1024: +10% over xla attention-only, +17% end-to-end;
            # S=2048: +25% attention-only) and is the only path at S>=32k
            # where the S x S score matrix exhausts HBM. Interpret-mode
            # flash (non-TPU backends) is never auto-chosen. Under the
            # GSPMD auto-partitioner (which cannot partition a Mosaic
            # custom call) the kernel still runs when the trace carries
            # its mesh and tp divides the heads — via a nested shard_map
            # over the head axis (_tp_sharded_flash); otherwise composed.
            impl = _resolve_auto_impl(cfg)
        if impl == "ring":
            from nezha_tpu.parallel.ring import ring_attention
            out = ring_attention(q, k, v, cfg.sp_axis, causal=True,
                                 use_flash=cfg.sp_use_flash)
        elif impl == "ulysses":
            from nezha_tpu.parallel.sequence_parallel import ulysses_attention
            out = ulysses_attention(q, k, v, cfg.sp_axis, causal=True,
                                    use_flash=cfg.sp_use_flash)
        elif impl == "flash_shmap":
            from nezha_tpu.parallel.gspmd import auto_partitioner_mesh
            mesh = auto_partitioner_mesh()
            if mesh is None or "tp" not in mesh.axis_names \
                    or cfg.num_heads % mesh.shape["tp"]:
                raise ValueError(
                    f"attn_impl='flash_shmap' needs an enclosing gspmd "
                    f"trace carrying a mesh with a 'tp' axis dividing "
                    f"num_heads={cfg.num_heads} "
                    f"(make_gspmd_train_step or "
                    f"auto_partitioner_scope(mesh=...)); got "
                    f"{mesh and dict(mesh.shape)}")
            out = _tp_sharded_flash(q, k, v, mesh, causal=True)
        elif impl == "flash":
            from nezha_tpu.ops.pallas import flash_attention
            out = flash_attention(q, k, v, causal=True)
        else:
            mask = ops.causal_mask(s, s)
            out = ops.dot_product_attention(q, k, v, mask=mask)

        out = out.transpose(0, 2, 1, 3).reshape(b, s, h)
        out = run_child(self.proj, "proj", variables, states, out,
                        training=training)
        out = run_child(self.drop, "drop", variables, states, out,
                        training=training, rng=rng)
        return out, states


    def _apply_paged(self, variables, x, q, k, v, cache, pos, prefill,
                     active, states, *, training):
        """The block-paged cache path (see ``apply``). ``cache`` is
        ``{"k": [N, H, bs, D], "v": [N, H, bs, D], "tables": [B, M]}``;
        the engine guarantees every position this call writes sits in a
        block the row owns exclusively (ref count 1 — prepare_write
        COWed/bound it), and every position it attends below a row's
        length was genuinely written (prefill order / prefix refs)."""
        cfg = self.cfg
        b, s, h = x.shape
        d = h // cfg.num_heads
        kp, vp, tab = cache["k"], cache["v"], cache["tables"]
        quant = "k_scale" in cache   # int8 pool: scales ride the cache
        ks_pool = cache.get("k_scale")
        vs_pool = cache.get("v_scale")
        bs_kv = kp.shape[2]
        m = tab.shape[1]
        L = m * bs_kv
        per_row = getattr(pos, "ndim", 0) == 1
        qerr = None
        out_pf = None   # flash-prefill kernel output, when that path ran
        if per_row and s > 1:
            # Speculative verify window: s tokens per row at PER-ROW
            # offsets, scattered through the block table. Positions
            # past the row's bound frontier gather a scratch (0) table
            # entry by construction, and positions past capacity — or
            # any position of a non-emitting row — are routed to
            # scratch explicitly: the PR 7 pad idiom, so a rejected
            # draft position can never scribble on a rebound block.
            ppos = pos[:, None] + jnp.arange(s)[None, :]       # [B, s]
            route = ppos >= L
            if active is not None:
                route = route | ~active[:, None]
            ppos_c = jnp.minimum(ppos, L - 1)
            bi = jnp.clip(ppos_c // bs_kv, 0, m - 1)
            blk = jnp.take_along_axis(tab, bi, axis=1)         # [B, s]
            blk = jnp.where(route, 0, blk)
            off = jnp.where(route, 0, ppos_c % bs_kv)
            if quant:
                # Sequential per-position block requants (the
                # _quant_decode_write move, once per window position):
                # position j+1's gather sees position j's write, so the
                # window lands exactly as k+1 single-token decodes
                # would — the bounded requant error is the same one
                # serve.kv.quant_error samples at prefill.
                k_pool, v_pool = kp, vp
                for j in range(s):
                    k_pool, ks_pool = _quant_decode_write(
                        k_pool, ks_pool, blk[:, j], off[:, j],
                        k[:, :, j, :])
                    v_pool, vs_pool = _quant_decode_write(
                        v_pool, vs_pool, blk[:, j], off[:, j],
                        v[:, :, j, :])
            else:
                k_pool = kp.at[blk, :, off, :].set(
                    k.transpose(0, 2, 1, 3).astype(kp.dtype))
                v_pool = vp.at[blk, :, off, :].set(
                    v.transpose(0, 2, 1, 3).astype(vp.dtype))
        elif per_row:
            # Decode: one token per row at its own depth. Clamp matches
            # the dense layout's update-slice clamp (a capacity-filled
            # row is done — its pad write may land on its own last
            # position, never past it), and inactive rows write scratch.
            pos_w = jnp.minimum(pos, L - 1)
            bi = jnp.clip(pos_w // bs_kv, 0, m - 1)
            blk = jnp.take_along_axis(tab, bi[:, None], axis=1)[:, 0]
            off = pos_w % bs_kv
            if active is not None:
                blk = jnp.where(active, blk, 0)
                off = jnp.where(active, off, 0)
            if quant:
                # Block-granularity requant (see _quant_decode_write):
                # the row's current block is rewritten whole so its
                # per-(block, head) scale tracks the content absmax.
                k_pool, ks_pool = _quant_decode_write(
                    kp, ks_pool, blk, off, k[:, :, 0, :])
                v_pool, vs_pool = _quant_decode_write(
                    vp, vs_pool, blk, off, v[:, :, 0, :])
            else:
                k_pool = kp.at[blk, :, off, :].set(
                    k[:, :, 0, :].astype(kp.dtype))
                v_pool = vp.at[blk, :, off, :].set(
                    v[:, :, 0, :].astype(vp.dtype))
        else:
            # Prefill chunk at a traced scalar offset. The flash-
            # prefill kernel (prefill_impl resolution, mirroring
            # decode_impl) attends the cached prefix through the block
            # table with the chunk's own K/V folded causally from the
            # fresh operands, ONE program for every start offset — and
            # on int8 pools it fuses the whole block write
            # (_quant_prefill_write's gather→dequant→insert→requant→
            # scatter chain) into its epilogue, stale-position zeroing
            # and the qerr sample included. Composed fallback: scatter
            # the s tokens through the table (pads beyond the prompt
            # land in the row's own bound blocks and are overwritten by
            # decode before any mask attends them — same argument as
            # dense), then masked attention over the gathered pool.
            # Sequence-sharded prefill: a trace-time scope the sharded
            # engine enters while tracing its bucket programs
            # (prefill_mode="sequence"). The sys.modules probe keeps
            # the check free unless the seq-prefill module was ever
            # imported — single-device serving never pays for it.
            import sys as _sys
            _spm = _sys.modules.get(
                "nezha_tpu.serve.sharded.seq_prefill")
            _sp = (_spm.seq_prefill_params()
                   if _spm is not None else None)
            use_pf = False
            pf_mesh = None
            if _sp is None:
                use_pf = _prefill_flash_ok(cfg)
                from nezha_tpu.parallel.gspmd import (
                    under_auto_partitioner)
                if under_auto_partitioner():
                    # Same move as decode below: the raw Mosaic call
                    # can never be handed to the auto-partitioner —
                    # the nested-shard_map variant runs it per head
                    # shard, or the composed path partitions.
                    use_pf = False
                    pf_mesh = _prefill_flash_shmap_mesh(cfg)
            if _sp is not None:
                # The nested shard_map owns BOTH the pool write and
                # the chunk attention; the kernel-vs-composed choice
                # mirrors prefill_impl exactly (the shmap-mesh
                # resolver honors NEZHA_NO_PREFILL_KERNEL and
                # NEZHA_NO_NESTED_KERNELS, and is backend-aware).
                starts = jnp.broadcast_to(
                    jnp.asarray(pos, jnp.int32), (b,))
                use_k = _prefill_flash_shmap_mesh(cfg) is not None
                (out_pf, k_pool, v_pool, ks_n, vs_n,
                 qerr) = _spm.seq_prefill_attention(
                    q, k, v, kp, vp, tab, starts, mesh=_sp.mesh,
                    variant=_sp.variant, use_kernel=use_k,
                    block_scales=((ks_pool, vs_pool) if quant
                                  else None))
                if quant:
                    ks_pool, vs_pool = ks_n, vs_n
            elif use_pf or pf_mesh is not None:
                from nezha_tpu.ops.pallas import (
                    flash_prefill_attention,
                    flash_prefill_attention_sharded,
                )
                starts = jnp.broadcast_to(
                    jnp.asarray(pos, jnp.int32), (b,))
                if quant:
                    if pf_mesh is not None:
                        (out_pf, k_pool, v_pool, ks_pool, vs_pool,
                         qerr) = flash_prefill_attention_sharded(
                            q, k, v, kp, vp, tab, starts, pf_mesh,
                            block_scales=(ks_pool, vs_pool))
                    else:
                        (out_pf, k_pool, v_pool, ks_pool, vs_pool,
                         qerr) = flash_prefill_attention(
                            q, k, v, kp, vp, tab, starts,
                            block_scales=(ks_pool, vs_pool))
                else:
                    # Float pools keep the one-scatter chunk write (it
                    # is already a single cheap XLA op); the kernel
                    # reads only prefix positions plus the fresh
                    # operands, so write and attention commute.
                    ppos = jnp.minimum(pos + jnp.arange(s), L - 1)
                    bi = jnp.clip(ppos // bs_kv, 0, m - 1)
                    blk = tab[:, bi]                           # [b, s]
                    off = (ppos % bs_kv)[None, :]              # [1, s]
                    k_pool = kp.at[blk, :, off, :].set(
                        k.transpose(0, 2, 1, 3).astype(kp.dtype))
                    v_pool = vp.at[blk, :, off, :].set(
                        v.transpose(0, 2, 1, 3).astype(vp.dtype))
                    if pf_mesh is not None:
                        out_pf = flash_prefill_attention_sharded(
                            q, k, v, kp, vp, tab, starts, pf_mesh)
                    else:
                        out_pf = flash_prefill_attention(
                            q, k, v, kp, vp, tab, starts)
            elif quant:
                k_pool, ks_pool, ek = _quant_prefill_write(
                    kp, ks_pool, tab, pos, k, s)
                v_pool, vs_pool, ev = _quant_prefill_write(
                    vp, vs_pool, tab, pos, v, s)
                qerr = jnp.maximum(ek, ev)
            else:
                ppos = jnp.minimum(pos + jnp.arange(s), L - 1)
                bi = jnp.clip(ppos // bs_kv, 0, m - 1)
                blk = tab[:, bi]                               # [b, s]
                off = (ppos % bs_kv)[None, :]                  # [1, s]
                k_pool = kp.at[blk, :, off, :].set(
                    k.transpose(0, 2, 1, 3).astype(kp.dtype))
                v_pool = vp.at[blk, :, off, :].set(
                    v.transpose(0, 2, 1, 3).astype(vp.dtype))
        use_decode_kernel = (not prefill and s == 1 and per_row
                             and _decode_flash_ok(cfg))
        shmap_mesh = None
        if not prefill and s == 1 and per_row:
            from nezha_tpu.parallel.gspmd import under_auto_partitioner
            if under_auto_partitioner():
                # Under the sharded serve engine's auto-partitioner
                # trace the RAW kernel is never an option — a Mosaic
                # custom call cannot be handed to the partitioner,
                # forced decode_impl="kernel" included. The nested-
                # shard_map variant runs it PER SHARD on each device's
                # head slice (block tables replicated, the training-
                # side flash_shmap idiom on the decode path); when the
                # mesh can't host it, the composed path partitions.
                use_decode_kernel = False
                shmap_mesh = _decode_flash_shmap_mesh(cfg)
        if out_pf is not None:
            # The flash-prefill kernel already produced the chunk's
            # attention (and, on int8 pools, the fused write above).
            out = out_pf
        elif use_decode_kernel or shmap_mesh is not None:
            # The kernel takes the POOLS + table directly (block-table
            # gather operand): rows only DMA table entries below their
            # own length, inactive rows skip every block. Int8 pools
            # add the [N, H] scale operands and the kernel dequantizes
            # inside its block loop — the int8 cache never round-trips
            # through a dense bf16 view.
            lengths = pos + 1
            if active is not None:
                lengths = jnp.where(active, lengths, 0)
            if shmap_mesh is not None:
                from nezha_tpu.ops.pallas import (
                    flash_decode_attention_sharded)
                out = flash_decode_attention_sharded(
                    q, k_pool, v_pool, lengths, shmap_mesh,
                    block_tables=tab,
                    block_scales=((ks_pool, vs_pool) if quant
                                  else None))
            else:
                from nezha_tpu.ops.pallas import flash_decode_attention
                out = flash_decode_attention(
                    q, k_pool, v_pool, lengths, block_tables=tab,
                    block_scales=((ks_pool, vs_pool) if quant
                                  else None))
        else:
            # Composed path: gather the rows' blocks into the dense
            # [b, H, L, D] view and run the same masked attention the
            # dense layout uses (unbound table entries gather scratch —
            # always masked, since they sit at/past the row's length).
            # Int8 pools dequantize the gathered blocks with the SAME
            # expression as the kernel's in-loop dequant
            # (ops.quant.dequantize_kv_block), so decode_impl="xla"
            # stays a faithful escape hatch for the quantized cache.
            # Prefill cost note: the serve engine's chunks always reach
            # here (a traced pos can never take the static-pos-0 flash
            # branch — true for the DENSE engine too), and dense chunk
            # attention is already masked-dense over the full L_max
            # rows, so paged adds only the gather copy itself, not a
            # new O(L) attention term. A diagonal-offset flash prefill
            # kernel (the engine docstring's "obvious next kernel")
            # would lift both layouts at once.
            if quant:
                from nezha_tpu.ops.quant import dequantize_kv_block
                k_all = dequantize_kv_block(k_pool[tab], ks_pool[tab],
                                            q.dtype)
                v_all = dequantize_kv_block(v_pool[tab], vs_pool[tab],
                                            q.dtype)
            else:
                k_all, v_all = k_pool[tab], v_pool[tab]
            k_all = k_all.transpose(0, 2, 1, 3, 4).reshape(
                b, cfg.num_heads, L, d)
            v_all = v_all.transpose(0, 2, 1, 3, 4).reshape(
                b, cfg.num_heads, L, d)
            if per_row:
                abs_q = pos[:, None] + jnp.arange(s)[None, :]
                attendable = (jnp.arange(L)[None, None, :]
                              <= abs_q[:, :, None])[:, None, :, :]
            else:
                abs_q = pos + jnp.arange(s)[:, None]
                attendable = jnp.arange(L)[None, :] <= abs_q
            mask = jnp.where(attendable, 0.0, -jnp.inf).astype(jnp.float32)
            out = ops.dot_product_attention(q, k_all.astype(q.dtype),
                                            v_all.astype(q.dtype),
                                            mask=mask)
        new_cache = {"k": k_pool, "v": v_pool, "tables": tab}
        if quant:
            new_cache["k_scale"] = ks_pool
            new_cache["v_scale"] = vs_pool
            if qerr is not None:
                # Per-chunk max-abs dequant error, harvested by the
                # engine's prefill program into serve.kv.quant_error
                # (a per-forward value, not running state — the engine
                # strips it before rebinding caches).
                new_cache["qerr"] = qerr
        states["cache"] = new_cache
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h)
        out = run_child(self.proj, "proj", variables, states, out,
                        training=training)
        return out, states


class MLPBlock(Module):
    def __init__(self, cfg: GPT2Config, policy: Policy):
        h, m = cfg.hidden_size, cfg.hidden_size * cfg.mlp_ratio
        self.fc = nn.Linear(h, m, kernel_init=init_lib.normal(0.02),
                            policy=policy)
        self.proj = nn.Linear(
            m, h, kernel_init=init_lib.normal(0.02 / (2 * cfg.num_layers) ** 0.5),
            policy=policy)
        self.drop = nn.Dropout(cfg.dropout)

    def apply(self, variables: Variables, x, training: bool = False, rng=None):
        states: dict = {}
        x = run_child(self.fc, "fc", variables, states, x, training=training)
        x = ops.gelu(x)
        x = run_child(self.proj, "proj", variables, states, x, training=training)
        x = run_child(self.drop, "drop", variables, states, x,
                      training=training, rng=rng)
        return x, states


class Block(Module):
    def __init__(self, cfg: GPT2Config, policy: Policy, use_moe: bool = False):
        h = cfg.hidden_size
        self.ln_1 = nn.LayerNorm(h, policy=policy, impl=cfg.ln_impl)
        self.attn = Attention(cfg, policy)
        self.ln_2 = nn.LayerNorm(h, policy=policy, impl=cfg.ln_impl)
        if use_moe:
            from nezha_tpu.parallel.expert import MoE, MoEConfig
            self.mlp = MoE(MoEConfig(
                d_model=h, d_ff=h * cfg.mlp_ratio,
                num_experts=cfg.moe_experts, top_k=cfg.moe_top_k),
                policy=policy)
        else:
            self.mlp = MLPBlock(cfg, policy)

    def apply(self, variables: Variables, x, training: bool = False, rng=None,
              cache=None, pos=None, prefill: bool = False, active=None):
        states: dict = {}
        y = run_child(self.ln_1, "ln_1", variables, states, x, training=training)
        y = run_child(self.attn, "attn", variables, states, y,
                      training=training, rng=rng, cache=cache, pos=pos,
                      prefill=prefill, active=active)
        x = x + y
        y = run_child(self.ln_2, "ln_2", variables, states, x, training=training)
        y = run_child(self.mlp, "mlp", variables, states, y,
                      training=training, rng=rng)
        return x + y, states


class ScannedBlocks(Module):
    """``num_layers`` homogeneous :class:`Block`s with layer-stacked
    parameters, applied via ``lax.scan``.

    Every param leaf carries a leading ``[num_layers]`` dim; the scan body
    slices one layer per iteration, so XLA compiles ONE block program
    (reference inlines per-layer graph nodes — SURVEY.md §1; on TPU the
    unrolled trace costs compile time and inter-layer scheduling, which is
    what this removes). Per-layer dropout RNGs are pre-split outside the
    scan with the SAME ``h{i}`` derivation as the unrolled trunk, so the
    two layouts are bit-identical in expectation and in tests.
    """

    _init_with_parent_rng = True  # layer keys derive from GPT2's rng

    def __init__(self, cfg: GPT2Config, policy: Policy):
        self.cfg = cfg
        self.policy = policy
        # Template holding the single-block structure; its params are
        # never used directly (init stacks per-layer inits instead).
        self.block = Block(cfg, policy)

    def init(self, rng: jax.Array) -> Variables:
        from nezha_tpu.nn.module import scan_stack_init
        return scan_stack_init(self.block, rng, self.cfg.num_layers, "h")

    def apply(self, variables: Variables, x, training: bool = False,
              rng=None, pos=None):
        from nezha_tpu.nn.module import scan_stack_apply
        x = scan_stack_apply(self.block, variables["params"], x,
                             self.cfg.num_layers, "h", rng=rng,
                             remat=self.cfg.remat and training,
                             training=training, pos=pos)
        return x, {}


def stack_layer_params(params: dict, num_layers: int) -> dict:
    """Unrolled GPT-2 params (``h0`` .. ``h{L-1}``) -> scan layout
    (``h_scan`` with a leading layer dim). Non-trunk entries pass through."""
    from nezha_tpu.nn.module import stack_prefixed_params
    return stack_prefixed_params(params, "h", num_layers, "h_scan")


def unstack_layer_params(params: dict, num_layers: int) -> dict:
    """Scan-layout GPT-2 params -> unrolled ``h{i}`` layout (checkpoint/HF
    interchange, tensor-parallel rule tables)."""
    from nezha_tpu.nn.module import unstack_prefixed_params
    return unstack_prefixed_params(params, "h", num_layers, "h_scan")


class GPT2(Module):
    """Returns LM logits [B, S, vocab]; weight-tied head.

    ``batch`` may be {"tokens": [B, S+1]} (inputs are tokens[:, :-1] — the
    LM-loss convention used by `lm_loss`) or a raw [B, S] int array.
    """

    def __init__(self, cfg: GPT2Config = GPT2Config(),
                 policy: Policy = DEFAULT_POLICY):
        self.cfg = cfg
        self.policy = policy
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size, policy=policy)
        self.wpe = nn.Embedding(cfg.max_positions, cfg.hidden_size,
                                embedding_init=init_lib.normal(0.01),
                                policy=policy)
        self.drop = nn.Dropout(cfg.dropout)
        if cfg.scan_layers:
            if cfg.moe_experts:
                raise ValueError(
                    "scan_layers requires homogeneous blocks; "
                    "incompatible with moe_experts")
            self.h_scan = ScannedBlocks(cfg, policy)
            self.h = []
        else:
            self.h = [Block(cfg, policy,
                            use_moe=bool(cfg.moe_experts)
                            and i % cfg.moe_every == cfg.moe_every - 1)
                      for i in range(cfg.num_layers)]
        self.ln_f = nn.LayerNorm(cfg.hidden_size, policy=policy,
                          impl=cfg.ln_impl)

    def apply(self, variables: Variables, batch, training: bool = False,
              rng=None, cache=None, pos=None, prefill: bool = False,
              active=None):
        # ``active`` ([B] bool, decode-with-cache only) marks rows whose
        # output is consumed. For single-token serving steps that is the
        # engine's occupancy mask; inside a decode-horizon scan it is the
        # per-scan-step ``active ∧ ¬done ∧ ok`` emit mask, so rows that
        # hit EOS / budget / a NaN freeze mid-block stop doing attention
        # work exactly like empty slots. It is advisory: the
        # flash-decode kernel skips ALL work for non-emitting rows
        # (length 0); the composed path ignores it (garbage rows are
        # masked by the engine's ``where(emit, ...)`` either way).
        if isinstance(batch, dict):
            tokens = batch["tokens"][:, :-1]
        else:
            tokens = batch
        states: dict = {}
        s = tokens.shape[1]
        if s > self.cfg.max_positions:
            # Without this, the position-embedding gather silently clamps.
            raise ValueError(
                f"sequence length {s} exceeds max_positions "
                f"{self.cfg.max_positions}")
        # ``pos`` without a cache = a global position offset: the sequence-
        # parallel train step passes each shard's offset so position
        # embeddings (and ring attention's causal mask) see global positions.
        # A [B] pos vector (serve decode) offsets each row independently.
        offset = 0 if pos is None else pos
        if getattr(pos, "ndim", 0) == 1:
            positions = pos[:, None] + jnp.arange(s)[None, :]
        else:
            positions = offset + jnp.arange(s)[None, :]
        x = run_child(self.wte, "wte", variables, states, tokens,
                      training=training)
        x = x + run_child(self.wpe, "wpe", variables, states, positions,
                          training=training)
        x = run_child(self.drop, "drop", variables, states, x,
                      training=training, rng=rng)
        if self.cfg.scan_layers:
            if cache is None:
                # rng passed RAW (not via run_child): ScannedBlocks does
                # the per-layer ``h{i}`` derivation itself so dropout keys
                # match the unrolled trunk exactly.
                x, _ = self.h_scan.apply(
                    child_vars(variables, "h_scan"), x,
                    training=training, rng=rng, pos=pos)
            else:
                # Decode: per-layer slices of the stacked params, states
                # emitted under the unrolled ``h{i}`` names so the
                # generate/KV-cache plumbing is layout-agnostic.
                stacked = child_vars(variables, "h_scan")["params"]
                for i in range(self.cfg.num_layers):
                    lvars = {"params": jax.tree_util.tree_map(
                        lambda p, i=i: p[i], stacked), "state": {}}
                    x, st = self.h_scan.block.apply(
                        lvars, x, training=training,
                        rng=child_rng(rng, f"h{i}"), cache=cache[i],
                        pos=pos, prefill=prefill, active=active)
                    if st:
                        states[f"h{i}"] = st
        # (With scan_layers, self.h is empty — the loop below is a no-op
        # and the shared ln_f/aux/head tail runs for both layouts.)
        remat = self.cfg.remat and training and cache is None
        for i, block in enumerate(self.h):
            if remat:
                # Save only each block's input; recompute its internals in
                # backward. rng/pos ride through as traced args so dropout
                # keys replay identically in the recompute.
                name = f"h{i}"

                def block_fn(bvars, xx, block=block):
                    return block.apply(bvars, xx, training=True,
                                       rng=child_rng(rng, name), pos=pos)

                x, st = jax.checkpoint(block_fn)(
                    child_vars(variables, name), x)
                if st:
                    states[name] = st
            else:
                x = run_child(block, f"h{i}", variables, states, x,
                              training=training, rng=rng,
                              cache=None if cache is None else cache[i],
                              pos=pos, prefill=prefill, active=active)
        x = run_child(self.ln_f, "ln_f", variables, states, x,
                      training=training)
        # MoE blocks report their load-balance losses through child state;
        # harvest them OUT of the state tree (they're per-forward values,
        # not running state — leaving them in would change the TrainState
        # pytree structure between steps) and surface the weighted sum so
        # lm_loss can add it to the objective.
        aux = None
        if self.cfg.moe_experts and cache is None:
            terms = []
            for i in range(self.cfg.num_layers):
                blk = states.get(f"h{i}")
                if blk and "aux_loss" in blk.get("mlp", {}):
                    mlp_state = dict(blk["mlp"])
                    terms.append(mlp_state.pop("aux_loss"))
                    if mlp_state:
                        blk["mlp"] = mlp_state
                    else:
                        del blk["mlp"]
                    if not blk:
                        del states[f"h{i}"]
            if terms:
                aux = self.cfg.moe_aux_weight * sum(terms)
        if self.cfg.fused_loss_chunk and cache is None:
            # Defer the LM head to the loss: hand back the final hidden
            # states + the tied table so chunked_lm_cross_entropy computes
            # logits blockwise (grads flow to wte through this dict; "chunk"
            # is a static python int — it never crosses a jit boundary).
            wte = child_vars(variables, "wte")["params"]["embedding"]
            out = {"hidden": x, "wte": wte,
                   "chunk": self.cfg.fused_loss_chunk}
            if aux is not None:
                out["aux_loss"] = aux
            return out, states
        logits = self.wte.attend(child_vars(variables, "wte"), x)
        logits = jnp.asarray(logits, jnp.float32)
        if aux is not None:
            return {"logits": logits, "aux_loss": aux}, states
        return logits, states


def gpt2_124m(policy: Policy | None = None, **overrides) -> GPT2:
    cfg = GPT2Config(**overrides)
    return GPT2(cfg, policy=policy or bf16_policy())


def lm_loss(out, batch):
    """Next-token CE over {"tokens": [B, S+1]} batches.

    ``out`` is either dense logits or the fused-head dict (see
    ``GPT2Config.fused_loss_chunk``)."""
    targets = batch["tokens"][:, 1:]
    from nezha_tpu.ops.losses import lm_objective
    return lm_objective(out, targets)
