"""Model zoo — the five benchmark workloads of BASELINE.json (SURVEY.md §2):
MLP, ResNet-50, Wide-ResNet-101, GPT-2 124M, BERT-base."""

from nezha_tpu.models.mlp import MLP

__all__ = ["MLP"]


_LAZY = {
    "ResNet": "resnet", "resnet50": "resnet", "wide_resnet101": "resnet",
    "GPT2": "gpt2", "GPT2Config": "gpt2", "gpt2_124m": "gpt2",
    "Bert": "bert", "BertConfig": "bert", "bert_base": "bert",
    "generate": "generate", "init_cache": "generate",
    "gpt2_from_hf": "convert", "bert_from_hf": "convert",
    "gpt2_params_from_hf": "convert", "gpt2_params_to_hf": "convert",
    "bert_params_from_hf": "convert",
}


def __getattr__(name):
    # Lazy imports keep `import nezha_tpu` fast; heavy models load on demand.
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f"nezha_tpu.models.{_LAZY[name]}")
        return getattr(mod, name)
    raise AttributeError(name)
