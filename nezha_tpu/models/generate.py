"""Autoregressive decoding for GPT-2 with a KV cache.

One compiled prefill (whole prompt writes layer caches) + one compiled
decode step reused for every generated token (`lax.scan`, static shapes,
traced position scalar) — the XLA-friendly decode loop: no per-token
recompilation, no growing shapes, cache updates via dynamic_update_slice.
Sampling: greedy, temperature, top-k, and top-p (nucleus).

The scanned step's single-token attention takes the same flash-decode
kernel path as the serving engine (models/gpt2.py routes ``s == 1``
cache attention through ``ops.pallas.flash_decode_attention`` under the
``attn_impl="auto"`` / ``GPT2Config.decode_impl`` resolution), so
training-side eval sampling shares the serving hot-path win; the
composed masked path remains the off-TPU / escape-hatch fallback and is
bit-compatible for greedy decoding (tests pin the parity).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu.models.gpt2 import GPT2


def init_cache(model: GPT2, batch_size: int, max_len: int,
               dtype=jnp.bfloat16) -> list:
    """Fixed-size per-layer K/V buffers: ``[B, H, max_len, D]`` each."""
    cfg = model.cfg
    d = cfg.hidden_size // cfg.num_heads
    shape = (batch_size, cfg.num_heads, max_len, d)
    return [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
            for _ in range(cfg.num_layers)]


def _caches_from_states(model: GPT2, states: dict, prev: list) -> list:
    return [states.get(f"h{i}", {}).get("attn", {}).get("cache", prev[i])
            for i in range(model.cfg.num_layers)]


def _sample(logits, rng, temperature: float, top_k: Optional[int],
            top_p: Optional[float] = None):
    """logits [B, V] -> token ids [B]. top-k truncation applies before
    top-p nucleus filtering (HF convention when both are set)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None:
        # Clamp to [1, vocab]: lax.top_k rejects k < 1 and k > axis size
        # with an opaque error, and callers (CLI, serving) may hand
        # through user-supplied values. top_k is static, so this is a
        # trace-time Python clamp — no runtime cost.
        top_k = max(1, min(int(top_k), logits.shape[-1]))
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        # Nucleus: keep the smallest prefix of descending-prob tokens whose
        # mass reaches top_p. The explicit rank==0 term keeps the top token
        # even at top_p <= 0 (exclusive-cumsum alone would empty the set
        # there and categorical over all--inf rows silently emits id 0) —
        # top_p -> 0 degrades to argmax, never to an empty set.
        sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
        rank = lax.broadcasted_iota(jnp.int32, sorted_logits.shape,
                                    sorted_logits.ndim - 1)
        keep = (exclusive_cum < top_p) | (rank == 0)
        threshold = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


# The jitted programs are built once per (model, sampling config) and
# cached: jax.jit keys on the function object, so closures created inside
# generate() would retrace and recompile on every call. Models hash by
# identity, which is exactly the lifetime of their compiled programs.
@functools.lru_cache(maxsize=64)
def _prefill_fn(model: GPT2):
    @jax.jit
    def prefill(variables, prompt, cache):
        # pos as a STATIC Python 0 (not jnp.int32(0), which traces to a
        # Tracer under jit): Attention.apply's flash-prefill guard only
        # fires when the cache position is statically known to be zero.
        logits, states = model.apply(variables, prompt, training=False,
                                     cache=cache, pos=0,
                                     prefill=True)
        return logits[:, -1, :], _caches_from_states(model, states, cache)

    return prefill


@functools.lru_cache(maxsize=64)
def _decode_fn(model: GPT2, temperature: float, top_k: Optional[int],
               top_p: Optional[float], max_new_tokens: int,
               eos_id: Optional[int] = None,
               pad_id: Optional[int] = None):
    # EOS early-stop keeps static shapes: a finished row keeps decoding
    # (its cache position advances over the pads it feeds itself) but its
    # SAMPLED tokens are masked to pad_id — so the program is the same
    # two compiled pieces whether rows finish early or not.
    pad = eos_id if pad_id is None else pad_id

    def mask_done(tok, done):
        if eos_id is None:
            return tok, done
        tok = jnp.where(done, jnp.int32(pad), tok)
        return tok, done | (tok == eos_id)

    @jax.jit
    def decode(variables, last_logits, cache, pos0, rng):
        def step(carry, _):
            logits, cache, pos, rng, done = carry
            rng, sub = jax.random.split(rng)
            tok = _sample(logits, sub, temperature, top_k, top_p)
            tok, done = mask_done(tok, done)
            out, states = model.apply(variables, tok[:, None],
                                      training=False, cache=cache, pos=pos)
            new_cache = _caches_from_states(model, states, cache)
            return (out[:, -1, :], new_cache, pos + 1, rng, done), tok

        # The last sampled token needs no forward pass (nothing consumes
        # its logits), so scan N-1 steps and sample the final token from
        # the carried logits — N-1 forwards for N tokens.
        done0 = jnp.zeros(last_logits.shape[:1], bool)
        init = (last_logits, cache, pos0, rng, done0)
        (logits, _, _, rng, done), tokens = lax.scan(
            step, init, None, length=max_new_tokens - 1)
        _, sub = jax.random.split(rng)
        final = _sample(logits, sub, temperature, top_k, top_p)
        final, _ = mask_done(final, done)
        tokens = jnp.concatenate([tokens, final[None, :]], axis=0)
        return tokens.T  # [steps, B] -> [B, steps]

    return decode


def generate(model: GPT2, variables: dict, prompt: jax.Array,
             max_new_tokens: int, temperature: float = 0.0,
             top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             rng: Optional[jax.Array] = None,
             cache_dtype=jnp.bfloat16,
             eos_id: Optional[int] = None,
             pad_id: Optional[int] = None) -> jax.Array:
    """Generate ``[B, prompt_len + max_new_tokens]`` token ids.

    ``temperature=0`` is greedy decoding; otherwise categorical sampling
    (optionally top-k truncated and/or top-p nucleus-filtered). Compiles exactly two programs per
    (model, sampling config, shapes) — prefill and the scanned
    single-token step — reused across calls.

    ``eos_id``: rows that emit it stop — their cache position keeps
    advancing (static shapes) but every subsequent sampled token is
    masked to ``pad_id`` (defaults to ``eos_id``), so output rows read
    ``... eos pad pad``.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, s = prompt.shape
    max_len = s + max_new_tokens
    if max_len > model.cfg.max_positions:
        raise ValueError(
            f"prompt+new = {max_len} exceeds max_positions "
            f"{model.cfg.max_positions}")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    cache = init_cache(model, b, max_len, cache_dtype)
    last_logits, cache = _prefill_fn(model)(variables, prompt, cache)
    new_tokens = _decode_fn(model, temperature, top_k, top_p,
                            max_new_tokens, eos_id, pad_id)(
        variables, last_logits, cache, jnp.int32(s), rng)
    return jnp.concatenate([prompt, new_tokens], axis=1)
