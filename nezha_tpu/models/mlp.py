"""3-layer MLP — benchmark config 1 (SURVEY.md §0: "3-layer MLP on MNIST,
single-process CPU path"). Smallest end-to-end slice of the framework."""

from __future__ import annotations

from typing import Sequence

import jax

from nezha_tpu import nn, ops
from nezha_tpu.nn.module import Module, Variables, child_rng, child_vars, make_variables
from nezha_tpu.tensor.policy import DEFAULT_POLICY, Policy


class MLP(Module):
    def __init__(self, in_features: int = 784,
                 hidden: Sequence[int] = (256, 256),
                 num_classes: int = 10,
                 policy: Policy = DEFAULT_POLICY):
        dims = [in_features, *hidden]
        self.layers = [
            nn.Linear(dims[i], dims[i + 1], policy=policy, name=f"fc{i}")
            for i in range(len(dims) - 1)
        ]
        self.head = nn.Linear(dims[-1], num_classes, policy=policy, name="head")
        self.policy = policy

    def init(self, rng: jax.Array) -> Variables:
        params = {}
        for i, layer in enumerate(self.layers):
            params[f"fc{i}"] = layer.init(child_rng(rng, f"fc{i}"))["params"]
        params["head"] = self.head.init(child_rng(rng, "head"))["params"]
        return make_variables(params)

    def apply(self, variables: Variables, batch, training: bool = False, rng=None):
        del rng
        x = batch["image"] if isinstance(batch, dict) else batch
        x = x.reshape(x.shape[0], -1)
        for i, layer in enumerate(self.layers):
            x, _ = layer.apply(child_vars(variables, f"fc{i}"), x, training=training)
            x = ops.relu(x)
        x, _ = self.head.apply(child_vars(variables, "head"), x, training=training)
        return x, {}
