"""The fault-point call-site API — the obs-registry shape applied to
failure: production code registers NAMED points, a process-wide plan
decides what (if anything) happens there, and with no plan installed
every site is a branch-only no-op (one attribute load + None check —
cheap enough for the serving hot path, same contract as
``obs.counter().inc()`` while telemetry is disabled).

Two site kinds:

- a control-flow site calls the point function with a name and may get a
  typed :class:`~nezha_tpu.faults.plan.InjectedFault` raised or a delay
  slept at it;
- a data site calls the corrupt function with a name and a float tensor
  and gets back either the same tensor (no rule fired) or a copy with a
  seeded-chosen row (or the whole tensor) poisoned to nan/inf/zero —
  how the NaN-logit-burst failure mode is manufactured on demand.

Every injection counts into the ``faults.injected_total`` obs counter
(schema-pinned for serving runs), so a chaos run's artifact records how
much chaos it actually received.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional, Sequence, Union

import numpy as np

from nezha_tpu import obs
from nezha_tpu.faults.plan import (CORRUPT_ACTIONS, FaultPlan, FaultRule,
                                   InjectedFault)

ENV_PLAN = "NEZHA_FAULT_PLAN"
ENV_SEED = "NEZHA_FAULT_SEED"


class _State:
    __slots__ = ("plan",)

    def __init__(self):
        self.plan: Optional[FaultPlan] = None


_state = _State()


# ------------------------------------------------------------- lifecycle
def enabled() -> bool:
    return _state.plan is not None


def active() -> Optional[FaultPlan]:
    return _state.plan


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Make ``plan`` the process-wide plan (None = disable). Returns it."""
    _state.plan = plan
    return plan


def clear() -> None:
    _state.plan = None


def install_from_env(env=None) -> Optional[FaultPlan]:
    """Install a plan from ``NEZHA_FAULT_PLAN`` (seed:
    ``NEZHA_FAULT_SEED``, default 0). With the variable unset or empty
    the current plan is left untouched and None is returned — callers
    (the CLIs) can't accidentally clear a programmatic plan."""
    env = os.environ if env is None else env
    spec = env.get(ENV_PLAN)
    if not spec:
        return None
    return install(FaultPlan.parse(spec, seed=int(env.get(ENV_SEED, "0"))))


# ------------------------------------------------------------ call sites
def point(name: str) -> None:
    """A control-flow fault point. No-op without a plan; with one, an
    ``error`` rule raises :class:`InjectedFault` here and a ``delay``
    rule sleeps (corruption rules are ignored — there is no tensor)."""
    plan = _state.plan
    if plan is None:
        return
    rule = plan.hit(name)
    if rule is not None and rule.action not in CORRUPT_ACTIONS:
        _execute(name, rule, plan)


def corrupt(name: str, x, rows: Union[None, Sequence[int],
                                      Callable[[], Sequence[int]]] = None):
    """A data fault point: returns ``x`` untouched unless a rule fires.

    Corruption rules (``nan``/``inf``/``zero``) poison a COPY of ``x`` —
    one seeded-chosen row from ``rows`` when given (``rows`` may be a
    callable, evaluated only on injection, so hot paths don't pay for
    the candidate list), else the whole tensor. ``error``/``delay``
    rules behave as at :func:`point`. Host-side only: call it on the
    arrays a program returned, never under a trace.
    """
    plan = _state.plan
    if plan is None:
        return x
    rule = plan.hit(name)
    if rule is None:
        return x
    if rule.action not in CORRUPT_ACTIONS:
        _execute(name, rule, plan)
        return x
    if callable(rows):
        rows = rows()
    if rows is not None:
        rows = list(rows)
        if not rows:          # nothing eligible (e.g. no active slots)
            return x
    plan.record_injection(name)
    obs.counter("faults.injected_total").inc()
    poison = {"nan": np.nan, "inf": np.inf, "zero": 0.0}[rule.action]
    arr = np.array(x, copy=True)
    if rows is None:
        arr[...] = poison
    else:
        arr[rows[plan.choose(len(rows))]] = poison
    if isinstance(x, np.ndarray):
        return arr
    import jax.numpy as jnp
    return jnp.asarray(arr)


def _execute(name: str, rule: FaultRule, plan: FaultPlan) -> None:
    plan.record_injection(name)
    obs.counter("faults.injected_total").inc()
    if rule.action == "delay":
        time.sleep(rule.delay_s)
        return
    raise InjectedFault(
        f"injected fault at point {name!r} "
        f"(hit {plan.hit_counts.get(name, 0)}, rule {rule.action!r})")
