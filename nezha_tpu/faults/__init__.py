"""Deterministic fault injection — the failure-mode mirror of
``nezha_tpu.obs``.

The obs registry made every subsystem permanently *observable* at
near-zero disabled cost; this package makes the same subsystems
permanently *breakable* on demand: named fault points stay in the
production code (serving admission/decode, checkpoint save, coordinator
dial — tools/check_fault_points.py pins the registry and requires each
name documented in the RUNBOOK and covered by a test), and a seeded
:class:`FaultPlan` — built in code or parsed from ``NEZHA_FAULT_PLAN`` —
decides which hits raise a typed :class:`InjectedFault`, sleep a delay,
or poison a tensor with nan/inf/zero. With no plan installed every site
is a branch-only no-op.

This is what lets the resilience claims be TESTED instead of asserted:
the tier-1 chaos suite (tests/test_faults.py) drives the serving loop,
checkpoint save, and coordinator join through seeded failure schedules
and proves isolation (errors retire one request, never the batch),
recovery (step retry, checkpoint fallback, join backoff), and zero slot
leaks. ``benchmarks/serving.py --fault-rate`` runs the same machinery
probabilistically to price the overhead.
"""

from nezha_tpu.faults.injector import (
    ENV_PLAN,
    ENV_SEED,
    active,
    clear,
    corrupt,
    enabled,
    install,
    install_from_env,
    point,
)
from nezha_tpu.faults.plan import (
    ACTIONS,
    CORRUPT_ACTIONS,
    FaultPlan,
    FaultRule,
    InjectedFault,
    parse_rule,
)

__all__ = [
    "FaultPlan", "FaultRule", "InjectedFault", "parse_rule",
    "ACTIONS", "CORRUPT_ACTIONS", "ENV_PLAN", "ENV_SEED",
    "point", "corrupt", "install", "install_from_env", "clear",
    "active", "enabled",
]
