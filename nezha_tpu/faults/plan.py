"""Fault plans: what to inject, where, and when.

A :class:`FaultPlan` is a seeded, deterministic schedule of failures over
named fault points (the ``serve.prefill`` / ``checkpoint.save`` call
sites registered across the tree — tools/check_fault_points.py pins the
registry). Plans come from code (tests build them directly) or from the
environment (``NEZHA_FAULT_PLAN`` — the operator's chaos knob, parsed by
:meth:`FaultPlan.parse`). The compact rule grammar::

    plan  := rule [ ";" rule ]...
    rule  := point ":" action [ "@" N ] [ "x" M | "x*" ] [ "%" P ]
    action := "error" | "delay=SECONDS" | "nan" | "inf" | "zero"

``@N`` arms the rule on the Nth hit of the point (1-based, default 1);
``xM`` keeps it firing for M consecutive hits (default 1, ``x*`` =
every hit from N on); ``%P`` instead fires each hit independently with
probability P drawn from the plan's seeded RNG (exclusive with ``@``/
``x`` — the probabilistic form used by ``benchmarks/serving.py
--fault-rate``). ``error``/``delay`` apply at any point; the corruption
actions (``nan``/``inf``/``zero``) only take effect at
``faults.corrupt(...)`` sites, which pass the tensor to poison.

Hit counting and the RNG live behind one lock, so concurrently-driven
points (HTTP handler threads over one scheduler) see a consistent
schedule. Determinism contract: same plan string + same seed + same
sequence of point hits = same injections.
"""

from __future__ import annotations

import dataclasses
import math
import random
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple


class InjectedFault(RuntimeError):
    """The typed error a fault plan raises at an ``error`` rule — distinct
    from every organic exception so tests (and operators reading logs)
    can tell injected failures from real ones."""


ACTIONS = ("error", "delay", "nan", "inf", "zero")
CORRUPT_ACTIONS = ("nan", "inf", "zero")

_RULE_RE = re.compile(
    r"^(?P<point>[A-Za-z0-9_]+(?:\.[A-Za-z0-9_]+)*)"
    r":(?P<action>[a-z]+)"
    r"(?:=(?P<arg>[0-9.eE+-]+))?"
    r"(?:@(?P<at>\d+))?"
    r"(?:x(?P<times>\d+|\*))?"
    r"(?:%(?P<p>[0-9.eE+-]+))?$")


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One injection rule. ``at``/``times`` select hits positionally
    (fire on hits ``[at, at + times)``); ``p`` selects probabilistically
    instead (per-hit coin flip from the plan's seeded RNG)."""

    point: str
    action: str
    at: int = 1
    times: float = 1          # math.inf = every hit from `at` on
    p: Optional[float] = None
    delay_s: float = 0.0

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"fault action must be one of {ACTIONS}, got "
                f"{self.action!r}")
        if self.action == "delay" and not self.delay_s > 0:
            raise ValueError("delay rules need delay=SECONDS > 0")
        if self.at < 1:
            raise ValueError(f"@N must be >= 1 (1-based hits), got {self.at}")
        if self.times < 1:
            raise ValueError(f"xM must be >= 1, got {self.times}")
        if self.p is not None:
            if not 0.0 < self.p <= 1.0:
                raise ValueError(f"%P must be in (0, 1], got {self.p}")
            if self.at != 1 or self.times != 1:
                raise ValueError(
                    "%P (probabilistic) is exclusive with @N/xM "
                    "(positional) — pick one firing mode per rule")


def parse_rule(token: str) -> FaultRule:
    """One ``point:action[@N][xM][%P]`` token -> :class:`FaultRule`."""
    m = _RULE_RE.match(token.strip())
    if m is None:
        raise ValueError(
            f"bad fault rule {token!r}: expected "
            f"point:action[=arg][@N][xM|x*][%P] with action one of "
            f"{ACTIONS}")
    action, arg = m.group("action"), m.group("arg")
    if arg is not None and action != "delay":
        raise ValueError(
            f"bad fault rule {token!r}: only delay takes =SECONDS")
    times: float = 1
    if m.group("times") is not None:
        times = math.inf if m.group("times") == "*" \
            else int(m.group("times"))
    return FaultRule(
        point=m.group("point"), action=action,
        at=int(m.group("at")) if m.group("at") else 1,
        times=times,
        p=float(m.group("p")) if m.group("p") else None,
        delay_s=float(arg) if arg else 0.0)


class FaultPlan:
    """A set of rules + seeded RNG + per-point hit accounting.

    ``hit(point)`` is the injector's single entry: it counts the hit and
    returns the first rule that fires on it (or None). ``injected_counts``
    / ``hit_counts`` expose what actually happened — benchmarks record
    them alongside the latency percentiles.
    """

    def __init__(self, rules: Sequence[FaultRule], seed: int = 0):
        self.rules: Tuple[FaultRule, ...] = tuple(rules)
        self.seed = seed
        self._by_point: Dict[str, List[FaultRule]] = {}
        for r in self.rules:
            self._by_point.setdefault(r.point, []).append(r)
        self._rng = random.Random(seed)
        self._hits: Dict[str, int] = {}
        self._injected: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        """Parse the ``;``-separated rule grammar (module docstring)."""
        rules = [parse_rule(tok) for tok in spec.split(";") if tok.strip()]
        if not rules:
            raise ValueError(f"fault plan {spec!r} contains no rules")
        return cls(rules, seed=seed)

    # ------------------------------------------------------------ firing
    def hit(self, point: str) -> Optional[FaultRule]:
        """Count one hit of ``point``; -> the rule that fires on it, if
        any (first match wins; positional windows and coin flips are
        evaluated under the plan lock). Firing is only SELECTION — the
        injector calls :meth:`record_injection` once it actually does
        something, so ``injected_counts`` never claims chaos that a
        call site discarded (e.g. a corruption rule at a control-flow
        point, or a corrupt site with no eligible rows)."""
        with self._lock:
            n = self._hits[point] = self._hits.get(point, 0) + 1
            for rule in self._by_point.get(point, ()):
                if rule.p is not None:
                    fired = self._rng.random() < rule.p
                else:
                    fired = rule.at <= n < rule.at + rule.times
                if fired:
                    return rule
        return None

    def record_injection(self, point: str) -> None:
        """Account one injection that actually HAPPENED at ``point``
        (raise/delay executed, tensor poisoned)."""
        with self._lock:
            self._injected[point] = self._injected.get(point, 0) + 1

    def choose(self, n: int) -> int:
        """Seeded pick in ``[0, n)`` — corruption sites use it to select
        the victim row, so "which request gets the NaN burst" is part of
        the deterministic schedule."""
        with self._lock:
            return self._rng.randrange(n)

    # --------------------------------------------------------- accounting
    @property
    def hit_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._hits)

    @property
    def injected_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._injected)

    @property
    def num_injected(self) -> int:
        with self._lock:
            return sum(self._injected.values())

    def __repr__(self) -> str:
        return (f"FaultPlan(rules={len(self.rules)}, seed={self.seed}, "
                f"injected={self.num_injected})")
