"""The continuous-batching engine: a frozen set of programs, reused forever.

Steady-state serving is exactly ``1 + len(prefill_buckets)`` XLA
programs regardless of request mix — the property that keeps TPU serving
latency flat:

- **prefill** — one compiled program per PREFILL BUCKET (static prompt
  pad widths, default powers of two up to ``max_prefill_len``). A
  prompt's tokens are padded to the smallest bucket that fits, the
  slot's pooled cache rows are sliced out (``read_slot``), the chunk
  runs through the model at its TRACED position offset via the masked
  attention path (which attends everything previously written to the
  slot), and the updated rows are written back (``write_slot``).
  Prompts longer than ``max_prefill_len`` are no longer rejected: they
  prefill in successive chunks — full ``max_prefill_len``-wide chunks,
  then a bucketed tail — reusing the same bucket programs at advancing
  offsets, so CHUNKING ADDS NO PROGRAMS. Bucket pads beyond the prompt
  write garbage K/V that is never attended (the masks stop at the
  written prefix, and decode overwrites pad positions before its mask
  reaches them). The traced offset is the trade the chunk contract
  buys: a traced ``pos`` cannot take the static-pos-0 flash-prefill
  path, so chunk attention is masked-dense over the slot's ``L_max``
  rows — paid once per request, versus the per-token decode win; a
  diagonal-offset flash prefill kernel would recover it without
  touching the program count and is the obvious next kernel.
- **step** — one batched single-token decode over all ``B_max`` rows:
  sample per row from the carried last-logits (per-row traced
  temperature / top-k / top-p — serve/sampling.py), forward through the
  model with PER-ROW cache positions (models/gpt2.py per-row pos path),
  advance active rows. On TPU the attention inside this step is the
  Pallas flash-decode kernel (ops/pallas/decode_attention.py): per-row
  ``lengths`` skip KV blocks above each row's depth, and inactive rows
  skip every block instead of computing masked garbage (host-side
  masking still applies — their state is frozen by ``where(active,
  ...)``).

All programs route through the runtime ``Executor`` (compile-cache keyed
on function identity + full arg shape signature), so the program-count
claim is enforced by the ``compile_cache.*`` obs counters: a shape drift
would show up as an extra miss, and tests pin the count at
``1 + len(prefill_buckets)`` with misses frozen after warmup (a bucket
program compiles the first time a prompt lands in its bucket).

All per-request scalars cross into the programs as 0-d ARRAYS, never
Python numbers — the executor's signature (and jax.jit's) would
otherwise key on the literal value and recompile per request.

Token-range validation lives in the scheduler's admission path
(``Scheduler.submit``), NOT here: the engine trusts its caller so the
per-prefill host work is one ``np.zeros`` + copy per chunk, and a bad
request is bounced before it ever holds a slot.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu import faults, obs
from nezha_tpu.models.generate import _caches_from_states
from nezha_tpu.runtime.executor import Executor
from nezha_tpu.serve.sampling import finite_rows, sample_tokens
from nezha_tpu.serve.slots import SlotPool, read_slot, write_slot


def default_prefill_buckets(max_prefill_len: int) -> Tuple[int, ...]:
    """Powers of two from 8 up to (and always ending exactly at)
    ``max_prefill_len`` — e.g. 32 -> (8, 16, 32), 24 -> (8, 16, 24),
    8 -> (8,). Small prompts pad to a small program instead of the full
    width, so short-prompt TTFT stops paying the long-prompt pad tax."""
    buckets: List[int] = []
    b = 8
    while b < max_prefill_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_prefill_len)
    return tuple(buckets)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Static serving shapes — everything a compiled program is keyed on.

    ``max_batch_size`` is the slot count (rows decoded per step),
    ``max_len`` the per-slot KV capacity (prompt + generated),
    ``max_prefill_len`` the widest single prefill chunk — longer prompts
    (up to ``max_len``) are prefilled in successive chunks, not
    rejected. ``prefill_buckets`` are the static prompt pad widths (one
    compiled prefill program each; ``()`` selects the powers-of-two
    default from :func:`default_prefill_buckets` — the last bucket must
    equal ``max_prefill_len``). ``k_max`` is the static top-k cap
    per-row ks are clamped to. ``queue_capacity`` bounds the scheduler's
    FIFO (backpressure); ``pad_id`` is the token fed for inactive rows.
    ``decode_impl`` (None = keep the model's own ``GPT2Config.
    decode_impl``) overrides the decode-attention choice for this
    engine: "auto" | "kernel" | "xla" — the serving-side toggle for the
    flash-decode kernel.
    """

    max_batch_size: int = 4
    max_len: int = 128
    max_prefill_len: int = 32
    prefill_buckets: Tuple[int, ...] = ()
    k_max: int = 64
    queue_capacity: int = 16
    pad_id: int = 0
    cache_dtype: Any = jnp.bfloat16
    decode_impl: Optional[str] = None

    def __post_init__(self):
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if not 1 <= self.max_prefill_len <= self.max_len:
            raise ValueError(
                f"need 1 <= max_prefill_len <= max_len, got "
                f"{self.max_prefill_len} / {self.max_len}")
        if self.k_max < 1:
            raise ValueError("k_max must be >= 1")
        if self.queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")
        if self.decode_impl not in (None, "auto", "kernel", "xla"):
            raise ValueError(
                f"decode_impl must be None, 'auto', 'kernel', or 'xla'; "
                f"got {self.decode_impl!r}")
        buckets = tuple(self.prefill_buckets) or default_prefill_buckets(
            self.max_prefill_len)
        if list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"prefill_buckets must be strictly increasing, got "
                f"{buckets}")
        if buckets[0] < 1 or buckets[-1] != self.max_prefill_len:
            # The last bucket IS the chunk width: every admissible tail
            # must fit some bucket, and chunking advances in
            # max_prefill_len strides.
            raise ValueError(
                f"prefill_buckets must be >= 1 and end exactly at "
                f"max_prefill_len={self.max_prefill_len}, got {buckets}")
        object.__setattr__(self, "prefill_buckets", buckets)


class Engine:
    """Device-side serving state + the frozen program set.

    The engine is deliberately request-blind: it knows slots, not
    requests. Admission policy, deadlines, retirement, and the
    request-level telemetry (TTFT/TPOT, queue depth, spans) live in the
    scheduler; the engine emits only what it alone can see — the
    bucket/chunk instruments (``serve.prefill.bucket_len`` /
    ``serve.prefill.chunks_total``), since the bucket choice is made
    here. The contract is ``prefill(slot, ...)`` to load one slot
    (however many chunks that takes) and ``step(active)`` to decode one
    token for every row and hand the batch back to the host.
    """

    def __init__(self, model, variables, cfg: ServeConfig = ServeConfig()):
        if cfg.max_len > model.cfg.max_positions:
            raise ValueError(
                f"max_len {cfg.max_len} exceeds the model's max_positions "
                f"{model.cfg.max_positions}")
        if (cfg.decode_impl is not None
                and cfg.decode_impl != model.cfg.decode_impl):
            # The decode-attention choice is a model-config knob (the
            # attention module reads it at trace time); honor the serving
            # override by rebuilding the module tree around a replaced
            # config — pure structure, the caller's ``variables`` slot
            # straight in.
            model = type(model)(
                dataclasses.replace(model.cfg, decode_impl=cfg.decode_impl),
                policy=model.policy)
        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.vocab = model.cfg.vocab_size
        self.k_max = min(cfg.k_max, self.vocab)
        self.pool = SlotPool(model, cfg.max_batch_size, cfg.max_len,
                             cfg.cache_dtype)
        b = cfg.max_batch_size
        self.last_logits = jnp.zeros((b, self.vocab), jnp.float32)
        # [B] bool from the latest step: False where that row's logits
        # (carried-in or freshly produced) went non-finite — the
        # scheduler's signal to retire the row with FinishReason.ERROR.
        self.step_ok: Optional[np.ndarray] = None
        self.positions = jnp.zeros((b,), jnp.int32)
        self.keys = jnp.zeros((b, 2), jnp.uint32)
        self.temps = jnp.zeros((b,), jnp.float32)
        self.top_ks = jnp.zeros((b,), jnp.int32)
        self.top_ps = jnp.ones((b,), jnp.float32)
        # Donate the pooled caches (positional arg 1 in EVERY program):
        # without donation every decoded token would copy the whole
        # [B_max, H, L_max, D] K/V pool per layer just to write one row —
        # double the KV memory and a full-pool bandwidth tax on the
        # latency-bound loop. The engine rebinds the returned buffers
        # immediately, so the invalidated inputs are never reused.
        self.executor = Executor(donate_argnums=(1,))
        # One prefill program per bucket width (compiled lazily: the
        # executor keys on the function object, so each closure is its
        # own cache entry the first time a prompt lands in its bucket).
        self._prefill_fns = {w: _build_prefill(self.model, w)
                             for w in cfg.prefill_buckets}
        self._step_fn = _build_step(self.model, self.k_max, cfg.pad_id)

    # -------------------------------------------------------- host API
    def bucket_for(self, n: int) -> int:
        """The static pad width the TAIL chunk of an ``n``-token prompt
        runs at: the smallest bucket >= n for single-chunk prompts,
        else the smallest bucket >= the chunked remainder. Benchmarks
        group TTFT by this value."""
        p_max = self.cfg.max_prefill_len
        rem = n if n <= p_max else (n % p_max or p_max)
        return next(w for w in self.cfg.prefill_buckets if w >= rem)

    def prefill(self, slot: int, tokens: Sequence[int], *, seed: int = 0,
                temperature: float = 0.0, top_k: Optional[int] = None,
                top_p: Optional[float] = None) -> None:
        """Load one request into ``slot``: prompt K/V, position, PRNG
        key, and sampling params. ``tokens`` may be up to
        ``max_len - 1`` long (room for at least one generated token);
        prompts wider than ``max_prefill_len`` run as successive chunks
        through the same bucket programs. Token ids are NOT validated
        here — admission (``Scheduler.submit``) is the validation
        boundary. The first generated token comes from the next
        :meth:`step`."""
        faults.point("serve.prefill")
        n = len(tokens)
        if not 1 <= n < self.cfg.max_len:
            raise ValueError(
                f"prompt length {n} not in [1, max_len-1="
                f"{self.cfg.max_len - 1}]")
        p_max = self.cfg.max_prefill_len
        tokens = np.asarray(tokens, np.int32)
        chunks: List[Tuple[int, int, int]] = []      # (offset, len, width)
        off = 0
        while n - off > p_max:
            chunks.append((off, p_max, p_max))
            off += p_max
        rem = n - off
        width = self.bucket_for(rem)
        if off + width > self.cfg.max_len:
            # A padded tail would spill past the slot's KV capacity
            # (max_len not a multiple of max_prefill_len, prompt near
            # capacity) — and dynamic_update_slice would CLAMP the write
            # start, corrupting the already-written prefix. Slide the
            # window back to cover the last `width` REAL tokens instead:
            # rewriting those positions recomputes identical K/V (same
            # tokens, same prefix), and no pad lands past capacity.
            # (Only reachable when chunked, where n > max_prefill_len
            # >= width, so off stays >= 0.)
            off, rem = n - width, width
        chunks.append((off, rem, width))
        obs.counter("serve.prefill.chunks_total").inc(len(chunks))
        for off, ln, width in chunks:
            obs.histogram("serve.prefill.bucket_len").observe(width)
            padded = np.zeros((1, width), np.int32)
            padded[0, :ln] = tokens[off:off + ln]
            out = self.executor.run(
                self._prefill_fns[width], self.variables, self.pool.caches,
                jnp.asarray(padded),
                np.int32(ln), np.int32(slot), np.int32(off),
                np.int32(seed), np.float32(temperature),
                np.int32(0 if top_k is None else top_k),
                np.float32(1.0 if top_p is None else top_p),
                self.last_logits, self.positions, self.keys,
                self.temps, self.top_ks, self.top_ps)
            (self.pool.caches, self.last_logits, self.positions, self.keys,
             self.temps, self.top_ks, self.top_ps) = out
        if faults.enabled():
            self.last_logits = faults.corrupt(
                "serve.prefill.logits", self.last_logits, rows=(slot,))

    def step(self, active: np.ndarray) -> np.ndarray:
        """Decode one token for every row; ``active`` is a ``[B_max]``
        bool mask. Returns the sampled tokens as a host array — entries
        for inactive rows are garbage and must be ignored. After the
        call :attr:`step_ok` holds a ``[B_max]`` bool health mask: False
        where a row's logits went non-finite (only meaningful for rows
        the caller knows are active)."""
        faults.point("serve.step")
        tok, ok, caches, last, pos, keys = self.executor.run(
            self._step_fn, self.variables, self.pool.caches,
            self.last_logits, self.positions,
            jnp.asarray(active, bool), self.keys,
            self.temps, self.top_ks, self.top_ps)
        self.pool.caches = caches
        if faults.enabled():
            last = faults.corrupt(
                "serve.step.logits", last,
                rows=lambda: np.flatnonzero(active))
        self.last_logits, self.positions, self.keys = last, pos, keys
        self.step_ok = np.asarray(ok)
        return np.asarray(tok)

    def compile_stats(self) -> dict:
        """Executor cache stats — steady state is ``entries ==
        1 + len(prefill_buckets)`` (step + one prefill per bucket),
        misses frozen there after every bucket has been warmed while
        hits grow."""
        return self.executor.stats()


def _build_prefill(model, width: int):
    def prefill(variables, caches, tokens, length, slot, pos, seed,
                temperature, top_k, top_p,
                last_logits, positions, keys, temps, top_ks, top_ps):
        # One prompt chunk, padded to this bucket's static `width`, runs
        # against the SLOT'S OWN cache rows at a traced offset: the
        # masked attention path sees the prefix earlier chunks wrote
        # (pos > 0) or nothing (pos == 0), so the same program serves
        # first chunks, middle chunks, and bucketed tails. Rows past
        # `length` are pad — their K/V lands above the prompt and is
        # overwritten by decode before any mask attends it.
        rows = [{"k": read_slot(pool["k"], slot),
                 "v": read_slot(pool["v"], slot)} for pool in caches]
        logits, states = model.apply(variables, tokens, training=False,
                                     cache=rows, pos=pos)
        new_rows = _caches_from_states(model, states, rows)
        new_caches = [
            {"k": write_slot(pool["k"], rk["k"], slot),
             "v": write_slot(pool["v"], rk["v"], slot)}
            for pool, rk in zip(caches, new_rows)]
        row = lax.dynamic_slice(
            logits, (0, length - 1, jnp.zeros((), jnp.int32)),
            (1, 1, logits.shape[-1]))[:, 0, :]          # [1, V] last REAL row
        key = jax.random.PRNGKey(seed).astype(keys.dtype)

        def set_row(buf, val):
            return lax.dynamic_update_slice(
                buf, jnp.asarray(val, buf.dtype).reshape(
                    (1,) + buf.shape[1:]),
                (slot,) + (jnp.zeros((), jnp.int32),) * (buf.ndim - 1))

        # Every chunk overwrites the whole per-slot state; only the final
        # chunk's values survive to decode (positions advances to the
        # running prefix length either way).
        return (new_caches,
                set_row(last_logits, row),
                set_row(positions, pos + length),
                set_row(keys, key),
                set_row(temps, temperature),
                set_row(top_ks, top_k),
                set_row(top_ps, top_p))

    return prefill


def _build_step(model, k_max: int, pad_id: int):
    def step(variables, caches, last_logits, positions, active, keys,
             temps, top_ks, top_ps):
        # Row health, checked in-program (no extra host round-trip): the
        # carried-in logits catch a burst that landed BETWEEN steps (the
        # sampled token below is then garbage and the scheduler discards
        # it), the fresh row catches one the forward pass itself
        # produced. Either way the scheduler retires the row with
        # FinishReason.ERROR while its neighbors keep decoding.
        in_ok = finite_rows(last_logits)
        # One key split per row per step: a request's RNG stream depends
        # only on its seed and step count, never on its batch neighbors.
        splits = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        next_keys, subs = splits[:, 0], splits[:, 1]
        tok = sample_tokens(last_logits, subs, temps, top_ks, top_ps,
                            k_max)
        tok = jnp.where(active, tok, pad_id)
        # `active` rides into the model so the flash-decode kernel can
        # zero inactive rows' lengths and skip their KV blocks entirely;
        # the composed fallback ignores it (garbage rows masked below).
        logits, states = model.apply(variables, tok[:, None],
                                     training=False, cache=caches,
                                     pos=positions, active=active)
        new_caches = _caches_from_states(model, states, caches)
        row_logits = logits[:, -1, :]
        act = active[:, None]
        return (tok,
                in_ok & finite_rows(row_logits),
                new_caches,
                jnp.where(act, row_logits, last_logits),
                jnp.where(active, positions + 1, positions),
                jnp.where(act, next_keys, keys))

    return step
