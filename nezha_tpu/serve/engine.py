"""The continuous-batching engine: two compiled programs, reused forever.

Steady-state serving is exactly TWO XLA programs regardless of request
mix — the property that keeps TPU serving latency flat:

- **prefill** — one request's prompt (padded to the static
  ``max_prefill_len``) runs through the model against a scratch cache,
  and its K/V rows, position, PRNG key, and sampling params are written
  into one SLOT of the pooled batch state via ``dynamic_update_slice``.
  Pad positions beyond the prompt write garbage K/V that is never
  attended (the decode mask stops at ``pos``, and every position below
  ``pos`` is rewritten by a decode step before the mask reaches it).
- **step** — one batched single-token decode over all ``B_max`` rows:
  sample per row from the carried last-logits (per-row traced
  temperature / top-k / top-p — serve/sampling.py), forward through the
  model with PER-ROW cache positions (models/gpt2.py per-row pos path),
  advance active rows. Inactive rows compute garbage that is masked out
  host-side; their state is frozen by ``where(active, ...)``.

Both programs route through the runtime ``Executor`` (compile-cache
keyed on function identity + full arg shape signature), so the
two-program claim is enforced by the ``compile_cache.*`` obs counters:
a shape drift would show up as a third miss, and tests pin it.

All per-request scalars cross into the programs as 0-d ARRAYS, never
Python numbers — the executor's signature (and jax.jit's) would
otherwise key on the literal value and recompile per request.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu.models.generate import _caches_from_states
from nezha_tpu.runtime.executor import Executor
from nezha_tpu.serve.sampling import sample_tokens
from nezha_tpu.serve.slots import SlotPool, write_slot


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Static serving shapes — everything a compiled program is keyed on.

    ``max_batch_size`` is the slot count (rows decoded per step),
    ``max_len`` the per-slot KV capacity (prompt + generated),
    ``max_prefill_len`` the static prompt pad width (prompts longer than
    this are rejected at admission), ``k_max`` the static top-k cap
    per-row ks are clamped to. ``queue_capacity`` bounds the scheduler's
    FIFO (backpressure); ``pad_id`` is the token fed for inactive rows.
    """

    max_batch_size: int = 4
    max_len: int = 128
    max_prefill_len: int = 32
    k_max: int = 64
    queue_capacity: int = 16
    pad_id: int = 0
    cache_dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if not 1 <= self.max_prefill_len <= self.max_len:
            raise ValueError(
                f"need 1 <= max_prefill_len <= max_len, got "
                f"{self.max_prefill_len} / {self.max_len}")
        if self.k_max < 1:
            raise ValueError("k_max must be >= 1")
        if self.queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")


class Engine:
    """Device-side serving state + the two compiled programs.

    The engine is deliberately request-blind: it knows slots, not
    requests. Admission policy, deadlines, retirement, and telemetry
    live in the scheduler; the engine's contract is ``prefill(slot, ...)``
    to load one slot and ``step(active)`` to decode one token for every
    row and hand the batch back to the host.
    """

    def __init__(self, model, variables, cfg: ServeConfig = ServeConfig()):
        if cfg.max_len > model.cfg.max_positions:
            raise ValueError(
                f"max_len {cfg.max_len} exceeds the model's max_positions "
                f"{model.cfg.max_positions}")
        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.vocab = model.cfg.vocab_size
        self.k_max = min(cfg.k_max, self.vocab)
        self.pool = SlotPool(model, cfg.max_batch_size, cfg.max_len,
                             cfg.cache_dtype)
        b = cfg.max_batch_size
        self.last_logits = jnp.zeros((b, self.vocab), jnp.float32)
        self.positions = jnp.zeros((b,), jnp.int32)
        self.keys = jnp.zeros((b, 2), jnp.uint32)
        self.temps = jnp.zeros((b,), jnp.float32)
        self.top_ks = jnp.zeros((b,), jnp.int32)
        self.top_ps = jnp.ones((b,), jnp.float32)
        # Donate the pooled caches (positional arg 1 in BOTH programs):
        # without donation every decoded token would copy the whole
        # [B_max, H, L_max, D] K/V pool per layer just to write one row —
        # double the KV memory and a full-pool bandwidth tax on the
        # latency-bound loop. The engine rebinds the returned buffers
        # immediately, so the invalidated inputs are never reused.
        self.executor = Executor(donate_argnums=(1,))
        self._prefill_fn = _build_prefill(model, cfg)
        self._step_fn = _build_step(model, self.k_max, cfg.pad_id)

    # -------------------------------------------------------- host API
    def prefill(self, slot: int, tokens: Sequence[int], *, seed: int = 0,
                temperature: float = 0.0, top_k: Optional[int] = None,
                top_p: Optional[float] = None) -> None:
        """Load one request into ``slot``: prompt K/V, position, PRNG
        key, and sampling params. ``tokens`` must fit
        ``max_prefill_len``; the first generated token comes from the
        next :meth:`step`."""
        n = len(tokens)
        p_max = self.cfg.max_prefill_len
        if not 1 <= n <= p_max:
            raise ValueError(
                f"prompt length {n} not in [1, max_prefill_len={p_max}]")
        padded = np.zeros((1, p_max), np.int32)
        padded[0, :n] = np.asarray(tokens, np.int32)
        if padded.max() >= self.vocab or padded.min() < 0:
            raise ValueError(f"prompt ids must be in [0, {self.vocab})")
        out = self.executor.run(
            self._prefill_fn, self.variables, self.pool.caches,
            jnp.asarray(padded),
            np.int32(n), np.int32(slot), np.int32(seed),
            np.float32(temperature),
            np.int32(0 if top_k is None else top_k),
            np.float32(1.0 if top_p is None else top_p),
            self.last_logits, self.positions, self.keys,
            self.temps, self.top_ks, self.top_ps)
        (self.pool.caches, self.last_logits, self.positions, self.keys,
         self.temps, self.top_ks, self.top_ps) = out

    def step(self, active: np.ndarray) -> np.ndarray:
        """Decode one token for every row; ``active`` is a ``[B_max]``
        bool mask. Returns the sampled tokens as a host array — entries
        for inactive rows are garbage and must be ignored."""
        tok, caches, last, pos, keys = self.executor.run(
            self._step_fn, self.variables, self.pool.caches,
            self.last_logits, self.positions,
            jnp.asarray(active, bool), self.keys,
            self.temps, self.top_ks, self.top_ps)
        self.pool.caches = caches
        self.last_logits, self.positions, self.keys = last, pos, keys
        return np.asarray(tok)

    def compile_stats(self) -> dict:
        """Executor cache stats — steady state is ``entries == 2``
        (prefill + step), misses frozen at 2 while hits grow."""
        return self.executor.stats()


def _scratch_cache(model, p_max: int, dtype) -> List[dict]:
    cfg = model.cfg
    d = cfg.hidden_size // cfg.num_heads
    shape = (1, cfg.num_heads, p_max, d)
    return [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
            for _ in range(cfg.num_layers)]


def _build_prefill(model, cfg: ServeConfig):
    p_max = cfg.max_prefill_len

    def prefill(variables, caches, tokens, length, slot, seed,
                temperature, top_k, top_p,
                last_logits, positions, keys, temps, top_ks, top_ps):
        # The prompt runs against a scratch cache at STATIC pos=0 (the
        # flash-prefill fast path on TPU), then its K/V rows land in the
        # pooled slot. tokens is [1, p_max]; rows past `length` are pad.
        scratch = _scratch_cache(model, p_max, caches[0]["k"].dtype)
        logits, states = model.apply(variables, tokens, training=False,
                                     cache=scratch, pos=0, prefill=True)
        chunk = _caches_from_states(model, states, scratch)
        new_caches = [
            {"k": write_slot(pool["k"], ck["k"], slot),
             "v": write_slot(pool["v"], ck["v"], slot)}
            for pool, ck in zip(caches, chunk)]
        row = lax.dynamic_slice(
            logits, (0, length - 1, jnp.zeros((), jnp.int32)),
            (1, 1, logits.shape[-1]))[:, 0, :]          # [1, V] last REAL row
        key = jax.random.PRNGKey(seed).astype(keys.dtype)

        def set_row(buf, val):
            return lax.dynamic_update_slice(
                buf, jnp.asarray(val, buf.dtype).reshape(
                    (1,) + buf.shape[1:]),
                (slot,) + (jnp.zeros((), jnp.int32),) * (buf.ndim - 1))

        return (new_caches,
                set_row(last_logits, row),
                set_row(positions, length),
                set_row(keys, key),
                set_row(temps, temperature),
                set_row(top_ks, top_k),
                set_row(top_ps, top_p))

    return prefill


def _build_step(model, k_max: int, pad_id: int):
    def step(variables, caches, last_logits, positions, active, keys,
             temps, top_ks, top_ps):
        # One key split per row per step: a request's RNG stream depends
        # only on its seed and step count, never on its batch neighbors.
        splits = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        next_keys, subs = splits[:, 0], splits[:, 1]
        tok = sample_tokens(last_logits, subs, temps, top_ks, top_ps,
                            k_max)
        tok = jnp.where(active, tok, pad_id)
        logits, states = model.apply(variables, tok[:, None],
                                     training=False, cache=caches,
                                     pos=positions)
        new_caches = _caches_from_states(model, states, caches)
        row_logits = logits[:, -1, :]
        act = active[:, None]
        return (tok,
                new_caches,
                jnp.where(act, row_logits, last_logits),
                jnp.where(active, positions + 1, positions),
                jnp.where(act, next_keys, keys))

    return step
